"""graftlint: the AST invariant linter + runtime lock-order detector.

Covers: (1) every static rule demonstrates a true-positive, a clean
pass, and a pragma suppression against its checked-in fixture trio
(tests/fixtures/graftlint/); (2) pragma parsing (reasons required for
daemon-ok, multi-line reasons, statement-span application); (3) the
baseline mechanism; (4) the runtime lock-order recorder: a synthetic
A→B / B→A cycle MUST be caught, a consistent order must not, and
instrumented locks keep full Lock/Condition semantics; (5) the real
tree: an in-process static run reports ZERO non-baseline findings, and
the full `python -m tools.lint --all` gate (static + fresh-process
lock-order scenario over one compiled train step + one decode batch +
one preemption drain) exits 0 and lands its JSON report in
benchmark/artifacts/ — the suite-level wiring of docs/STATIC_ANALYSIS.md.
"""
import json
import os
import shutil
import subprocess
import sys
import threading

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lint import RULES, load_baseline, run_static  # noqa: E402
from tools.lint import runtime as lint_runtime  # noqa: E402
from tools.lint.core import Finding  # noqa: E402

FIXTURES = os.path.join(REPO, "tests", "fixtures", "graftlint")

# rule -> (fixture stem, filename the fixture must land under in the
# tmp package — host-sync only watches the declared hot-path modules)
RULE_FIXTURES = {
    "env-discipline": ("env", "fixture_mod.py"),
    "thread-discipline": ("thread", "fixture_mod.py"),
    "host-sync": ("hostsync", "cached_step.py"),
    "fault-site": ("faultsite", "fixture_mod.py"),
    "counter-discipline": ("counter", "fixture_mod.py"),
    "donation": ("donation", "fixture_mod.py"),
}


def _mini_tree(tmp_path, rule, variant):
    """tmp repo: mxnet_tpu/<target> from the fixture + docs/tests stubs
    (the fault-site rule cross-checks both)."""
    stem, target = RULE_FIXTURES[rule]
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir(exist_ok=True)
    shutil.copy(os.path.join(FIXTURES, f"{stem}_{variant}.py"),
                str(pkg / target))
    docs = tmp_path / "docs"
    docs.mkdir(exist_ok=True)
    (docs / "ROBUSTNESS.md").write_text(
        "| Site | Where | Recovery |\n|---|---|---|\n"
        "| `fixture.documented` | fixture | retried |\n")
    tests = tmp_path / "tests"
    tests.mkdir(exist_ok=True)
    (tests / "test_fixture.py").write_text(
        'PLAN = "fixture.documented"\n')
    return str(tmp_path)


@pytest.mark.parametrize("rule", sorted(RULE_FIXTURES))
def test_rule_true_positive(rule, tmp_path):
    root = _mini_tree(tmp_path, rule, "violation")
    findings, _ = run_static(root, only={rule})
    assert findings, f"{rule}: violation fixture produced no finding"
    assert all(f.rule == rule for f in findings)
    expected = {"env-discipline": 3, "host-sync": 4, "fault-site": 2,
                "counter-discipline": 3, "donation": 2,
                "thread-discipline": 1}[rule]
    assert len(findings) == expected, [str(f) for f in findings]


@pytest.mark.parametrize("rule", sorted(RULE_FIXTURES))
def test_rule_clean(rule, tmp_path):
    root = _mini_tree(tmp_path, rule, "clean")
    findings, _ = run_static(root, only={rule})
    assert findings == [], [str(f) for f in findings]


@pytest.mark.parametrize("rule", sorted(RULE_FIXTURES))
def test_rule_pragma_suppressed(rule, tmp_path):
    root = _mini_tree(tmp_path, rule, "pragma")
    findings, ctx = run_static(root, only={rule})
    assert findings == [], [str(f) for f in findings]
    assert ctx.suppressed >= 1, \
        f"{rule}: pragma suppression was not counted"


def test_daemon_ok_requires_reason(tmp_path):
    """An empty daemon-ok() justifies nothing — the finding stands."""
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    (pkg / "m.py").write_text(
        "import threading\n\n"
        "def go():\n"
        "    # graftlint: daemon-ok()\n"
        "    t = threading.Thread(target=print)\n"
        "    t.start()\n")
    findings, _ = run_static(str(tmp_path), only={"thread-discipline"})
    assert len(findings) == 1


def test_parse_error_is_a_finding(tmp_path):
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    (pkg / "broken.py").write_text("def oops(:\n")
    findings, _ = run_static(str(tmp_path), only={"env-discipline"})
    assert any(f.rule == "parse-error" for f in findings)


def test_baseline_filters_known_findings(tmp_path):
    root = _mini_tree(tmp_path, "env-discipline", "violation")
    findings, _ = run_static(root, only={"env-discipline"})
    baseline = {f.key for f in findings}
    live = [f for f in findings if f.key not in baseline]
    assert live == []
    # the key is line-free: a Finding at another line matches the same
    # baseline entry
    f = findings[0]
    moved = Finding(f.rule, f.path, f.line + 40, 0, f.message)
    assert moved.key in baseline


def test_list_rules_names_all_six():
    assert set(RULE_FIXTURES) <= set(RULES)
    for r in RULES.values():
        assert r.doc, f"rule {r.name} has no doc"


# ---------------------------------------------------------------------------
# runtime lock-order recorder
# ---------------------------------------------------------------------------

def test_lock_cycle_synthetic():
    """The canonical inversion: thread 1 takes A then B, thread 2 takes
    B then A.  No deadlock ever happens (the threads run sequentially)
    — the ORDER graph still carries the cycle, which is the point:
    deterministic detection without the unlucky interleaving."""
    rec = lint_runtime.enable()
    try:
        a = threading.Lock()
        b = threading.Lock()

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        for fn in (t1, t2):
            th = threading.Thread(target=fn)
            th.start()
            th.join()
    finally:
        lint_runtime.disable()
    cycles = rec.cycles()
    assert len(cycles) == 1, rec.report()
    assert len(cycles[0]) == 2
    assert all("test_graftlint.py" in site for site in cycles[0])


def test_lock_consistent_order_no_cycle():
    rec = lint_runtime.enable()
    try:
        a = threading.Lock()
        b = threading.Lock()

        def t(n):
            for _ in range(n):
                with a:
                    with b:
                        pass

        for _ in range(2):
            th = threading.Thread(target=t, args=(3,))
            th.start()
            th.join()
    finally:
        lint_runtime.disable()
    assert rec.cycles() == []
    assert rec.acquisitions >= 12


def test_instrumented_locks_keep_semantics():
    """Wrapped locks must behave as locks: context manager, Condition
    protocol (incl. RLock delegation), locked(), and survival after
    disable()."""
    rec = lint_runtime.enable()
    try:
        lock = threading.Lock()
        with lock:
            assert lock.locked()
        assert not lock.locked()
        cv = threading.Condition(threading.RLock())
        hit = []

        def waiter():
            with cv:
                cv.wait(timeout=5)
                hit.append(1)

        th = threading.Thread(target=waiter)
        th.start()
        import time

        time.sleep(0.05)
        with cv:
            cv.notify_all()
        th.join(timeout=5)
        assert hit == [1]
    finally:
        lint_runtime.disable()
    # post-disable: the same wrapper objects still function
    with lock:
        assert lock.locked()
    assert rec.acquisitions > 0 and not rec.active


def test_instance_level_edges_no_false_cycle():
    """Two lock INSTANCES from one creation site, nested both ways
    across threads, are NOT a cycle (per-instance ordered locks are a
    legal pattern); the graph is instance-keyed exactly for this."""
    rec = lint_runtime.enable()
    try:
        locks = [threading.Lock() for _ in range(2)]   # one site

        def t(first, second):
            with locks[first]:
                with locks[second]:
                    pass

        th = threading.Thread(target=t, args=(0, 1))
        th.start()
        th.join()
        # same ordered pair again — never the reverse
        th = threading.Thread(target=t, args=(0, 1))
        th.start()
        th.join()
    finally:
        lint_runtime.disable()
    assert rec.cycles() == []


# ---------------------------------------------------------------------------
# the real tree
# ---------------------------------------------------------------------------

def test_real_tree_static_zero_findings():
    """mxnet_tpu/ lints clean with an EMPTY baseline — every
    grandfathered finding was fixed or pragma'd with a reason."""
    findings, ctx = run_static(REPO)
    baseline = load_baseline()
    assert baseline == set(), \
        "baseline must stay empty (docs/STATIC_ANALYSIS.md policy)"
    live = [str(f) for f in findings]
    assert live == [], "\n".join(live)
    assert len(ctx.sources) > 100          # the walk actually walked
    assert ctx.suppressed > 0              # pragmas are in play


@pytest.mark.slow  # ISSUE-18 wall: subprocess gate; test_real_tree_static_zero_findings stays tier-1
def test_full_gate_subprocess_and_artifact():
    """`python -m tools.lint --all`: static rules + the fresh-process
    lock-order scenario (compiled train step + decode batch + preemption
    drain) exit 0, the acquisition graph is acyclic, and the JSON report
    lands in benchmark/artifacts/ for bench rounds to diff."""
    artifact = os.path.join(REPO, "benchmark", "artifacts",
                            "graftlint.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONPATH", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--all", "--json", artifact],
        capture_output=True, text=True, timeout=540, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    with open(artifact) as f:
        report = json.load(f)
    assert report["static"]["findings"] == []
    rt = report["runtime"]
    assert not rt.get("error"), rt
    assert rt["cycles"] == []
    assert rt["locks"] > 10 and rt["acquisitions"] > 50
    # the scenario really ran its three legs
    assert rt["scenario"]["train_steps"] == 3
    assert rt["scenario"]["drain_exit_code"] == 83
    # framework locks are in the observed graph, not just jax internals
    sites = {e["held"] for e in rt["edges"]} \
        | {e["acquired"] for e in rt["edges"]}
    assert any(s.startswith("mxnet_tpu/") for s in sites), sites
