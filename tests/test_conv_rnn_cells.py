"""Conv-RNN cell family (reference python/mxnet/gluon/rnn/conv_rnn_cell.py,
tests mirror tests/python/unittest/test_gluon_rnn.py's conv-cell block).

Oracles:
- shape contract: hidden spatial size = i2h conv output size; h2h conv
  preserves it for every pad/dilate combination;
- degenerate equivalence: with 1x1 kernels on 1x1 spatial input a conv
  cell IS the dense cell — same weights must give identical outputs
  (gate order and gate math are pinned by the dense cells' own
  manual-unroll tests);
- unroll + autograd integration.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import rnn


CELLS = {
    1: (rnn.Conv1DRNNCell, rnn.Conv1DLSTMCell, rnn.Conv1DGRUCell),
    2: (rnn.Conv2DRNNCell, rnn.Conv2DLSTMCell, rnn.Conv2DGRUCell),
    3: (rnn.Conv3DRNNCell, rnn.Conv3DLSTMCell, rnn.Conv3DGRUCell),
}
GATES = {"RNN": 1, "LSTM": 4, "GRU": 3}


def _kind(cell_cls):
    for k in GATES:
        if k in cell_cls.__name__:
            return k
    raise AssertionError(cell_cls)


@pytest.mark.parametrize("dims", [1, 2, 3])
@pytest.mark.parametrize("idx", [0, 1, 2])
def test_forward_shapes(dims, idx):
    cell_cls = CELLS[dims][idx]
    spatial = (8, 7, 6)[:dims]
    input_shape = (3,) + spatial
    cell = cell_cls(input_shape, hidden_channels=4, i2h_kernel=3,
                    h2h_kernel=3, i2h_pad=1)
    cell.initialize(mx.init.Xavier())
    x = nd.random.normal(shape=(2,) + input_shape)
    states = cell.begin_state(2)
    out, new_states = cell(x, states)
    assert out.shape == (2, 4) + spatial
    info = cell.state_info(2)
    assert len(new_states) == (2 if idx == 1 else 1)
    for s, i in zip(new_states, info):
        assert s.shape == tuple(i["shape"])
        assert i["__layout__"] == cell._conv_layout


def test_i2h_shrinks_state_no_pad():
    """Without i2h padding the state spatial size is the conv output size
    (reference _decide_shapes/_get_conv_out_size)."""
    cell = rnn.Conv2DRNNCell((3, 10, 9), hidden_channels=2, i2h_kernel=3,
                             h2h_kernel=5)
    cell.initialize(mx.init.Xavier())
    x = nd.random.normal(shape=(2, 3, 10, 9))
    out, _ = cell(x, cell.begin_state(2))
    assert out.shape == (2, 2, 8, 7)
    # dilated i2h
    cell2 = rnn.Conv2DLSTMCell((3, 10, 9), hidden_channels=2, i2h_kernel=3,
                               h2h_kernel=3, i2h_dilate=2)
    cell2.initialize(mx.init.Xavier())
    out2, _ = cell2(x, cell2.begin_state(2))
    assert out2.shape == (2, 2, 6, 5)


def test_even_h2h_kernel_rejected():
    with pytest.raises(ValueError, match="odd"):
        rnn.Conv2DRNNCell((3, 8, 8), 4, i2h_kernel=3, h2h_kernel=2)


@pytest.mark.parametrize("kind", ["RNN", "LSTM", "GRU"])
def test_degenerate_1x1_equals_dense_cell(kind):
    """Conv cell with 1x1 kernels on 1x1 spatial input == dense cell."""
    rs = onp.random.RandomState(0)
    B, C, H = 3, 5, 4
    conv_cls = {"RNN": rnn.Conv1DRNNCell, "LSTM": rnn.Conv1DLSTMCell,
                "GRU": rnn.Conv1DGRUCell}[kind]
    dense_cls = {"RNN": rnn.RNNCell, "LSTM": rnn.LSTMCell,
                 "GRU": rnn.GRUCell}[kind]
    conv = conv_cls((C, 1), hidden_channels=H, i2h_kernel=1, h2h_kernel=1)
    dense = (dense_cls(H, input_size=C) if kind != "RNN"
             else dense_cls(H, activation="tanh", input_size=C))
    conv.initialize(mx.init.Xavier())
    dense.initialize(mx.init.Xavier())
    x2d = rs.randn(B, C).astype(onp.float32)
    dense(nd.array(x2d), dense.begin_state(B))  # materialize shapes
    ng = H * GATES[kind]
    wi = rs.randn(ng, C).astype(onp.float32)
    wh = rs.randn(ng, H).astype(onp.float32)
    bi = rs.randn(ng).astype(onp.float32)
    bh = rs.randn(ng).astype(onp.float32)
    for cell, reshape in ((conv, True), (dense, False)):
        p = {name.split(".")[-1]: param
             for name, param in cell.collect_params().items()}
        p["i2h_weight"]._data[0]._set_data(
            nd.array(wi.reshape(ng, C, 1) if reshape else wi)._data)
        p["h2h_weight"]._data[0]._set_data(
            nd.array(wh.reshape(ng, H, 1) if reshape else wh)._data)
        p["i2h_bias"]._data[0]._set_data(nd.array(bi)._data)
        p["h2h_bias"]._data[0]._set_data(nd.array(bh)._data)

    states_c = conv.begin_state(B)
    states_d = dense.begin_state(B)
    xc = nd.array(x2d.reshape(B, C, 1))
    xd = nd.array(x2d)
    for _ in range(3):  # a few chained steps compound any gate-math error
        out_c, states_c = conv(xc, states_c)
        out_d, states_d = dense(xd, states_d)
        onp.testing.assert_allclose(
            out_c.asnumpy().reshape(B, H), out_d.asnumpy(),
            rtol=1e-5, atol=1e-6)
    for sc, sd in zip(states_c, states_d):
        onp.testing.assert_allclose(sc.asnumpy().reshape(B, H),
                                    sd.asnumpy(), rtol=1e-5, atol=1e-6)


def test_unroll_and_gradients():
    cell = rnn.Conv2DLSTMCell((2, 6, 6), hidden_channels=3, i2h_kernel=3,
                              h2h_kernel=3, i2h_pad=1)
    cell.initialize(mx.init.Xavier())
    seq = nd.random.normal(shape=(2, 4, 2, 6, 6))  # NTC...
    with autograd.record():
        outs, states = cell.unroll(4, seq, layout="NTC",
                                   merge_outputs=True)
        loss = (outs * outs).mean()
    loss.backward()
    g = cell.i2h_weight.grad()
    assert g.shape == cell.i2h_weight.shape
    assert float(nd.abs(g).sum().asscalar()) > 0
    assert outs.shape == (2, 4, 3, 6, 6)


def test_conv_gru_residual_zoneout_compose():
    """Conv cells compose with modifier cells like dense ones."""
    base = rnn.Conv2DGRUCell((3, 5, 5), hidden_channels=3, i2h_kernel=3,
                             h2h_kernel=3, i2h_pad=1)
    cell = rnn.ZoneoutCell(base, zoneout_states=0.1)
    base.initialize(mx.init.Xavier())
    x = nd.random.normal(shape=(2, 3, 5, 5))
    out, states = cell(x, cell.begin_state(2))
    assert out.shape == (2, 3, 5, 5)
