"""Async pipeline engine (PR 5 tentpole: engine.py ThreadedEngine analog).

Covers the acceptance contract: (1) depth-k device prefetch preserves
source order — never reordered, dropped, or double-applied — including
under an injected DataLoader worker crash and a transient
``engine.prefetch`` transfer fault; (2) the deferred AMP gate
(MXNET_AMP_LAG=1) is bit-exact vs the synchronous gate — params AND
optimizer state — including an injected-overflow step and the rollback
across the lag window; (3) device-side metric accumulators match host
accumulation with the host read deferred to .get()/waitall()/every
MXNET_METRIC_SYNC_STEPS, and host-path fallbacks count LOUDLY in
metric.host_sync_count; (4) async checkpointing snapshots copy-on-write
(donated buffers never read mid-overwrite) under the ``checkpoint.async``
fault site; (5) engine.waitall() drains every stage and
MXNET_ENGINE_TYPE=NaiveEngine forces fully synchronous execution.
"""
import threading
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import amp, cached_step, engine, faults, gluon, metric
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
from mxnet_tpu.ndarray import ndarray as _ndmod


# ---------------------------------------------------------------------------
# shared fixtures
# ---------------------------------------------------------------------------

def _mlp(seed=0):
    class Net(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.d1 = nn.Dense(16, in_units=8, activation="relu")
            self.d2 = nn.Dense(4, in_units=16)

        def forward(self, x):
            return self.d2(self.d1(x))

    net = Net()
    net.initialize(mx.init.Xavier())
    rng = onp.random.RandomState(seed)
    for _name, p in sorted(net.collect_params().items()):
        p.data()._set_data(mx.nd.array(rng.randn(*p.shape) * 0.1)._data)
    net.hybridize()
    return net


def _loss_fn(net, x, y):
    return ((net(x) - y) ** 2).mean()


def _batches(n, seed=3, overflow_at=()):
    """n (x, y) batches; steps listed in ``overflow_at`` get a target so
    large the fp32 squared error overflows to inf — the injected-overflow
    step the AMP gate must skip."""
    rng = onp.random.RandomState(seed)
    out = []
    for i in range(n):
        x = rng.randn(6, 8).astype(onp.float32)
        y = rng.randn(6, 4).astype(onp.float32)
        if i in overflow_at:
            # 3e38 is finite in fp32, but the scaled residual gradient
            # 2*(pred-y)*scale/batch overflows to inf -> all-finite False
            y = onp.full_like(y, 3e38)
        out.append((x, y))
    return out


# ---------------------------------------------------------------------------
# (1) device prefetch: ordering, faults, NaiveEngine
# ---------------------------------------------------------------------------

def test_prefetcher_preserves_order_no_drop_no_dup():
    batches = [onp.full((4,), i, onp.float32) for i in range(20)]
    pf = engine.DevicePrefetcher(iter(batches), depth=3)
    got = [b.asnumpy() for b in pf]
    assert len(got) == 20
    for i, b in enumerate(got):
        onp.testing.assert_array_equal(b, batches[i])
    s = pf.stats()
    assert s["staged"] == 20 and s["consumed"] == 20


def test_prefetcher_runs_ahead_of_slow_consumer():
    batches = [onp.full((4,), i, onp.float32) for i in range(10)]
    pf = engine.DevicePrefetcher(iter(batches), depth=3)
    time.sleep(0.2)                     # transfer thread fills the FIFO
    got = []
    for b in pf:
        got.append(b.asnumpy())
        time.sleep(0.01)                # "step" time: stage N+1 overlaps
    assert len(got) == 10
    s = pf.stats()
    assert s["max_ahead"] >= 2, s       # the acceptance bar: depth >= 2
    assert s["steady_ahead"] >= 2, s


def test_prefetch_transient_transfer_fault_retries_in_order():
    batches = [onp.full((2,), i, onp.float32) for i in range(8)]
    with faults.active(faults.FaultPlan().fail("engine.prefetch", times=2)):
        pf = engine.DevicePrefetcher(iter(batches), depth=2)
        got = [b.asnumpy() for b in pf]
    assert len(got) == 8
    for i, b in enumerate(got):
        onp.testing.assert_array_equal(b, batches[i])
    evs = faults.events("engine.prefetch")
    assert any(e["action"] == "retry" for e in evs)     # recovery path ran


def test_prefetch_source_error_delivered_in_order():
    def source():
        for i in range(3):
            yield onp.full((2,), i, onp.float32)
        raise RuntimeError("source died")

    pf = engine.DevicePrefetcher(source(), depth=2)
    got = []
    with pytest.raises(RuntimeError, match="source died"):
        for b in pf:
            got.append(b.asnumpy())
    # every batch produced before the error arrived, in order, first
    assert len(got) == 3
    for i, b in enumerate(got):
        onp.testing.assert_array_equal(b, onp.full((2,), i, onp.float32))


def test_dataloader_device_prefetch_ordering_under_worker_crash():
    """The ISSUE's ordering bar: an injected DataLoader worker crash in
    a device-prefetched epoch never reorders, drops, or double-applies a
    batch (the worker retry is invisible to the consumer)."""
    data = onp.arange(48, dtype=onp.float32).reshape(12, 4)
    ds = ArrayDataset(data)
    baseline = [b.asnumpy() for b in DataLoader(ds, batch_size=4)]
    loader = DataLoader(ds, batch_size=4, num_workers=2, thread_pool=True,
                        timeout=30, device_prefetch=True)
    with faults.active(faults.FaultPlan().fail("dataloader.worker")):
        got = [b.asnumpy() for b in loader]
    assert len(got) == len(baseline)
    for a, b in zip(got, baseline):
        onp.testing.assert_array_equal(a, b)


def test_dataloader_device_prefetch_parity_with_sync_path():
    data = onp.arange(44, dtype=onp.float32).reshape(11, 4)
    ds = ArrayDataset(data)
    sync_batches = [b.asnumpy()
                    for b in DataLoader(ds, batch_size=4, last_batch="pad")]
    loader = DataLoader(ds, batch_size=4, last_batch="pad",
                        device_prefetch=True)
    pre_batches = []
    valids = []
    for b in loader:
        pre_batches.append(b.asnumpy())
        valids.append(loader.last_batch_valid)
    assert len(pre_batches) == len(sync_batches)
    for a, b in zip(pre_batches, sync_batches):
        onp.testing.assert_array_equal(a, b)
    assert valids[-1] == 3              # pad contract rides the queue


def test_naive_engine_forces_fully_synchronous(monkeypatch):
    monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")
    assert engine.is_naive()
    assert engine.prefetch_depth() == 0
    assert engine.amp_lag() == 0
    # prefetch degrades to an inline generator — no transfer thread
    out = engine.prefetch(iter([onp.ones(2, onp.float32)]))
    assert not isinstance(out, engine.DevicePrefetcher)
    assert [b.asnumpy().tolist() for b in out] == [[1.0, 1.0]]
    # metrics accumulate on host (counted loudly)
    m = metric.Accuracy()
    assert not m._device_ok()
    before = metric.host_sync_count()
    m.update([mx.nd.array([1, 0])], [mx.nd.array([[0.1, 0.9], [0.9, 0.1]])])
    assert metric.host_sync_count() > before
    assert m._dev_pending == 0


# ---------------------------------------------------------------------------
# (2) deferred AMP gate: bit-exact parity + rollback
# ---------------------------------------------------------------------------

def _train(lag, overflow_at=(), steps=6, scale_window=3):
    net = _mlp()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    trainer._amp_loss_scaler = amp.LossScaler(init_scale=8.0,
                                              scale_window=scale_window)
    step = trainer.compile_step(net, _loss_fn)
    for x, y in _batches(steps, overflow_at=overflow_at):
        step(mx.nd.array(x), mx.nd.array(y), batch_size=6)
    assert step.last_step_compiled, step.last_fallback_reason
    engine.waitall()                    # land the trailing deferred flag
    return net, trainer


@pytest.mark.parametrize("overflow_at", [(), (2,), (0, 3)])
def test_deferred_gate_bit_exact_vs_synchronous(monkeypatch, overflow_at):
    """MXNET_AMP_LAG=1 (read step N-1's flag while dispatching step N)
    ends bit-identical to the synchronous gate: params, optimizer state,
    and loss scale — including injected-overflow steps whose update must
    be skipped, and a scale_window small enough that the scale GROWS
    mid-run (both speculation branches exercised)."""
    monkeypatch.setenv("MXNET_AMP_LAG", "0")
    net_s, tr_s = _train(0, overflow_at)
    monkeypatch.setenv("MXNET_AMP_LAG", "1")
    net_d, tr_d = _train(1, overflow_at)

    ps, pd = net_s.collect_params(), net_d.collect_params()
    for k in ps:
        assert onp.array_equal(ps[k].data().asnumpy(),
                               pd[k].data().asnumpy()), k
    ss = tr_s._updaters[0].states
    sd = tr_d._updaters[0].states
    assert set(ss) == set(sd)
    for idx in ss:
        a, b = ss[idx], sd[idx]
        if a is None:
            assert b is None
            continue
        for ai, bi in zip(a if isinstance(a, (list, tuple)) else [a],
                          b if isinstance(b, (list, tuple)) else [b]):
            assert onp.array_equal(ai.asnumpy(), bi.asnumpy()), f"state {idx}"
    assert tr_s._amp_loss_scaler.loss_scale == tr_d._amp_loss_scaler.loss_scale
    assert tr_s._amp_loss_scaler._unskipped == tr_d._amp_loss_scaler._unskipped


def test_deferred_gate_rollback_across_lag_window(monkeypatch):
    """An overflow on the FINAL step is still pending when training
    stops; the skipped update already held on device (params unchanged),
    and waitall() rolls the host scaler back across the lag window."""
    monkeypatch.setenv("MXNET_AMP_LAG", "1")
    net = _mlp()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    trainer._amp_loss_scaler = amp.LossScaler(init_scale=8.0)
    step = trainer.compile_step(net, _loss_fn)
    clean = _batches(3)
    for x, y in clean:
        step(mx.nd.array(x), mx.nd.array(y), batch_size=6)
    engine.waitall()
    before = {k: p.data().asnumpy().copy()
              for k, p in net.collect_params().items()}
    (x, y), = _batches(1, overflow_at=(0,))
    step(mx.nd.array(x), mx.nd.array(y), batch_size=6)
    # flag unread: host scaler hasn't seen the overflow yet
    assert trainer._amp_loss_scaler.loss_scale == 8.0
    # ...but the device already skipped the update (the fused group gates
    # on THIS step's flag, independent of the lag window)
    for k, p in net.collect_params().items():
        onp.testing.assert_array_equal(p.data().asnumpy(), before[k])
    engine.waitall()                    # the lag window closes
    assert trainer._amp_loss_scaler.loss_scale == 4.0


def test_deferred_read_counter_and_host_sync_budget(monkeypatch):
    """Steady-state budget (tools/check_dispatch_budget.py): a non-AMP
    compiled step performs ZERO blocking host syncs; with AMP + lag the
    only sync is the ONE deferred read of step N-1's flag."""
    monkeypatch.setenv("MXNET_AMP_LAG", "1")
    net = _mlp()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    step = trainer.compile_step(net, _loss_fn)
    batches = _batches(6)
    x0, y0 = batches[0]
    step(mx.nd.array(x0), mx.nd.array(y0), batch_size=6)    # warm
    h0 = _ndmod.host_sync_count()
    for x, y in batches[1:]:
        step(mx.nd.array(x), mx.nd.array(y), batch_size=6)
    assert step.last_step_compiled
    assert _ndmod.host_sync_count() - h0 == 0               # non-AMP: zero

    net2 = _mlp()
    tr2 = gluon.Trainer(net2.collect_params(), "sgd",
                        {"learning_rate": 0.1})
    tr2._amp_loss_scaler = amp.LossScaler(init_scale=8.0)
    step2 = tr2.compile_step(net2, _loss_fn)
    step2(mx.nd.array(x0), mx.nd.array(y0), batch_size=6)   # warm
    h0, d0 = _ndmod.host_sync_count(), cached_step.deferred_read_count()
    for x, y in batches[1:]:
        step2(mx.nd.array(x), mx.nd.array(y), batch_size=6)
    syncs = _ndmod.host_sync_count() - h0
    deferred = cached_step.deferred_read_count() - d0
    assert syncs == deferred == len(batches) - 1            # 1/step, lagged


# ---------------------------------------------------------------------------
# (3) device-side metric accumulators
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("make,feed", [
    (metric.Accuracy,
     lambda rng: ([mx.nd.array(rng.randint(0, 4, (8,)))],
                  [mx.nd.array(rng.rand(8, 4).astype(onp.float32))])),
    (metric.MSE,
     lambda rng: ([mx.nd.array(rng.randn(8, 3).astype(onp.float32))],
                  [mx.nd.array(rng.randn(8, 3).astype(onp.float32))])),
    (metric.CrossEntropy,
     lambda rng: ([mx.nd.array(rng.randint(0, 4, (8,)))],
                  [mx.nd.array(rng.dirichlet(onp.ones(4), 8)
                               .astype(onp.float32))])),
])
def test_device_accumulator_matches_host_path(monkeypatch, make, feed):
    monkeypatch.setenv("MXNET_METRIC_DEVICE", "1")
    dev, host = make(), make()
    rng1, rng2 = onp.random.RandomState(5), onp.random.RandomState(5)
    h0 = metric.host_sync_count()
    for _ in range(4):
        dev.update(*feed(rng1))
    assert metric.host_sync_count() == h0       # no per-batch host sync
    assert dev._dev_pending == 4
    monkeypatch.setenv("MXNET_METRIC_DEVICE", "0")
    for _ in range(4):
        host.update(*feed(rng2))
    assert host._dev_pending == 0
    assert metric.host_sync_count() > h0        # loud host path
    nd_, vd = dev.get()
    nh, vh = host.get()
    assert dev._dev_pending == 0                # .get() folded
    assert vd == pytest.approx(vh, rel=1e-6)
    assert dev.num_inst == host.num_inst


def test_metric_sync_steps_bounds_the_queue(monkeypatch):
    monkeypatch.setenv("MXNET_METRIC_DEVICE", "1")
    monkeypatch.setenv("MXNET_METRIC_SYNC_STEPS", "3")
    m = metric.Loss()
    pred = mx.nd.array(onp.ones(4, onp.float32))
    for i in range(7):
        m.update(0, pred)
    # folds fired at updates 3 and 6 -> at most SYNC_STEPS-1 pending
    assert m._dev_pending == 1
    assert m.get()[1] == pytest.approx(1.0)


def test_waitall_drains_metric_accumulators(monkeypatch):
    monkeypatch.setenv("MXNET_METRIC_DEVICE", "1")
    m = metric.Accuracy()
    m.update([mx.nd.array([1, 1])], [mx.nd.array([[0.0, 1.0], [1.0, 0.0]])])
    assert m._dev_pending == 1
    engine.waitall()
    assert m._dev_pending == 0
    assert m.sum_metric == 1.0 and m.num_inst == 2


def test_metric_reset_drops_pending_device_batches(monkeypatch):
    monkeypatch.setenv("MXNET_METRIC_DEVICE", "1")
    m = metric.Loss()
    m.update(0, mx.nd.array(onp.full(4, 9.0, onp.float32)))
    m.reset()
    m.update(0, mx.nd.array(onp.full(4, 2.0, onp.float32)))
    assert m.get()[1] == pytest.approx(2.0)     # epoch-1 batch discarded


def test_host_only_metric_counts_syncs_loudly():
    m = metric.F1()                             # confusion-matrix family
    h0 = metric.host_sync_count()
    m.update([mx.nd.array([1, 0, 1, 1])],
             [mx.nd.array([[0.1, 0.9], [0.8, 0.2], [0.2, 0.8], [0.3, 0.7]])])
    assert metric.host_sync_count() > h0


# ---------------------------------------------------------------------------
# (4) async checkpointing: COW snapshot + fault site
# ---------------------------------------------------------------------------

def test_async_checkpoint_survives_donation_of_live_buffers(tmp_path):
    """The copy-on-write guard: save() enqueues ON-DEVICE copies, so a
    later compiled step donating (deleting) the live buffers can never
    corrupt the snapshot — the reference's write-after-read hazard that
    the dependency engine exists to prevent."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel.elastic import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=True)
    w = jnp.arange(8.0)
    mgr.save(1, {"w": w})
    assert mgr.snapshot_stats["async"] == 1
    # donate w's buffer — after this the ORIGINAL array is deleted and
    # any read of it raises; only the COW copy keeps the snapshot alive
    bumped = jax.jit(lambda a: a + 1, donate_argnums=0)(w)
    bumped.block_until_ready()
    mgr.wait()
    out, step = mgr.restore()
    assert step == 1
    onp.testing.assert_array_equal(out["w"], onp.arange(8.0))
    mgr.close()


def test_async_checkpoint_naive_engine_is_synchronous(tmp_path, monkeypatch):
    from mxnet_tpu.parallel.elastic import CheckpointManager
    import jax.numpy as jnp

    monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")
    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=True)
    mgr.save(1, {"w": jnp.ones(4)})
    assert mgr.snapshot_stats == {"async": 0, "sync": 1}
    mgr.wait()
    mgr.close()


def test_checkpoint_async_fault_surfaces_at_wait(tmp_path):
    """A failure absorbed by the background writer (site
    ``checkpoint.async``) re-raises at the wait point — the reference
    engine's deferred-exception contract — and the manager keeps working
    afterwards."""
    import jax.numpy as jnp
    from mxnet_tpu.parallel.elastic import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=True)
    with faults.active(faults.FaultPlan().fail("checkpoint.async")):
        mgr.save(1, {"w": jnp.ones(4)})
        with pytest.raises(RuntimeError, match="async checkpoint failed"):
            mgr.wait()
    mgr.save(2, {"w": jnp.full((4,), 2.0)})     # recovered
    engine.waitall()                            # waitall drains writers too
    out, step = mgr.restore()
    assert step == 2
    onp.testing.assert_array_equal(out["w"], onp.full((4,), 2.0))
    mgr.close()


# ---------------------------------------------------------------------------
# (5) waitall / profiler timeline
# ---------------------------------------------------------------------------

def test_waitall_drains_deferred_amp_flag(monkeypatch):
    monkeypatch.setenv("MXNET_AMP_LAG", "1")
    net = _mlp()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    trainer._amp_loss_scaler = amp.LossScaler(init_scale=8.0,
                                              scale_window=1)
    step = trainer.compile_step(net, _loss_fn)
    (x, y), = _batches(1)
    step(mx.nd.array(x), mx.nd.array(y), batch_size=6)
    assert trainer._amp_loss_scaler.loss_scale == 8.0   # flag pending
    engine.waitall()
    assert trainer._amp_loss_scaler.loss_scale == 16.0  # clean step landed


def test_step_timeline_phases_and_idle_gap():
    from mxnet_tpu import profiler

    tl = profiler.StepTimeline("t")
    for _ in range(3):
        with tl.phase("h2d"):
            time.sleep(0.002)
        with tl.phase("dispatch"):
            time.sleep(0.004)
        with tl.phase("read"):
            time.sleep(0.001)
        tl.step()
    s = tl.summary()
    assert s["steps"] == 3
    per = s["phase_us_per_step"]
    assert per["h2d"] >= 1500 and per["dispatch"] >= 3000
    # idle gap = everything except dispatch
    assert s["device_idle_gap_us"] == pytest.approx(
        sum(v for k, v in per.items() if k != "dispatch"), rel=0.01)
    assert s["wall_us_per_step"] >= s["device_idle_gap_us"]
