"""Executor bind/grad scenarios (reference
tests/python/unittest/test_executor.py): binary ops across ranks with
analytic gradient oracles, dot with random shapes, simple_bind reshape
semantics, and the zero-input CachedOp-init analog."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym


def check_bind_with_uniform(ufunc, gfunc, dim, sf=None, lshape=None,
                            rshape=None, rng=None):
    """reference test_executor.check_bind_with_uniform: random uniform
    inputs, forward vs numpy ufunc, backward vs analytic gfunc."""
    rng = rng or onp.random.RandomState(0)
    shape = lshape or tuple(rng.randint(1, 6, size=dim))
    lhs = sym.var("lhs")
    rhs = sym.var("rhs")
    ret = sf(lhs, rhs) if sf is not None else ufunc(lhs, rhs)

    lhs_arr = nd.array(rng.uniform(-1, 1, lshape or shape)
                       .astype(onp.float32))
    rhs_arr = nd.array(rng.uniform(-1, 1, rshape or shape)
                       .astype(onp.float32))
    lhs_grad = nd.zeros((lshape or shape))
    rhs_grad = nd.zeros((rshape or shape))
    exe = ret.bind(mx.cpu(), args=[lhs_arr, rhs_arr],
                   args_grad=[lhs_grad, rhs_grad])
    exe.forward(is_train=True)
    out = exe.outputs[0].asnumpy()
    expect = ufunc(lhs_arr.asnumpy(), rhs_arr.asnumpy())
    onp.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)

    out_grad = nd.array(onp.ones(out.shape, onp.float32) * 2)
    exe.backward([out_grad])
    lg, rg = gfunc(out_grad.asnumpy(), lhs_arr.asnumpy(), rhs_arr.asnumpy())
    onp.testing.assert_allclose(lhs_grad.asnumpy(), lg, rtol=1e-5, atol=1e-6)
    onp.testing.assert_allclose(rhs_grad.asnumpy(), rg, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("dim", [1, 2, 3])
def test_bind_binary_ops(dim):
    rng = onp.random.RandomState(dim)
    check_bind_with_uniform(lambda x, y: x + y, lambda g, x, y: (g, g),
                            dim, rng=rng)
    check_bind_with_uniform(lambda x, y: x - y, lambda g, x, y: (g, -g),
                            dim, rng=rng)
    check_bind_with_uniform(lambda x, y: x * y,
                            lambda g, x, y: (y * g, x * g), dim, rng=rng)
    check_bind_with_uniform(lambda x, y: x / y,
                            lambda g, x, y: (g / y, -x * g / (y ** 2)),
                            dim, rng=rng)


@pytest.mark.parametrize("dim", [1, 2])
def test_bind_maximum_minimum(dim):
    rng = onp.random.RandomState(10 + dim)
    check_bind_with_uniform(lambda x, y: onp.maximum(x, y),
                            lambda g, x, y: (g * (x >= y), g * (y > x)),
                            dim, sf=sym.maximum, rng=rng)
    check_bind_with_uniform(lambda x, y: onp.minimum(x, y),
                            lambda g, x, y: (g * (x <= y), g * (y < x)),
                            dim, sf=sym.minimum, rng=rng)


def test_dot_random_shapes():
    rng = onp.random.RandomState(7)
    for _ in range(5):
        s = tuple(rng.randint(1, 50, size=3))
        check_bind_with_uniform(
            lambda x, y: onp.dot(x, y),
            lambda g, x, y: (onp.dot(g, y.T), onp.dot(x.T, g)),
            2, lshape=(s[0], s[1]), rshape=(s[1], s[2]), sf=sym.dot,
            rng=rng)


def test_dot_1d_inner_product():
    rng = onp.random.RandomState(8)
    for _ in range(3):
        (n,) = tuple(rng.randint(1, 50, size=1))
        check_bind_with_uniform(lambda x, y: onp.dot(x, y),
                                lambda g, x, y: (g * y, g * x),
                                1, lshape=(n,), rshape=(n,), sf=sym.dot,
                                rng=rng)


def test_simple_bind_fc_reshape_semantics():
    # reference test_reshape: weight sharing across reshaped executors,
    # data buffers NOT shared
    x = sym.var("x")
    y = sym.FullyConnected(x, sym.var("w"), sym.var("b"), num_hidden=4)
    exe = y.simple_bind(mx.cpu(), grad_req="null", x=(5, 4))
    exe.arg_dict["x"]._set_data(nd.ones((5, 4))._data)
    exe.arg_dict["w"]._set_data(nd.ones((4, 4))._data)
    exe.arg_dict["b"]._set_data(nd.zeros((4,))._data)
    exe.forward(is_train=False)
    assert (exe.outputs[0].asnumpy() == 4).all()

    exe2 = exe.reshape(x=(3, 4))
    exe2.forward(is_train=False, x=nd.ones((3, 4)))
    assert exe2.outputs[0].shape == (3, 4)
    assert (exe2.outputs[0].asnumpy() == 4).all()

    # weight array is shared; data array is fresh per shape
    exe.arg_dict["x"]._set_data(nd.zeros((5, 4))._data)
    assert (exe2.arg_dict["w"].asnumpy() == 1).all()
    assert exe2.arg_dict["x"].shape == (3, 4)


def test_zero_input_graph_executes():
    # reference test_cached_op_init: a graph with no data inputs runs
    out = sym.zeros((3, 3))
    (z,) = out.eval()
    assert (z.asnumpy() == 0).all()
    out2 = sym.zeros((2, 2)) + 1.0
    (z2,) = out2.eval()
    assert (z2.asnumpy() == 1).all()


def test_grad_req_add_accumulates():
    # reference OpReqType kAddTo through the executor surface
    x = sym.var("x")
    y = x * 2.0
    xa = nd.array(onp.ones((3,), onp.float32))
    xg = nd.array(onp.full((3,), 5.0, onp.float32))
    exe = y.bind(mx.cpu(), args=[xa], args_grad=[xg], grad_req="add")
    exe.forward(is_train=True)
    exe.backward([nd.ones((3,))])
    onp.testing.assert_allclose(xg.asnumpy(), 5.0 + 2.0)
    exe.forward(is_train=True)
    exe.backward([nd.ones((3,))])
    onp.testing.assert_allclose(xg.asnumpy(), 7.0 + 2.0)
