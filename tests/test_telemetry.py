"""Unified telemetry subsystem (ISSUE 10): the process-wide counter
registry, the structured event bus, the span layer, the exporters, and
the ``tools/check_telemetry.py`` CI gate.

Covers: (1) registry declaration/idempotence, deterministic snapshot
ordering, and cumulative-vs-gauge ``delta()`` semantics; (2) the
canonical counter map — every static counter and every dynamic family
this repo ships is named HERE (the gate's test-coverage check keys on
these literals); (3) thread-safety: the registry hammered from
prefetcher / checkpoint-writer / serving-dispatcher threads while
snapshots run concurrently — no torn reads, cumulatives monotonic,
final totals exact; (4) the event bus: step indices on fault events,
the ``MXNET_FAULT_EVENTS`` capacity knob (default + subprocess
override); (5) the ``profiler.dumps(reset=True)`` regression: a trace
reset clears events, never registry-backed ``profiler.Counter`` values;
(6) spans: context-manager + post-hoc records, StepTimeline phases,
``Trainer.step_spans()`` / engine ``spans()`` views, and the chrome
dump; (7) the legacy accessors as registry views; (8) the JSON-lines
flight recorder flushed by ``engine.waitall()``; (9) the gate itself.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as onp
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import (cached_step, engine, faults, gluon, metric,  # noqa: E402
                       profiler, serving, serving_decode, telemetry)
from mxnet_tpu.gluon import nn  # noqa: E402
from mxnet_tpu.parallel import sharding, spmd  # noqa: E402


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_declaration_and_idempotence():
    c1 = telemetry.counter("test.reg.alpha", "a test counter")
    c2 = telemetry.counter("test.reg.alpha", "redeclared")
    assert c1 is c2                       # idempotent by name
    c1.reset()
    c1.inc()
    c1.inc(4)
    assert c1.value == 5 and int(c1) == 5
    g = telemetry.gauge("test.reg.beta")
    g.set(17)
    assert g.kind == "gauge" and g.value == 17
    with pytest.raises(ValueError):
        telemetry.Counter("x", kind="bogus")
    with pytest.raises(KeyError):
        telemetry.get("test.reg.never_declared")
    meta = telemetry.registered()["test.reg.alpha"]
    assert meta["kind"] == "cumulative" and meta["doc"] == "a test counter"


def test_snapshot_deterministic_ordering_and_delta():
    a = telemetry.counter("test.delta.a")
    b = telemetry.counter("test.delta.b")
    g = telemetry.gauge("test.delta.g")
    a.reset(), b.reset()
    base = telemetry.snapshot()
    assert list(base) == sorted(base)     # deterministic ordering
    a.inc(3)
    g.set(42)
    d = telemetry.delta(base)
    assert d["test.delta.a"] == 3 and d["test.delta.b"] == 0
    assert d["test.delta.g"] == 42        # gauges report current value
    # a counter born after the base deltas from zero
    telemetry.counter("test.delta.late").inc(2)
    assert telemetry.delta(base)["test.delta.late"] == 2


def test_counter_group_is_a_mapping_view():
    grp = telemetry.CounterGroup(
        telemetry.instance_name("test.group"), ("x", "y"),
        family="test.group")
    assert dict(grp) == {"x": 0, "y": 0}
    grp.inc("x")
    grp["y"] = 7                          # absolute set
    grp["y"] += 1                         # get-then-set also works
    assert grp["x"] == 1 and grp["y"] == 8 and len(grp) == 2
    # the values live in the registry under the instance prefix
    assert telemetry.snapshot()[f"{grp.prefix}.y"] == 8
    # instance prefixes never collide
    assert telemetry.CounterGroup(
        telemetry.instance_name("test.group"), ("x",)).prefix != grp.prefix


def test_canonical_counters_registered():
    """The counter map: every STATIC registry counter ships declared
    (this list is also the gate's test-coverage anchor)."""
    static = [
        "cached_step.deferred_read",
        "metric.host_sync",
        "ndarray.invoke",
        "ndarray.host_sync",
        "spmd.reshard",
        "spmd.replicated_batch",
        "sharding.legalize_refusal",
        "quantization.pallas_skipped",
        "transformer_lm.flash_fallback",
        "fused.trace",
        "fused.dispatch",
        "nn.pad_channels",
        "engine.drainables",
        "telemetry.events",
        "telemetry.spans",
    ]
    # the ops/nn + models + optimizer modules declare at import
    from mxnet_tpu.contrib import quantization  # noqa: F401
    from mxnet_tpu.models import transformer_lm  # noqa: F401
    from mxnet_tpu.ops import nn as _nn  # noqa: F401
    from mxnet_tpu.optimizer import fused as _fused  # noqa: F401

    reg = telemetry.registered()
    missing = [n for n in static if n not in reg]
    assert not missing, f"static counters not registered: {missing}"
    # program_store namespaces register the full field set
    for ns in ("train_step", "serving", "serving_decode",
               "hybrid_forward", "eager_jit"):
        for f in ("hits", "misses", "evictions", "traces", "dispatches",
                  "aot_fallbacks", "load_degrades", "compile_count",
                  "compile_seconds"):
            assert f"program_store.{ns}.{f}" in reg
    assert reg["program_store.train_step.hits"]["family"] \
        == "program_store.namespace"
    assert reg["program_store.train_step.compile_seconds"]["kind"] == "time"
    # dynamic families: instantiating an owner declares its group
    pool = serving_decode.PagePool(pages=4, page=8)
    assert reg_family(pool._counts.prefix + ".alloc") == "kv_pool"
    grp = faults._stats("telemetry.test_site")
    assert reg_family(grp.prefix + ".attempts") == "faults.site"
    # serving.engine / decode.engine / profiler.user families are pinned
    # by the engine + profiler tests below


def reg_family(name):
    return telemetry.registered()[name]["family"]


def test_engine_stats_are_registry_views():
    """ServingEngine.stats() / GenerativeEngine.stats() read through
    registry counter groups (families serving.engine / decode.engine)."""

    class Id(gluon.HybridBlock):
        def forward(self, x):
            return x * 2

    net = Id()
    net.initialize()
    eng = serving.ServingEngine(net)
    try:
        assert reg_family(eng._stats.prefix + ".requests") \
            == "serving.engine"
        out = eng.infer(mx.nd.ones((2, 3)))
        assert out.shape == (2, 3)
        st = eng.stats()
        assert st["requests"] == 1
        assert telemetry.snapshot()[eng._stats.prefix + ".requests"] == 1
    finally:
        eng.close()
    gen = serving_decode.GenerativeEngine(
        serving_decode.TinyCausalLM(),
        pool=serving_decode.PagePool(pages=32, page=8), max_rows=2)
    try:
        assert reg_family(gen._stats.prefix + ".requests") \
            == "decode.engine"
        toks = gen.generate(onp.asarray([3, 1]), max_new_tokens=2)
        assert len(toks) == 2
        assert gen.stats()["delivered"] == 1
        assert telemetry.snapshot()[gen._stats.prefix + ".delivered"] == 1
        # decode spans rode along (prefill + decode iterations)
        assert any(s["name"] == "decode.prefill" for s in gen.spans())
    finally:
        gen.close()


# ---------------------------------------------------------------------------
# thread safety
# ---------------------------------------------------------------------------

def test_registry_thread_safety_under_hammer():
    """The satellite contract: hammer the registry from threads playing
    the prefetcher, checkpoint writer, and serving dispatcher while the
    main thread snapshots — snapshots are internally consistent (no torn
    reads), cumulatives are monotonic across snapshots, and the final
    totals are exact."""
    shared = telemetry.counter("test.hammer.shared")
    shared.reset()
    privates = {}
    N, ROLES = 2000, ("prefetcher", "checkpoint-writer",
                      "serving-dispatcher")
    for role in ROLES:
        privates[role] = telemetry.counter(f"test.hammer.{role}")
        privates[role].reset()
    stop = threading.Event()
    snaps = []

    def hammer(role):
        for _ in range(N):
            shared.inc()
            privates[role].inc()

    def snapper():
        while not stop.is_set():
            snaps.append(telemetry.snapshot())
        snaps.append(telemetry.snapshot())

    threads = [threading.Thread(target=hammer, args=(r,), name=r)
               for r in ROLES]
    sn = threading.Thread(target=snapper, name="snapper")
    sn.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    sn.join()
    # exact totals: no lost increment under contention
    assert shared.value == N * len(ROLES)
    for role in ROLES:
        assert privates[role].value == N
    # monotonic cumulatives + internal consistency across snapshots
    keys = ["test.hammer.shared"] + [f"test.hammer.{r}" for r in ROLES]
    for prev, cur in zip(snaps, snaps[1:]):
        for k in keys:
            assert cur[k] >= prev[k]
        # the shared counter can never lag the per-role counters it is
        # bumped in lockstep with (a torn read would break this)
        assert cur["test.hammer.shared"] >= max(
            cur[f"test.hammer.{r}"] for r in ROLES)


# ---------------------------------------------------------------------------
# event bus
# ---------------------------------------------------------------------------

def test_event_bus_step_indices_and_fault_routing():
    telemetry.clear_events()
    telemetry.set_step(41)
    telemetry.event("retrace", "test.bus")
    ev = telemetry.events(kind="retrace", name="test.bus")[-1]
    assert ev["step"] == 41 and ev["t_us"] > 0 and ev["seq"] > 0
    # fault events route through the bus and pick up the step index
    telemetry.set_step(42)
    faults.record_event("telemetry.test_site", "retry", ValueError("x"),
                        attempt=2)
    fev = telemetry.events(kind="fault", name="telemetry.test_site")[-1]
    assert fev["step"] == 42 and fev["action"] == "retry"
    assert fev["attempt"] == 2 and "ValueError" in fev["error"]
    # reserved-key collisions are prefixed, not dropped
    telemetry.event("fault", "test.bus", kind_override_check=1,
                    **{"kind": "TransientFault"})
    assert telemetry.events(name="test.bus")[-1]["x_kind"] \
        == "TransientFault"
    telemetry.set_step(None)


def test_fault_event_buffer_capacity_default():
    # the hard-coded deque(maxlen=1024) became the MXNET_FAULT_EVENTS
    # knob; default preserved
    from mxnet_tpu import config as _config

    assert _config.get("MXNET_FAULT_EVENTS") == 1024
    assert faults._EVENTS.maxlen == 1024
    assert telemetry._EVENTS.maxlen \
        == _config.get("MXNET_TELEMETRY_EVENTS") == 4096


@pytest.mark.slow
def test_fault_event_buffer_capacity_knob_subprocess():
    """MXNET_FAULT_EVENTS bounds faults.events() (subprocess: the knob
    is read once at import)."""
    code = (
        "from mxnet_tpu import faults\n"
        "assert faults._EVENTS.maxlen == 7, faults._EVENTS.maxlen\n"
        "for i in range(20):\n"
        "    faults.record_event('cap.site', 'note', i=i)\n"
        "evs = faults.events('cap.site')\n"
        "assert len(evs) == 7 and evs[-1]['i'] == 19\n"
        "print('CAP_OK')\n")
    env = dict(os.environ, MXNET_FAULT_EVENTS="7", JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "CAP_OK" in r.stdout


# ---------------------------------------------------------------------------
# profiler interplay (satellite: dumps(reset=True) vs Counter)
# ---------------------------------------------------------------------------

def test_profiler_counter_survives_trace_reset():
    """Regression: ``profiler.dumps(reset=True)`` clears recorded trace
    events but must NOT clear declared counters — registry-backed
    ``profiler.Counter`` values persist across the reset and across
    re-instantiation."""
    profiler.set_state("run")
    try:
        c = profiler.Counter("survivor")
        c.set_value(5)
        c += 3
        assert c._value == 8
        profiler.dumps(reset=True)        # clears events...
        assert c._value == 8              # ...not the declared counter
        assert telemetry.snapshot()["profiler.survivor"] == 8
        # a re-created Counter of the same name resumes, not restarts
        c2 = profiler.Counter("survivor")
        c2.increment()
        assert c2._value == 9
        assert telemetry.registered()["profiler.survivor"]["family"] \
            == "profiler.user"
        # and the post-reset emission pipeline still works
        table = profiler.dumps(format="json")
        assert "survivor" in table
    finally:
        profiler.set_state("stop")


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

def _tiny_trainer():
    class Net(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.d = nn.Dense(4, in_units=4)

        def forward(self, x):
            return self.d(x)

    net = Net()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.01})
    step = tr.compile_step(net, lambda n, x, y: ((n(x) - y) ** 2).mean())
    x = mx.nd.ones((4, 4))
    y = mx.nd.zeros((4, 4))
    return tr, step, x, y


def test_spans_unify_train_step_and_step_timeline(tmp_path):
    telemetry.clear_spans()
    tr, step, x, y = _tiny_trainer()
    fn = str(tmp_path / "trace.json")
    profiler.set_config(filename=fn)
    profiler.set_state("run")
    try:
        tl = profiler.StepTimeline()
        with tl.phase("h2d"):
            pass
        with tl.phase("dispatch"):
            step(x, y, batch_size=4).asnumpy()
        tl.step()
        with telemetry.span("user.block", cat="user",
                            args={"k": 1}) as sp:
            sp.annotate(extra=2)
    finally:
        profiler.set_state("stop")
    # every layer landed in the ONE span buffer...
    cats = {s["cat"] for s in telemetry.spans()}
    assert {"train_step", "step_phase", "user"} <= cats
    rec = telemetry.spans(cat="user")[-1]
    assert rec["args"] == {"k": 1, "extra": 2} and rec["dur_us"] >= 1
    # ...and in the ONE chrome-trace pipe (profiler.dump)
    path = profiler.dump()
    with open(path) as f:
        trace = json.load(f)
    chrome_cats = {e["cat"] for e in trace["traceEvents"]
                   if e.get("ph") == "X"}
    assert {"train_step", "step_phase", "user"} <= chrome_cats
    # the per-step span record API: one record per TrainStep call,
    # carrying the step index and the compiled/eager path
    spans = tr.step_spans()
    assert spans and spans[-1]["name"] == "train_step.step"
    assert spans[-1]["args"]["path"] in ("compiled", "eager")
    assert isinstance(spans[-1]["args"]["step"], int)


def test_train_step_advances_step_index():
    _, step, x, y = _tiny_trainer()
    before = telemetry.current_step()
    step(x, y, batch_size=4)
    after = telemetry.current_step()
    assert after is not None and (before is None or after == before + 1)


def test_serving_engine_spans():
    class Id(gluon.HybridBlock):
        def forward(self, x):
            return x + 1

    net = Id()
    net.initialize()
    eng = serving.ServingEngine(net)
    try:
        eng.infer(mx.nd.ones((2, 2)))
    finally:
        eng.close()
    names = {s["name"] for s in eng.spans()}
    assert "serving.request" in names and "serving.dispatch" in names


# ---------------------------------------------------------------------------
# legacy accessors are views
# ---------------------------------------------------------------------------

def test_legacy_accessors_are_registry_views():
    # cached_step.deferred_read_count
    base = telemetry.snapshot()
    telemetry.get("cached_step.deferred_read").inc()
    assert cached_step.deferred_read_count() \
        == telemetry.snapshot()["cached_step.deferred_read"]
    telemetry.get("cached_step.deferred_read").inc(-1)  # restore
    # metric.host_sync_count (the loud host-path fallback counter)
    metric.reset_host_sync_count()
    metric._host(mx.nd.array([1.0, 2.0]))
    assert metric.host_sync_count() \
        == telemetry.snapshot()["metric.host_sync"] == 1
    # spmd / sharding counters
    assert spmd.reshard_count() == telemetry.snapshot()["spmd.reshard"]
    assert spmd.replicated_batch_count() \
        == telemetry.snapshot()["spmd.replicated_batch"]
    assert sharding.legalize_refusal_count() \
        == telemetry.snapshot()["sharding.legalize_refusal"]
    # engine drainables (computed gauge)
    assert telemetry.snapshot()["engine.drainables"] \
        == engine.drainable_count()
    # program_store-backed module views
    ns_traces = telemetry.snapshot()["program_store.train_step.traces"]
    assert cached_step.trace_count() == ns_traces
    # faults counters (family faults.site)
    faults.retry_call(lambda: 1, site="telemetry.test_site")
    assert faults.counters("telemetry.test_site")["attempts"] \
        == telemetry.snapshot()["faults.telemetry.test_site.attempts"]
    # reset functions reset the registry values too
    cached_step.reset_counters()
    assert telemetry.snapshot()["cached_step.deferred_read"] == 0


# ---------------------------------------------------------------------------
# flight recorder + report
# ---------------------------------------------------------------------------

def test_flight_recorder_flushed_by_waitall(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY_DIR", str(tmp_path))
    telemetry.event("retrace", "test.recorder", detail="flush me")
    engine.waitall()                      # flushes the recorder
    path = telemetry.flight_recorder_path()
    assert path is not None and os.path.exists(path)
    lines = [json.loads(l) for l in open(path) if l.strip()]
    kinds = [l["kind"] for l in lines]
    assert "snapshot" in kinds            # the counter snapshot record
    assert any(l.get("name") == "test.recorder" for l in lines)
    snap = [l for l in lines if l["kind"] == "snapshot"][-1]
    assert "telemetry.events" in snap["counters"]
    # flush is incremental: a second flush does not duplicate events
    n0 = sum(1 for l in lines if l.get("name") == "test.recorder")
    telemetry.flush()
    lines2 = [json.loads(l) for l in open(path) if l.strip()]
    assert sum(1 for l in lines2
               if l.get("name") == "test.recorder") == n0


def test_flight_recorder_off_by_default(monkeypatch):
    monkeypatch.delenv("MXNET_TELEMETRY_DIR", raising=False)
    assert telemetry.flight_recorder_path() is None
    assert telemetry.flush() is None


def test_report_table():
    telemetry.counter("test.report.widget").inc(3)
    out = telemetry.report(prefix="test.report")
    assert "test.report.widget" in out and "cumulative" in out
    assert "declared counters" in out.splitlines()[-1]


# ---------------------------------------------------------------------------
# the CI gate
# ---------------------------------------------------------------------------

def _load_gate():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_telemetry", os.path.join(REPO, "tools",
                                        "check_telemetry.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check_telemetry_gate_static_smoke():
    """Tier-1 smoke for the telemetry gate: the order-independent
    static + registry halves over the REAL tree — accessors found, zero
    raw (non-registry) counter state, and every counter registered so
    far named in a test.  The runtime lanes (deterministic TrainStep
    delta, chrome trace, 2-process merge) ride the slow lane (ISSUE-17
    wall slice 2)."""
    gate = _load_gate()
    pkg = os.path.join(REPO, "mxnet_tpu")
    accessors = gate.collect_accessors(pkg)
    assert accessors
    assert gate.collect_raw_state(pkg) == []
    assert gate.check_tested(telemetry.registered(),
                             os.path.join(REPO, "tests")) == []


@pytest.mark.slow
def test_check_telemetry_gate_passes():
    """The CI gate itself: zero unregistered counters, every counter
    named in a test, deterministic steady-state TrainStep delta, chrome
    trace with >= 3 span categories.  ~20s of compiled runtime lanes,
    so slow-marked; tier-1 keeps the static smoke above (ISSUE-17 wall
    slice 2)."""
    gate = _load_gate()
    assert gate.main(REPO) == 0


def test_check_telemetry_detects_rogue_counter(tmp_path):
    """A raw module-global counter (the pre-registry idiom) or an
    accessor with no registered counter fails the gate's static half."""
    gate = _load_gate()
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    (pkg / "rogue.py").write_text(
        "_ROGUE_COUNT = 0\n\n"
        "def rogue_count():\n    return _ROGUE_COUNT\n")
    raw = gate.collect_raw_state(str(pkg))
    assert raw and "rogue" in raw[0]
    acc = gate.collect_accessors(str(pkg))
    assert "rogue" in acc
    assert gate.check_registered(acc, {"some.other.counter": {}}) \
        == [f"rogue_count (declared in "
            f"{os.path.join('mxnet_tpu', 'rogue.py')})"]
