"""Loss-function edge cases (reference tests/python/unittest/test_loss.py
scenarios: weighting, masking, numerical stability, known-value oracles)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


def test_softmax_ce_matches_manual():
    rng = onp.random.RandomState(0)
    logits = rng.randn(4, 5).astype(onp.float32)
    labels = onp.array([0, 2, 4, 1], onp.int32)
    loss = gluon.loss.SoftmaxCrossEntropyLoss()(
        nd.array(logits), nd.array(labels)).asnumpy()
    p = onp.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    expect = -onp.log(p[onp.arange(4), labels])
    onp.testing.assert_allclose(loss, expect, rtol=1e-5)


def test_softmax_ce_sparse_vs_dense_labels():
    rng = onp.random.RandomState(1)
    logits = rng.randn(3, 4).astype(onp.float32)
    sparse = onp.array([1, 3, 0], onp.int32)
    dense = onp.eye(4, dtype=onp.float32)[sparse]
    l1 = gluon.loss.SoftmaxCrossEntropyLoss()(
        nd.array(logits), nd.array(sparse)).asnumpy()
    l2 = gluon.loss.SoftmaxCrossEntropyLoss(sparse_label=False)(
        nd.array(logits), nd.array(dense)).asnumpy()
    onp.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_sample_weight_zeroes_contributions():
    rng = onp.random.RandomState(2)
    pred = nd.array(rng.rand(4, 3).astype(onp.float32))
    label = nd.array(rng.rand(4, 3).astype(onp.float32))
    w = nd.array(onp.array([1, 0, 1, 0], onp.float32).reshape(4, 1))
    loss = gluon.loss.L2Loss()(pred, label, w).asnumpy()
    assert loss[1] == 0 and loss[3] == 0
    assert loss[0] > 0 and loss[2] > 0


def test_sigmoid_bce_extreme_logits_stable():
    """Large-magnitude logits must not produce inf/nan (log-sum-exp
    stability, reference test_bce_loss)."""
    pred = nd.array(onp.array([[50.0], [-50.0]], onp.float32))
    label = nd.array(onp.array([[1.0], [0.0]], onp.float32))
    loss = gluon.loss.SigmoidBinaryCrossEntropyLoss()(pred, label).asnumpy()
    assert onp.isfinite(loss).all() and (loss >= 0).all()
    assert loss.max() < 1e-3          # correct prediction -> tiny loss
    # wrong-way extreme logits -> ~|logit| loss, still finite
    loss2 = gluon.loss.SigmoidBinaryCrossEntropyLoss()(
        pred, 1 - label).asnumpy()
    onp.testing.assert_allclose(loss2.ravel(), [50.0, 50.0], rtol=1e-3)


def test_kl_div_known_value():
    """from_logits=True consumes LOG-probabilities (reference loss.py
    KLDivLoss contract); the value is mean-over-axis KL."""
    p = onp.array([[0.2, 0.3, 0.5]], onp.float32)
    q = onp.array([[0.3, 0.3, 0.4]], onp.float32)
    loss = gluon.loss.KLDivLoss(from_logits=True)(
        nd.array(onp.log(q)), nd.array(p))
    expect = (p * onp.log(p / q)).sum() / 3  # mean over axis
    onp.testing.assert_allclose(float(loss.asnumpy()[0]), expect,
                                rtol=1e-4)


def test_huber_transitions_quadratic_to_linear():
    rho = 1.0
    pred = nd.array(onp.array([[0.5], [3.0]], onp.float32))
    label = nd.zeros((2, 1))
    loss = gluon.loss.HuberLoss(rho=rho)(pred, label).asnumpy().ravel()
    onp.testing.assert_allclose(loss[0], 0.5 * 0.5 ** 2, rtol=1e-5)
    onp.testing.assert_allclose(loss[1], 3.0 - 0.5 * rho, rtol=1e-5)


def test_triplet_loss_margin_semantics():
    a = nd.zeros((2, 4))
    pos = nd.zeros((2, 4))
    neg = nd.array(onp.full((2, 4), 10.0, onp.float32))
    loss = gluon.loss.TripletLoss(margin=1.0)(a, pos, neg).asnumpy()
    assert (loss == 0).all()          # negative far away -> no loss
    loss2 = gluon.loss.TripletLoss(margin=1.0)(a, neg, pos).asnumpy()
    assert (loss2 > 0).all()          # swapped -> margin violated


def test_losses_backward_finite():
    rng = onp.random.RandomState(3)
    pred = nd.array(rng.rand(4, 6).astype(onp.float32))
    pred.attach_grad()
    label = nd.array(rng.rand(4, 6).astype(onp.float32))
    for loss_fn in (gluon.loss.L1Loss(), gluon.loss.L2Loss(),
                    gluon.loss.HuberLoss(),
                    gluon.loss.SigmoidBinaryCrossEntropyLoss()):
        with autograd.record():
            loss = loss_fn(pred, label).sum()
        loss.backward()
        assert onp.isfinite(pred.grad.asnumpy()).all(), type(loss_fn)


def test_sdml_loss_oracle_and_grad():
    """SDMLLoss (reference loss.py:997): per-row KL between the softmax
    over negative pairwise distances and a smoothed identity."""
    R = onp.random.RandomState(2)
    x1 = R.rand(6, 8).astype("float32")
    x2 = x1 + 0.01 * R.rand(6, 8).astype("float32")
    loss_fn = gluon.loss.SDMLLoss(smoothing_parameter=0.3)
    a, b = nd.array(x1), nd.array(x2)
    a.attach_grad()
    with autograd.record():
        loss = loss_fn(a, b)
    loss.backward()
    assert loss.shape == (6,)
    assert onp.isfinite(a.grad.asnumpy()).all()

    d = ((x1[:, None, :] - x2[None, :, :]) ** 2).sum(2)
    m = (-d) - (-d).max(1, keepdims=True)
    lp = m - onp.log(onp.exp(m).sum(1, keepdims=True))
    eye = onp.eye(6)
    s = 0.3
    lab = eye * (1 - s) + (1 - eye) * s / 5
    want = (lab * (onp.log(lab + 1e-12) - lp)).sum(1)
    onp.testing.assert_allclose(loss.asnumpy(), want, rtol=1e-4, atol=1e-5)


def test_sdml_loss_prefers_aligned_pairs():
    """Training signal sanity: aligned batches produce a smaller loss
    than shuffled (misaligned) ones."""
    R = onp.random.RandomState(3)
    x = R.rand(8, 16).astype("float32") * 3
    loss_fn = gluon.loss.SDMLLoss()
    aligned = float(loss_fn(nd.array(x), nd.array(x)).mean().asnumpy())
    perm = R.permutation(8)
    shuffled = float(loss_fn(nd.array(x),
                             nd.array(x[perm])).mean().asnumpy())
    assert aligned < shuffled
