"""README code snippets stay executable (a doc snippet already shipped
broken once — this is the guard; the reference's analog is its doctest
suite, tests/python/doctest)."""
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _python_blocks():
    text = open(os.path.join(REPO, "README.md")).read()
    return re.findall(r"```python\n(.*?)```", text, re.DOTALL)


@pytest.mark.slow
def test_readme_python_snippets_execute():
    blocks = _python_blocks()
    assert len(blocks) >= 2, "README lost its quick-start snippets"
    # snippets build on each other: run them as one program, in order
    program = "\n\n".join(blocks) + "\nprint('README_OK')\n"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONPATH", None)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    r = subprocess.run(
        [sys.executable, "-c",
         f"import sys; sys.path.insert(0, {REPO!r})\n" + program],
        capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, (
        f"README snippet failed:\nstdout:{r.stdout[-1500:]}\n"
        f"stderr:{r.stderr[-1500:]}")
    assert "README_OK" in r.stdout
