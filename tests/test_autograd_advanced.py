"""Deeper autograd + exception-propagation scenarios.

Reference analogs: tests/python/unittest/test_autograd.py (grad-of-graph,
retain_graph, create_graph higher-order), test_exc_handling.py (async
errors surface at sync points; NaiveEngine surfaces them at the op).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


def test_autograd_grad_function():
    """autograd.grad returns grads without touching .grad attributes."""
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    (gx,) = autograd.grad(y, [x])
    onp.testing.assert_allclose(gx.asnumpy(), 2 * x.asnumpy())


def test_second_order_gradient():
    """grad of grad: d2/dx2 (x^3) = 6x (reference create_graph=True)."""
    x = nd.array([1.0, 2.0, 4.0])
    x.attach_grad()
    with autograd.record():
        y = (x ** 3).sum()
        (gx,) = autograd.grad(y, [x], create_graph=True)
        z = gx.sum()
    z.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 6 * x.asnumpy(),
                                rtol=1e-5)


def test_retain_graph_double_backward():
    x = nd.array([2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward(retain_graph=True)
    first = x.grad.asnumpy().copy()
    y.backward()                       # second pass must still work
    onp.testing.assert_allclose(first, 2 * x.asnumpy())


def test_train_vs_predict_mode_dropout():
    """Dropout drops under train_mode and is identity under predict_mode
    (reference autograd train_mode/predict_mode scopes)."""
    mx.random.seed(0)
    net = mx.gluon.nn.Dropout(0.5)
    x = nd.ones((200,))
    with autograd.record(train_mode=True):
        out_train = net(x)
    with autograd.record(train_mode=False):
        out_pred = net(x)
    assert (out_train.asnumpy() == 0).any(), "train mode must drop"
    onp.testing.assert_allclose(out_pred.asnumpy(), x.asnumpy())


def test_grad_through_custom_function_twice():
    class Square(autograd.Function):
        def forward(self, x):
            self.save_for_backward(x)
            return x * x

        def backward(self, dy):
            (x,) = self.saved_tensors
            return 2 * x * dy

    x = nd.array([3.0, 4.0])
    x.attach_grad()
    f = Square()
    with autograd.record():
        y = f(x).sum()
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_grad_req_null_parameter():
    """grad_req='null' params get no gradient and don't break backward."""
    a = nd.array([1.0, 2.0])
    b = nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad(grad_req="null")
    with autograd.record():
        y = (a * b).sum()
    y.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(), b.asnumpy())


# ---------------------------------------------------------------------------
# exception propagation (reference test_exc_handling.py): async dispatch
# defers errors to the sync point; NaiveEngine surfaces them at the op
# ---------------------------------------------------------------------------

def test_invalid_op_args_raise():
    a = nd.ones((2, 3))
    b = nd.ones((4, 5))
    with pytest.raises(Exception):
        nd.dot(a, b).asnumpy()        # shape mismatch surfaces at/by sync


def test_error_surfaces_at_sync_not_lost():
    """An invalid argument combination must raise, not silently produce
    garbage, whether or not a sync follows immediately."""
    a = nd.ones((2, 3))
    with pytest.raises(Exception):
        out = nd.reshape(a, shape=(7, 7))   # impossible reshape
        out.wait_to_read()


def test_naive_engine_surfaces_at_op(monkeypatch):
    """With MXNET_ENGINE_TYPE=NaiveEngine every op is synchronous, so the
    raise happens at the faulting call itself (reference NaiveEngine
    debugging contract)."""
    monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")
    from mxnet_tpu import engine

    assert engine.is_naive()
    a = nd.ones((2, 3))
    with pytest.raises(Exception):
        nd.dot(a, nd.ones((4, 5)))    # no sync needed


def test_exception_inside_record_leaves_state_clean():
    """A raising op inside record() must not leave the tape recording."""
    x = nd.array([1.0])
    x.attach_grad()
    try:
        with autograd.record():
            nd.dot(nd.ones((2, 3)), nd.ones((4, 5)))
    except Exception:
        pass
    assert not autograd.is_recording()
    # a fresh record still works
    with autograd.record():
        y = (x * 2).sum()
    y.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [2.0])


def test_third_order_gradient():
    """The grad node carries its own pure fn, so replay recurses:
    d3/dx3 (x^4) = 24x."""
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = (x ** 4).sum()
        (g1,) = autograd.grad(y, [x], create_graph=True)
        (g2,) = autograd.grad(g1.sum(), [x], create_graph=True)
        z = g2.sum()
    z.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 24 * x.asnumpy(),
                                rtol=1e-5)


def test_second_order_through_hybridized_block():
    """create_graph replays through a hybridized (whole-graph jitted)
    block node: d2/dx2 sum(Dense(x)^2) = 2*W^T W diag contributions."""
    net = mx.gluon.nn.Dense(3, use_bias=False)
    net.initialize()
    net(nd.ones((2, 4)))
    net.hybridize()
    net(nd.ones((2, 4)))                    # build the cached op
    x = nd.array(onp.random.RandomState(0).rand(2, 4).astype("float32"))
    x.attach_grad()
    with autograd.record():
        y = (net(x) ** 2).sum()
        (gx,) = autograd.grad(y, [x], create_graph=True)
        z = (gx ** 2).sum()
    z.backward()
    W = net.weight.data().asnumpy()
    # gx = 2 x W^T W ; z = ||gx||^2 ; dz/dx = 2 gx (2 W^T W) = 8 x (W^T W)^2
    WtW = W.T @ W
    expect = 8 * x.asnumpy() @ (WtW @ WtW)
    onp.testing.assert_allclose(x.grad.asnumpy(), expect, rtol=1e-4)


def test_create_graph_constant_mutation_isolation():
    """Replay must see constants as they were at RECORD time; mutating a
    non-variable input afterwards must not change the gradient (regression:
    value_of once read live _data)."""
    x = nd.array([1.0, 1.0])
    c = nd.array([3.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * c).sum()
        c[:] = 0.0                    # mutate AFTER the op recorded
        (gx,) = autograd.grad(y, [x], create_graph=True)
    onp.testing.assert_allclose(gx.asnumpy(), [3.0, 3.0])
    (gx_ref,) = autograd.grad(y, [x])
    onp.testing.assert_allclose(gx.asnumpy(), gx_ref.asnumpy())


def test_create_graph_cuts_at_variables():
    """A custom Function UPSTREAM of the variable is off the replay path
    and must not trip the pure-replay check (regression: _collect_subgraph
    once walked through variables)."""
    class Cube(autograd.Function):
        def forward(self, x):
            self.save_for_backward(x)
            return x * x * x

        def backward(self, dy):
            (x,) = self.saved_tensors
            return 3 * x * x * dy

    w = nd.array([2.0])
    w.attach_grad()
    with autograd.record():
        t = Cube()(w)                  # un-replayable node
        u = t + 0.0
        y = (u * u).sum()
        (gu,) = autograd.grad(y, [u], create_graph=True)  # cut at u
        z = gu.sum()
    z.backward()                       # d(2u)/du = 2, flows back through u
    onp.testing.assert_allclose(gu.asnumpy(), 2 * u.asnumpy(), rtol=1e-6)
