"""NDArray basics (reference tests/python/unittest/test_ndarray.py analog)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation():
    x = nd.zeros((2, 3))
    assert x.shape == (2, 3)
    assert x.dtype == onp.float32
    assert onp.array_equal(x.asnumpy(), onp.zeros((2, 3), "float32"))
    y = nd.ones((4,), dtype="int32")
    assert y.dtype == onp.int32
    z = nd.full((2, 2), 7.0)
    assert float(z[0, 0].asscalar()) == 7.0
    a = nd.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)


def test_arithmetic():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[10.0, 20.0], [30.0, 40.0]])
    assert onp.allclose((a + b).asnumpy(), [[11, 22], [33, 44]])
    assert onp.allclose((b - a).asnumpy(), [[9, 18], [27, 36]])
    assert onp.allclose((a * 2).asnumpy(), [[2, 4], [6, 8]])
    assert onp.allclose((2 * a).asnumpy(), [[2, 4], [6, 8]])
    assert onp.allclose((1.0 / a).asnumpy(), 1.0 / a.asnumpy())
    assert onp.allclose((a ** 2).asnumpy(), [[1, 4], [9, 16]])
    assert onp.allclose((-a).asnumpy(), -a.asnumpy())
    c = a.copy()
    c += b
    assert onp.allclose(c.asnumpy(), [[11, 22], [33, 44]])


def test_broadcast():
    a = nd.ones((2, 1, 3))
    b = nd.ones((1, 4, 3))
    assert (a + b).shape == (2, 4, 3)
    assert onp.allclose((a + b).asnumpy(), 2.0)


def test_comparisons():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    assert onp.array_equal((a > b).asnumpy(), [0, 0, 1])
    assert onp.array_equal((a == b).asnumpy(), [0, 1, 0])
    assert onp.array_equal((a <= 2.0).asnumpy(), [1, 1, 0])


def test_reshape_transpose():
    a = nd.arange(0, 24).reshape((2, 3, 4))
    assert a.shape == (2, 3, 4)
    assert a.T.shape == (4, 3, 2)
    assert a.transpose((1, 0, 2)).shape == (3, 2, 4)
    assert a.reshape((-1, 4)).shape == (6, 4)
    # MXNet special reshape codes
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.flatten().shape == (2, 12)
    assert a.expand_dims(0).shape == (1, 2, 3, 4)
    assert a.squeeze(axis=None).shape == (2, 3, 4)


def test_indexing():
    a = nd.arange(0, 12).reshape((3, 4))
    assert a[1].shape == (4,)
    assert float(a[1, 2].asscalar()) == 6.0
    assert a[0:2].shape == (2, 4)
    assert a[:, 1:3].shape == (3, 2)
    a[0, 0] = 42.0
    assert float(a[0, 0].asscalar()) == 42.0
    a[:] = 0.0
    assert onp.allclose(a.asnumpy(), 0.0)


def test_reductions():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    assert float(a.sum().asscalar()) == 10.0
    assert float(a.mean().asscalar()) == 2.5
    assert float(a.max().asscalar()) == 4.0
    assert float(a.min().asscalar()) == 1.0
    assert onp.allclose(a.sum(axis=0).asnumpy(), [4, 6])
    assert onp.allclose(a.sum(axis=1, keepdims=True).asnumpy(), [[3], [7]])
    assert onp.allclose(nd.norm(a).asnumpy(), onp.linalg.norm(a.asnumpy()))
    assert onp.array_equal(a.argmax(axis=1).asnumpy(), [1, 1])


def test_dot():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[5.0, 6.0], [7.0, 8.0]])
    assert onp.allclose(nd.dot(a, b).asnumpy(), a.asnumpy() @ b.asnumpy())
    v = nd.array([1.0, 2.0])
    assert onp.allclose(nd.dot(a, v).asnumpy(), a.asnumpy() @ v.asnumpy())
    # batch_dot
    x = nd.random.uniform(shape=(4, 2, 3))
    y = nd.random.uniform(shape=(4, 3, 5))
    assert nd.batch_dot(x, y).shape == (4, 2, 5)


def test_concat_split_stack():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    c2 = nd.concat(a, b, dim=1)
    assert c2.shape == (2, 6)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)
    parts = nd.split(c, num_outputs=2, axis=0)
    assert len(parts) == 2 and parts[0].shape == (2, 3)


def test_take_one_hot():
    w = nd.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
    idx = nd.array([0, 2], dtype="int32")
    out = nd.take(w, idx)
    assert onp.allclose(out.asnumpy(), [[1, 2], [5, 6]])
    oh = nd.one_hot(idx, 3)
    assert onp.allclose(oh.asnumpy(), [[1, 0, 0], [0, 0, 1]])


def test_astype_cast():
    a = nd.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == onp.int32
    c = a.astype("float16")
    assert c.dtype == onp.float16


def test_copyto_context():
    a = nd.array([1.0, 2.0])
    b = nd.zeros((2,))
    a.copyto(b)
    assert onp.allclose(b.asnumpy(), [1, 2])
    c = a.as_in_context(mx.cpu())
    assert c.ctx.device_type == "cpu"


def test_array_explicit_ctx_moves_committed_payload():
    # nd.array(nd, ctx=...) must MOVE the payload (reference device-to-device
    # copy semantics), even though the source already wraps a jax array.
    # Caught live: the int8 bench staged params to the accelerator but the
    # input stayed committed to host CPU, failing jit device placement.
    import jax

    if len(jax.devices()) < 2:
        import pytest
        pytest.skip("needs >=2 devices")
    a = nd.array([1.0, 2.0], ctx=mx.cpu(0))
    b = nd.array(a, ctx=mx.cpu(1))
    assert b.ctx == mx.cpu(1)
    assert list(b._data.devices()) == [jax.devices()[1]]
    assert onp.allclose(b.asnumpy(), [1, 2])
    # no explicit ctx: wrap in place, no surprise copy
    c = nd.array(a)
    assert c.ctx == a.ctx


def test_save_load(tmp_path):
    fname = str(tmp_path / "params.npz")
    data = {"w": nd.array([1.0, 2.0]), "b": nd.array([3.0])}
    nd.save(fname, data)
    loaded = nd.load(fname)
    assert set(loaded) == {"w", "b"}
    assert onp.allclose(loaded["w"].asnumpy(), [1, 2])
    lst = [nd.ones((2,)), nd.zeros((3,))]
    nd.save(fname, lst)
    loaded2 = nd.load(fname)
    assert isinstance(loaded2, list) and len(loaded2) == 2


def test_scalar_conversions():
    a = nd.array([3.5])
    assert float(a) == 3.5
    assert a.asscalar() == onp.float32(3.5)
    with pytest.raises(ValueError):
        nd.ones((2,)).asscalar()


def test_waitall_and_sync():
    a = nd.random.uniform(shape=(100, 100))
    b = nd.dot(a, a)
    b.wait_to_read()
    nd.waitall()
    assert b.shape == (100, 100)


def test_version_bumps_on_write():
    a = nd.zeros((2,))
    v0 = a.version
    a[:] = 1.0
    assert a.version == v0 + 1


def test_where_clip_maximum():
    a = nd.array([-1.0, 0.5, 2.0])
    assert onp.allclose(a.clip(0.0, 1.0).asnumpy(), [0, 0.5, 1.0])
    b = nd.maximum_scalar(a, scalar=0.0)
    assert onp.allclose(b.asnumpy(), [0, 0.5, 2.0])
    cond = nd.array([1.0, 0.0, 1.0])
    x = nd.ones((3,))
    y = nd.zeros((3,))
    assert onp.allclose(nd.where(cond, x, y).asnumpy(), [1, 0, 1])


def test_contrib_namespace_resolves_registry():
    """nd.contrib exposes every registry op (the reference's generated
    contrib namespace), including late/aliased registrations."""
    import numpy as onp

    import mxnet_tpu as mx

    assert callable(mx.nd.contrib.box_nms)
    assert callable(mx.nd.contrib.RROIAlign)
    out = mx.nd.contrib.quadratic(mx.nd.array([1.0, 2.0]), a=1, b=2, c=3)
    onp.testing.assert_allclose(out.asnumpy(), [6.0, 11.0])
    import pytest

    with pytest.raises(AttributeError):
        mx.nd.contrib.not_an_op_at_all
