"""Statistical correctness of the device samplers — mirrors the
reference's ``test_random.py`` generator family (chi-square bucket fits
via ``test_utils.verify_generator``, seed discipline, shuffle
uniformity)."""
import numpy as onp
import pytest
from scipy import stats as sps

import mxnet_tpu as mx
from mxnet_tpu import nd, test_utils as tu

_NS = 20000
_NREP = 3


def _gen(fn):
    def g(n):
        return fn(n).asnumpy().ravel()

    return g


def test_uniform_generator():
    buckets, probs = tu.gen_buckets_probs_with_ppf(
        sps.uniform(0, 1).ppf, 5)
    tu.verify_generator(_gen(lambda n: mx.nd.random.uniform(
        0.0, 1.0, shape=(n,))), buckets, probs, nsamples=_NS,
        nrepeat=_NREP)


def test_normal_generator():
    mu, sigma = 1.5, 2.0
    buckets, probs = tu.gen_buckets_probs_with_ppf(
        sps.norm(mu, sigma).ppf, 5)
    tu.verify_generator(_gen(lambda n: mx.nd.random.normal(
        mu, sigma, shape=(n,))), buckets, probs, nsamples=_NS,
        nrepeat=_NREP)


def test_gamma_generator():
    alpha, beta = 9.0, 0.5
    buckets, probs = tu.gen_buckets_probs_with_ppf(
        sps.gamma(a=alpha, scale=beta).ppf, 5)
    tu.verify_generator(_gen(lambda n: mx.nd.random.gamma(
        alpha, beta, shape=(n,))), buckets, probs, nsamples=_NS,
        nrepeat=_NREP)


def test_exponential_generator():
    lam = 4.0
    buckets, probs = tu.gen_buckets_probs_with_ppf(
        sps.expon(scale=1.0 / lam).ppf, 5)
    tu.verify_generator(_gen(lambda n: mx.nd.random.exponential(
        lam, shape=(n,))), buckets, probs, nsamples=_NS, nrepeat=_NREP)


def test_poisson_generator():
    lam = 4.0
    buckets = list(range(10))
    probs = [float(sps.poisson.pmf(k, lam)) for k in buckets]
    # discrete buckets: out-of-range mass (k >= 10) is ~0.8%; fold it by
    # testing only the covered range proportions via raw counts
    tu.verify_generator(_gen(lambda n: mx.nd.random.poisson(
        lam, shape=(n,))), buckets, probs, nsamples=_NS, nrepeat=_NREP,
        success_rate=0.2)


def test_randint_generator():
    lo, hi = 3, 11
    buckets = list(range(lo, hi))
    probs = [1.0 / (hi - lo)] * (hi - lo)
    tu.verify_generator(_gen(lambda n: mx.nd.random.randint(
        lo, hi, shape=(n,))), buckets, probs, nsamples=_NS,
        nrepeat=_NREP)


def test_multinomial_proportions():
    p = onp.array([0.1, 0.2, 0.3, 0.4], "float32")
    out = mx.nd.random.multinomial(nd.array(p), shape=(_NS,)).asnumpy()
    counts = onp.bincount(out.astype(int).ravel(), minlength=4)
    onp.testing.assert_allclose(counts / counts.sum(), p, atol=0.02)


def test_mean_var_of_normal_sampler():
    g = _gen(lambda n: mx.nd.random.normal(2.0, 3.0, shape=(n,)))
    assert tu.mean_check(g, 2.0, 3.0, nsamples=200000, alpha=0.01)
    assert tu.var_check(g, 3.0, nsamples=2000)


# ---------------------------------------------------------------------------
# seed discipline (reference test_random_seed_setting /
# test_parallel_random_seed_setting)
# ---------------------------------------------------------------------------

def test_seed_determinism():
    mx.random.seed(1234)
    a = mx.nd.random.uniform(shape=(16,)).asnumpy()
    b = mx.nd.random.uniform(shape=(16,)).asnumpy()
    mx.random.seed(1234)
    a2 = mx.nd.random.uniform(shape=(16,)).asnumpy()
    b2 = mx.nd.random.uniform(shape=(16,)).asnumpy()
    onp.testing.assert_array_equal(a, a2)
    onp.testing.assert_array_equal(b, b2)
    assert not onp.array_equal(a, b)        # the chain advances


def test_different_seeds_differ():
    mx.random.seed(1)
    a = mx.nd.random.normal(shape=(32,)).asnumpy()
    mx.random.seed(2)
    b = mx.nd.random.normal(shape=(32,)).asnumpy()
    assert not onp.array_equal(a, b)


def test_np_random_shares_seed_control():
    mx.random.seed(77)
    a = mx.np.random.uniform(size=(8,)).asnumpy()
    mx.random.seed(77)
    b = mx.np.random.uniform(size=(8,)).asnumpy()
    onp.testing.assert_array_equal(a, b)


def test_seed_independent_of_draw_shape():
    """Counter-based keys: seeding then drawing different shapes stays
    reproducible per call position."""
    mx.random.seed(5)
    _ = mx.nd.random.uniform(shape=(3,))
    second = mx.nd.random.uniform(shape=(4, 4)).asnumpy()
    mx.random.seed(5)
    _ = mx.nd.random.uniform(shape=(3,))
    second2 = mx.nd.random.uniform(shape=(4, 4)).asnumpy()
    onp.testing.assert_array_equal(second, second2)


# ---------------------------------------------------------------------------
# shuffle (reference test_shuffle's small-permutation frequency check)
# ---------------------------------------------------------------------------

def test_shuffle_is_uniform_over_permutations():
    import itertools

    n_repeat = 1200
    counts = {p: 0 for p in itertools.permutations(range(3))}
    mx.random.seed(0)
    for _ in range(n_repeat):
        out = mx.nd.random.shuffle(nd.array([0.0, 1.0, 2.0])).asnumpy()
        counts[tuple(int(v) for v in out)] += 1
    # chi-square against uniform over the 6 permutations
    obs = onp.array(list(counts.values()), "float64")
    exp = onp.full(6, n_repeat / 6)
    stat = ((obs - exp) ** 2 / exp).sum()
    assert stat < sps.chi2.ppf(0.999, 5), counts


def test_shuffle_preserves_multiset():
    x = nd.array(onp.arange(10, dtype="float32"))
    out = mx.nd.random.shuffle(x).asnumpy()
    onp.testing.assert_array_equal(onp.sort(out), onp.arange(10))


def test_randint_extremes_and_dtype():
    out = mx.nd.random.randint(2 ** 30, 2 ** 30 + 2,
                               shape=(8,)).asnumpy()
    assert ((out >= 2 ** 30) & (out < 2 ** 30 + 2)).all()
