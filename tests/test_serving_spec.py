"""Speculative decoding + in-program stochastic sampling (ISSUE 19
tentpole, ``mxnet_tpu/serving_decode.py``).

Pins: (1) the in-program sampler — temperature / top-k / top-p ride
the ONE fixed-shape decode program as traced per-row operands, every
grid point seed-for-seed identical to the ``eager_generate`` oracle,
``temperature == 0`` bit-identical to the plain argmax, heterogeneous
configs sharing one program with 0 retraces; (2) the counter-based
PRNG — ``fold_in(PRNGKey(seed), position)`` makes replay positional,
so retries and cross-host dispatch are token-exact; (3) speculative
decoding (``MXNET_SPEC_DECODE``) — the high-agreement pair decodes
token-exact under greedy while committing k tokens per verify
dispatch, a low-agreement draft trips the sticky auto-disable and the
stream STAYS token-exact, and the knob off means ZERO spec dispatches
even with a draft attached; (4) the sampling spec over the
``serving_remote`` wire; and (5) the dispatch-budget spec lane + the
``spec_draft_poison`` chaos cell run end-to-end by the tool gates.
"""
import functools
import threading

import numpy as onp
import pytest

import mxnet_tpu as mx  # noqa: F401  (jax/backend init via conftest)
from mxnet_tpu import engine as _engine
from mxnet_tpu import serving_decode as sd


@functools.lru_cache(maxsize=None)
def _tiny_cached(seed):
    model = sd.TinyCausalLM(vocab=31, d_model=16, n_layers=2,
                            n_heads=2, max_seq=32)
    return model, model.init_params(seed)


@functools.lru_cache(maxsize=None)
def _pair_cached(seed=0):
    """Module-shared high-agreement (target, draft) fixture — same
    geometry as the plain-decode tests so warm programs are reused
    across the file."""
    return sd.high_agreement_pair(vocab=31, d_model=16,
                                  target_layers=2, draft_layers=1,
                                  n_heads=2, max_seq=32, seed=seed)


def _mk(model, params, pages=64, page=4, max_rows=4, warm=8,
        name="spec", **kw):
    pool = sd.PagePool(pages=pages, page=page)
    eng = sd.GenerativeEngine(model, params=params, pool=pool,
                              max_rows=max_rows, name=name, **kw)
    if warm:
        eng.warmup(max_len=warm)
    return eng, pool


# ---------------------------------------------------------------------------
# SamplingSpec surface
# ---------------------------------------------------------------------------
def test_sampling_spec_validation_and_wire_roundtrip():
    s = sd.SamplingSpec(temperature=0.8, top_k=5, top_p=0.9, seed=7)
    assert not s.greedy
    assert sd.SamplingSpec.from_wire(s.to_wire()) == s
    import json
    json.dumps(s.to_wire())                     # frame-protocol safe
    assert sd.GREEDY.greedy and sd.SamplingSpec().greedy
    with pytest.raises(ValueError):
        sd.SamplingSpec(temperature=-0.1)
    with pytest.raises(ValueError):
        sd.SamplingSpec(temperature=float("inf"))
    with pytest.raises(ValueError):
        sd.SamplingSpec(top_p=0.0)
    with pytest.raises(ValueError):
        sd.SamplingSpec(top_p=1.5)
    # seeds coerce into PRNGKey space identically everywhere
    assert sd.SamplingSpec(seed=-1).seed == sd.SamplingSpec(
        seed=-1).to_wire()["seed"]


def test_generate_rejects_non_spec_sampling():
    model, params = _tiny_cached(0)
    eng, pool = _mk(model, params, warm=0, name="val")
    with eng:
        with pytest.raises(TypeError):
            eng.generate([1, 2], max_new_tokens=2,
                         sampling={"temperature": 1.0})


# ---------------------------------------------------------------------------
# In-program sampling: compiled vs eager, seed-for-seed, every grid point
# ---------------------------------------------------------------------------
def test_sampled_decode_parity_grid_vs_eager_oracle():
    """The tentpole's layer-1 acceptance bar: for EVERY
    (temperature, top_k, top_p) grid point the batched engine's output
    is seed-for-seed identical to the eager oracle — same sampler, same
    counter-based keys, different program."""
    model, params = _tiny_cached(11)
    eng, pool = _mk(model, params, name="grid")
    grid = [(t, k, p) for t in (0.0, 0.8, 1.5)
            for k in (0, 4) for p in (1.0, 0.85)]
    prompt = [3, 5, 7]
    with eng:
        for i, (t, k, p) in enumerate(grid):
            samp = sd.SamplingSpec(temperature=t, top_k=k, top_p=p,
                                   seed=100 + i)
            got = eng.generate(prompt, max_new_tokens=4, sampling=samp)
            ref = sd.eager_generate(model, params, prompt, 4,
                                    sampling=samp)
            assert got == ref, (t, k, p)
    assert pool.in_use() == 0


def test_temperature_zero_is_bit_exact_greedy():
    """A greedy request through the sampling-capable program decodes
    exactly as before: sampling=None, an all-default SamplingSpec, and
    temperature-0 with active filters all land on the argmax chain."""
    model, params = _tiny_cached(12)
    eng, pool = _mk(model, params, name="t0")
    prompt = [9, 2, 4, 1]
    with eng:
        plain = eng.generate(prompt, max_new_tokens=5)
        for samp in (sd.GREEDY,
                     sd.SamplingSpec(temperature=0.0, top_k=3,
                                     top_p=0.5, seed=999)):
            assert eng.generate(prompt, max_new_tokens=5,
                                sampling=samp) == plain
    assert plain == sd.eager_generate(model, params, prompt, 5)


def test_sampling_positional_replay_and_seed_sensitivity():
    """Determinism is positional: the same (seed, prompt) replays the
    SAME tokens (the retry/failover/hedge story), while a different
    seed diverges (it is actually sampling)."""
    model, params = _tiny_cached(13)
    eng, pool = _mk(model, params, name="replay")
    prompt = [1, 2, 3]
    with eng:
        a = eng.generate(prompt, max_new_tokens=6,
                         sampling=sd.SamplingSpec(1.2, seed=5))
        b = eng.generate(prompt, max_new_tokens=6,
                         sampling=sd.SamplingSpec(1.2, seed=5))
        assert a == b
        outs = {tuple(eng.generate(prompt, max_new_tokens=6,
                                   sampling=sd.SamplingSpec(1.2,
                                                            seed=s)))
                for s in range(8)}
    assert len(outs) > 1                        # seeds matter


def test_mixed_sampling_configs_share_programs_zero_retraces():
    """Heterogeneous sampling configs ride ONE program set: after
    warm-up a concurrent mix of greedy and wildly different sampled
    requests adds 0 traces and 0 programs."""
    model, params = _tiny_cached(14)
    eng, pool = _mk(model, params, name="mix")
    grid = eng.stats()["programs"]
    t0 = sd.trace_count()
    samps = [None,
             sd.SamplingSpec(0.7, top_k=3, seed=1),
             sd.SamplingSpec(1.5, top_p=0.8, seed=2),
             sd.SamplingSpec(0.0),
             sd.SamplingSpec(2.0, top_k=9, top_p=0.6, seed=3)]
    res = [None] * len(samps)

    def fire(i):
        res[i] = eng.generate([4 + i, 5], max_new_tokens=4,
                              sampling=samps[i])

    threads = [threading.Thread(target=fire, args=(i,))
               for i in range(len(samps))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, samp in enumerate(samps):
        assert res[i] == sd.eager_generate(model, params, [4 + i, 5],
                                           4, sampling=samp), i
    assert sd.trace_count() - t0 == 0
    assert eng.stats()["programs"] == grid
    assert pool.in_use() == 0
    eng.close()


# ---------------------------------------------------------------------------
# The sampling spec over the serving_remote wire (satellite: router +
# remote protocol carry per-request sampling end-to-end)
# ---------------------------------------------------------------------------
def test_router_failover_replays_sampled_request_token_exact():
    """A failed-over SAMPLED request replays token-exact: the seed +
    committed positions ride the re-dispatch (like t_enqueue), and the
    counter-based PRNG makes the replica swap invisible — same tokens
    as the uninterrupted eager oracle."""
    from mxnet_tpu import faults
    from mxnet_tpu.serving_router import ReplicaRouter

    model, params = _tiny_cached(17)
    engines, pools = [], []
    for i in range(2):
        eng, pool = _mk(model, params, pages=32, page=4, max_rows=2,
                        name=f"fo{i}")
        engines.append(eng)
        pools.append(pool)
    router = ReplicaRouter(engines, breaker_errs=2,
                           breaker_cooldown_s=0.2)
    samp = sd.SamplingSpec(temperature=1.0, top_k=6, top_p=0.9,
                           seed=77)
    try:
        with faults.active(faults.FaultPlan().fail("router.dispatch",
                                                   times=1)):
            out = router.generate([2, 4, 6], max_new_tokens=5,
                                  sampling=samp)
        assert out == sd.eager_generate(model, params, [2, 4, 6], 5,
                                        sampling=samp)
    finally:
        for eng in engines:
            eng.close()
    _engine.waitall()
    assert all(p.in_use() == 0 for p in pools)


def test_remote_sampled_parity_seed_for_seed():
    from mxnet_tpu import serving_remote as srm

    model, params = _tiny_cached(15)
    eng, pool = _mk(model, params, max_rows=2, name="wire-s")
    srv = srm.ReplicaServer(eng).start()
    try:
        rr = srm.RemoteReplica("127.0.0.1", srv.port)
        samp = sd.SamplingSpec(temperature=0.9, top_k=5, top_p=0.9,
                               seed=42)
        out = rr.generate([4, 5, 6], max_new_tokens=5, sampling=samp)
        assert out == sd.eager_generate(model, params, [4, 5, 6], 5,
                                        sampling=samp)
        # greedy default unchanged: no sampling field → argmax chain
        assert rr.generate([4, 5, 6], max_new_tokens=3) == \
            sd.eager_generate(model, params, [4, 5, 6], 3)
    finally:
        srv.close()
    _engine.waitall()
    assert pool.in_use() == 0


# ---------------------------------------------------------------------------
# Speculative decoding (MXNET_SPEC_DECODE)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def spec_engine():
    """ONE warmed high-agreement spec engine shared by the knob-off /
    greedy / sampled pins below (tier-1 wall guard: the spec program
    grid traces once, not once per test).  The knob is read per
    REQUEST, so tests flip MXNET_SPEC_DECODE around individual
    generate() calls."""
    target, tp, draft, dp = _pair_cached()
    eng, pool = _mk(target, tp, name="spec-hi", draft=draft,
                    draft_params=dp, spec_k=4)
    yield eng, pool, target, tp
    eng.close()


def test_spec_off_by_default_zero_spec_dispatches(spec_engine,
                                                  monkeypatch):
    """A draft attached but the knob unset means plain decode at serve
    time: warmup still pre-compiles the spec grid (so a later knob
    flip is free), but ZERO spec traces/dispatches happen for real
    traffic and the tokens are identical to the draftless chain."""
    monkeypatch.delenv("MXNET_SPEC_DECODE", raising=False)
    eng, pool, target, tp = spec_engine
    st0, sd0 = sd.spec_trace_count(), sd.spec_dispatch_count()
    rounds0 = eng.stats()["spec_rounds"]
    out = eng.generate([2, 7, 1], max_new_tokens=5)
    assert out == sd.eager_generate(target, tp, [2, 7, 1], 5)
    assert eng.stats()["spec_rounds"] == rounds0
    assert sd.spec_trace_count() - st0 == 0      # post-warmup serve path
    assert sd.spec_dispatch_count() - sd0 == 0
    assert pool.in_use() == 0


def test_spec_greedy_token_exact_high_agreement(spec_engine,
                                                monkeypatch):
    """The tentpole's layer-2 acceptance bar: with the knob on and the
    agreeing draft, greedy decode is token-exact vs the target-only
    oracle while speculation actually runs — rounds > 0, acceptance
    1.0 by construction, multiple tokens per verify dispatch."""
    monkeypatch.setenv("MXNET_SPEC_DECODE", "1")
    eng, pool, target, tp = spec_engine
    prompts = [[3, 5, 7], [1], [8, 2, 9, 4]]
    budgets = [8, 6, 7]
    res = [None] * 3

    def fire(i):
        res[i] = eng.generate(prompts[i], max_new_tokens=budgets[i])

    threads = [threading.Thread(target=fire, args=(i,))
               for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(3):
        assert res[i] == sd.eager_generate(target, tp, prompts[i],
                                           budgets[i]), f"request {i}"
    st = eng.stats()
    assert st["spec_rounds"] > 0 and not st["spec_disabled"]
    assert st["spec_accepted"] == st["spec_proposed"]    # 1.0
    # the k-for-1 economics: committed tokens per verify dispatch > 1
    assert st["spec_accepted"] > 0
    assert st["spec_programs"] > 0
    assert pool.in_use() == 0                            # BOTH geometries


def test_spec_sampled_lane_runs_and_temp_zero_stays_exact(spec_engine,
                                                          monkeypatch):
    """Sampling through the spec lane: a temperature-0 SamplingSpec
    (with active filters) rides the rejection-sampling verify programs
    and STAYS bit-exact with the plain greedy chain — the 0-branch
    degenerates to the argmax accept test — while a hot-temperature
    spec actually speculates and emits in-vocab tokens.  (Stochastic
    outputs are distributionally the target's, not positionally
    replayable: which positions land as proposal / resample / bonus
    depends on the cost-table arbitration, so only greedy pins
    token-for-token.)"""
    monkeypatch.setenv("MXNET_SPEC_DECODE", "1")
    eng, pool, target, tp = spec_engine
    g0 = eng.generate([6, 3], max_new_tokens=6,
                      sampling=sd.SamplingSpec(temperature=0.0,
                                               top_k=5, top_p=0.7,
                                               seed=31))
    assert g0 == sd.eager_generate(target, tp, [6, 3], 6)
    hot = eng.generate([6, 3], max_new_tokens=6,
                       sampling=sd.SamplingSpec(temperature=1.1,
                                                top_k=7, top_p=0.95,
                                                seed=31))
    assert len(hot) == 6 and all(0 <= t < 31 for t in hot)
    assert eng.stats()["spec_rounds"] > 0
    assert pool.in_use() == 0


def test_spec_low_agreement_auto_disables_stream_stays_exact(
        monkeypatch):
    """The degrade path: an independent (disagreeing) draft trips the
    sticky low-acceptance cutoff after the probation rounds — the
    spec.autodisabled counter ticks, the engine falls back to plain
    decode IN-PLACE, and the greedy stream was token-exact the whole
    time (rejection sampling never commits a wrong token)."""
    monkeypatch.setenv("MXNET_SPEC_DECODE", "1")
    target, tp = _tiny_cached(16)
    low = sd.TinyCausalLM(vocab=31, d_model=16, n_layers=1, n_heads=2,
                          max_seq=32)
    lp = low.init_params(77)
    before = sd._SPEC_STATS["autodisabled"]
    eng, pool = _mk(target, tp, name="spec-lo", draft=low,
                    draft_params=lp, spec_k=4)
    with eng:
        out = eng.generate([5, 1, 3], max_new_tokens=12)
    assert out == sd.eager_generate(target, tp, [5, 1, 3], 12)
    st = eng.stats()
    assert st["spec_disabled"] is True
    assert st["spec_rounds"] >= 4                # probation ran
    assert st["spec_accepted"] < st["spec_proposed"]
    assert sd._SPEC_STATS["autodisabled"] == before + 1
    assert pool.in_use() == 0


def test_spec_requires_decode_chunk_and_matching_vocab():
    target, tp, draft, dp = _pair_cached()
    pool = sd.PagePool(pages=8, page=4)
    other = sd.TinyCausalLM(vocab=13, d_model=16, n_layers=1,
                            n_heads=2, max_seq=32)
    with pytest.raises(ValueError):
        sd.GenerativeEngine(target, params=tp, pool=pool, name="v",
                            draft=other, draft_params=other.init_params())


# ---------------------------------------------------------------------------
# Tool-gate lanes (the full gates run as slow subprocess tests)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_dispatch_budget_spec_lane_in_process():
    """The CI gate's spec lane: bounded program set over BOTH
    namespaces, 0 retraces across mixed sampled/greedy traffic,
    target dispatches amortized below 1/token, greedy rows token-exact,
    and the knob-off leg byte-identical to a draftless engine."""
    import importlib.util
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_dispatch_budget",
        os.path.join(root, "tools", "check_dispatch_budget.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    d = mod._measure_spec()
    assert not d["errors"]
    for key, budget in mod.SPEC_BUDGET.items():
        assert d[key] <= budget, (key, d)
    assert d["spec_rounds"] > 0 and not d["spec_disabled"]
    assert d["acceptance"] >= 0.7
    assert d["target_dispatches_per_token"] < 1.0
    assert d["greedy_token_exact"]
    assert d["greedy_off_outputs_equal"]


@pytest.mark.slow
def test_availability_gate_spec_draft_poison_scenario():
    """The chaos cell end-to-end as a real subprocess drill: a draft
    poisoned mid-round auto-disables speculation on BOTH replicas with
    0 dropped requests, token-exact streams, and a clean page audit."""
    import tools.check_availability_budget as gate

    assert gate.main(["spec_draft_poison"]) == 0
