"""End-to-end convergence test — mirrors the reference's
``tests/python/train/test_autograd.py``: MNISTIter over idx-format files,
multi-context train loop with ``gluon.utils.split_and_load``, accuracy
scoring, and a save/load resume check."""
import os
import struct

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def _write_idx_images(path, arr):
    """Pack uint8 images in MNIST idx3 format."""
    arr = arr.astype(onp.uint8)
    with open(path, "wb") as f:
        f.write(struct.pack(">IIII", 0x00000803, *arr.shape))
        f.write(arr.tobytes())


def _write_idx_labels(path, arr):
    with open(path, "wb") as f:
        f.write(struct.pack(">II", 0x00000801, arr.shape[0]))
        f.write(arr.astype(onp.uint8).tobytes())


@pytest.fixture(scope="module")
def mnist_files(tmp_path_factory):
    """Synthetic separable digits in REAL idx files (exercises the
    iter_mnist.cc-analog reader)."""
    root = tmp_path_factory.mktemp("mnist")
    rng = onp.random.RandomState(0)

    def make(n, seed):
        r = onp.random.RandomState(seed)
        y = r.randint(0, 10, size=n)
        x = r.uniform(0, 30, size=(n, 28, 28))
        for i, k in enumerate(y):
            rr, cc = divmod(int(k), 4)
            x[i, 7 * rr:7 * rr + 7, 7 * cc:7 * cc + 7] += 200
        return x, y

    xtr, ytr = make(1200, 1)
    xte, yte = make(400, 2)
    paths = {k: str(root / k) for k in
             ("train-img", "train-lbl", "val-img", "val-lbl")}
    _write_idx_images(paths["train-img"], xtr)
    _write_idx_labels(paths["train-lbl"], ytr)
    _write_idx_images(paths["val-img"], xte)
    _write_idx_labels(paths["val-lbl"], yte)
    return paths


def _get_net():
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(10))
    return net


def _score(net, val_data, ctx_list):
    metric = mx.metric.Accuracy()
    val_data.reset()
    for batch in val_data:
        datas = gluon.utils.split_and_load(batch.data[0], ctx_list)
        labels = gluon.utils.split_and_load(batch.label[0], ctx_list)
        metric.update(labels, [net(x) for x in datas])
    return metric.get()[1]


@pytest.mark.slow
def test_train_autograd_end_to_end(mnist_files, tmp_path):
    train_data = mx.io.MNISTIter(image=mnist_files["train-img"],
                                 label=mnist_files["train-lbl"],
                                 data_shape=(784,), batch_size=100,
                                 shuffle=True, flat=True, seed=10)
    val_data = mx.io.MNISTIter(image=mnist_files["val-img"],
                               label=mnist_files["val-lbl"],
                               data_shape=(784,), batch_size=100,
                               shuffle=False, flat=True)
    ctx_list = [mx.cpu(0), mx.cpu(0)]

    net = _get_net()
    net.initialize(mx.init.Xavier(magnitude=2.24))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for _epoch in range(3):
        train_data.reset()
        for batch in train_data:
            datas = gluon.utils.split_and_load(batch.data[0], ctx_list)
            labels = gluon.utils.split_and_load(batch.label[0], ctx_list)
            with autograd.record():
                losses = [loss_fn(net(x), y)
                          for x, y in zip(datas, labels)]
            for loss in losses:
                loss.backward()
            trainer.step(batch.data[0].shape[0])

    acc = _score(net, val_data, ctx_list)
    assert acc > 0.90, f"end-to-end training failed to converge: {acc}"

    # save -> fresh net -> load -> identical score (resume contract)
    path = str(tmp_path / "e2e.params")
    net.save_parameters(path)
    net2 = _get_net()
    net2.initialize()
    net2(mx.nd.zeros((1, 784)))          # materialize shapes
    net2.load_parameters(path)
    assert abs(_score(net2, val_data, ctx_list) - acc) < 1e-6
