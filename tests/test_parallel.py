"""Parallelism tests on the virtual 8-device CPU mesh.

Mirrors the reference's dist test strategy (tests/nightly/dist_sync_kvstore.py
run with the local launcher — SURVEY.md §4): numerical equality of the
distributed result against a single-device oracle.
"""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel as par
from jax.sharding import PartitionSpec as P


def test_make_mesh_axis_order():
    mesh = par.make_mesh({"tp": 2, "dp": 4})
    assert mesh.axis_names == ("dp", "tp")
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2


def test_auto_mesh_fills_dp():
    mesh = par.auto_mesh(8, tp=2)
    assert mesh.shape["dp"] == 4


def test_sharding_plan_legalize():
    mesh = par.make_mesh({"dp": 2, "tp": 4})
    plan = par.ShardingPlan([(r"weight$", P("tp", None))])
    # 8 divisible by 4 -> sharded
    assert plan.spec_for("dense0.weight", (8, 16), mesh) == P("tp")
    # 6 not divisible by 4 -> replicated fallback
    assert plan.spec_for("dense0.weight", (6, 16), mesh) == P()
    # non-matching name -> default replicated
    assert plan.spec_for("dense0.bias", (8,), mesh) == P()


def test_fsdp_plan_shards_largest_dim():
    mesh = par.make_mesh({"fsdp": 8})
    plan = par.fsdp_plan(min_size=64)
    assert plan.spec_for("w", (16, 24), mesh) == P(None, "fsdp")
    assert plan.spec_for("tiny", (4,), mesh) == P()


def test_collectives_all_reduce_matches_sum():
    mesh = par.make_mesh({"dp": 8})
    x = jnp.arange(16.0).reshape(8, 2)

    def f(xs):
        return par.all_reduce(jnp.sum(xs), "dp")

    out = par.run_sharded(f, mesh, in_specs=(P("dp", None),), out_specs=P())(x)
    assert float(out) == float(jnp.sum(x))


def test_ring_shift_rotates():
    mesh = par.make_mesh({"sp": 8})
    x = jnp.arange(8.0)

    def f(xs):
        return par.ring_shift(xs, "sp", shift=1)

    out = par.run_sharded(f, mesh, in_specs=(P("sp"),), out_specs=P("sp"))(x)
    # shift=1 sends each shard to the next device: device j receives j-1's
    assert onp.allclose(onp.asarray(out), onp.roll(onp.arange(8.0), 1))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    B, H, S, D = 2, 4, 64, 16
    rng = onp.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, S, D), dtype=jnp.float32)
    k = jnp.asarray(rng.randn(B, H, S, D), dtype=jnp.float32)
    v = jnp.asarray(rng.randn(B, H, S, D), dtype=jnp.float32)

    scale = 1.0 / onp.sqrt(D)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        mask = onp.tril(onp.ones((S, S), dtype=bool))
        s = jnp.where(jnp.asarray(mask)[None, None], s, -jnp.inf)
    expected = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)

    mesh = par.make_mesh({"sp": 8})
    out = par.ring_attention_sharded(q, k, v, mesh, causal=causal,
                                     batch_axes=())
    assert onp.allclose(onp.asarray(out), onp.asarray(expected), atol=1e-4)


def test_moe_layer_shapes_and_routing():
    G, S, M, E, Hd = 2, 16, 8, 4, 32
    rng = onp.random.RandomState(1)
    x = jnp.asarray(rng.randn(G, S, M), dtype=jnp.float32)
    gate_w = jnp.asarray(rng.randn(M, E) * 0.1, dtype=jnp.float32)
    w_in = jnp.asarray(rng.randn(E, M, Hd) * 0.1, dtype=jnp.float32)
    w_out = jnp.asarray(rng.randn(E, Hd, M) * 0.1, dtype=jnp.float32)
    out, aux = par.moe_layer(x, gate_w, w_in, w_out, k=2,
                             capacity_factor=2.0)
    assert out.shape == (G, S, M)
    assert float(aux) > 0
    assert onp.isfinite(onp.asarray(out)).all()


def test_moe_single_expert_equals_dense_ffn():
    # with E=1, k=1, ample capacity the MoE must equal the plain FFN
    G, S, M, Hd = 1, 8, 4, 16
    rng = onp.random.RandomState(2)
    x = jnp.asarray(rng.randn(G, S, M), dtype=jnp.float32)
    gate_w = jnp.zeros((M, 1), dtype=jnp.float32)
    w_in = jnp.asarray(rng.randn(1, M, Hd) * 0.3, dtype=jnp.float32)
    w_out = jnp.asarray(rng.randn(1, Hd, M) * 0.3, dtype=jnp.float32)
    out, _ = par.moe_layer(x, gate_w, w_in, w_out, k=1, capacity_factor=1.0,
                           capacity=None)
    expected = jax.nn.gelu(x @ w_in[0]) @ w_out[0]
    assert onp.allclose(onp.asarray(out), onp.asarray(expected), atol=1e-5)


def test_pipeline_matches_sequential():
    n_stage, B, Dm = 8, 16, 8
    rng = onp.random.RandomState(3)
    ws = [jnp.asarray(rng.randn(Dm, Dm) * 0.2, dtype=jnp.float32)
          for _ in range(n_stage)]
    x = jnp.asarray(rng.randn(B, Dm), dtype=jnp.float32)

    def stage(params, a):
        return jnp.tanh(a @ params["w"])

    expected = x
    for w in ws:
        expected = jnp.tanh(expected @ w)

    mesh = par.make_mesh({"pp": 8})
    stacked = par.stack_stage_params([{"w": w} for w in ws])
    fn = par.pipelined(stage, mesh, num_microbatches=4, axis_name="pp",
                       param_spec={"w": P("pp", None, None)}, x_spec=P())
    out = fn(stacked, x)
    assert onp.allclose(onp.asarray(out), onp.asarray(expected), atol=1e-5)


def test_hetero_pipeline_matches_sequential():
    """Non-shape-preserving heterogeneous stages (4 -> 16 -> 8 widths)
    through pp=2 x dp=4 must match the sequential program."""
    B = 16
    rng = onp.random.RandomState(7)
    w0 = jnp.asarray(rng.randn(4, 16) * 0.3, jnp.float32)
    b0 = jnp.asarray(rng.randn(16) * 0.1, jnp.float32)
    w1 = jnp.asarray(rng.randn(16, 8) * 0.3, jnp.float32)
    x = jnp.asarray(rng.randn(B, 4), jnp.float32)

    def stage0(p, a):
        return jax.nn.relu(a @ p["w"] + p["b"])

    def stage1(p, a):
        return a @ p["w"]

    expected = stage1({"w": w1}, stage0({"w": w0, "b": b0}, x))

    mesh = par.make_mesh({"pp": 2, "dp": 4})
    pipe = par.HeteroPipeline(
        [stage0, stage1], [{"w": w0, "b": b0}, {"w": w1}], mesh,
        num_microbatches=2, example_x=x)
    out = pipe.apply(pipe.packed_params, x)
    assert out.shape == (B, 8)
    assert onp.allclose(onp.asarray(out), onp.asarray(expected), atol=1e-5)

    # params round-trip through the packed buffer exactly
    sp0, sp1 = pipe.unpack_stage_params()
    assert onp.allclose(onp.asarray(sp0["w"]), onp.asarray(w0))
    assert onp.allclose(onp.asarray(sp1["w"]), onp.asarray(w1))


@pytest.mark.slow   # ISSUE-20 wall: remat + 4-microbatch compile
def test_hetero_pipeline_grads_match_sequential():
    """Microbatch gradient accumulation through the pp scan equals the
    unpipelined gradient."""
    B = 8
    rng = onp.random.RandomState(8)
    w0 = jnp.asarray(rng.randn(6, 12) * 0.3, jnp.float32)
    w1 = jnp.asarray(rng.randn(12, 3) * 0.3, jnp.float32)
    x = jnp.asarray(rng.randn(B, 6), jnp.float32)
    y = jnp.asarray(rng.randn(B, 3), jnp.float32)

    def stage0(p, a):
        return jnp.tanh(a @ p["w"])

    def stage1(p, a):
        return a @ p["w"]

    def seq_loss(ws):
        out = stage1({"w": ws[1]}, stage0({"w": ws[0]}, x))
        return jnp.mean((out - y) ** 2)

    g_seq = jax.grad(seq_loss)((w0, w1))

    mesh = par.make_mesh({"pp": 2, "dp": 2})
    pipe = par.HeteroPipeline(
        [stage0, stage1], [{"w": w0}, {"w": w1}], mesh,
        num_microbatches=4, example_x=x, remat=True)

    def pp_loss(packed):
        out = pipe.apply(packed, x)
        return jnp.mean((out - y) ** 2)

    g_packed = jax.grad(pp_loss)(pipe.packed_params)
    g0, g1 = pipe.unpack_stage_params(g_packed)
    assert onp.allclose(onp.asarray(g0["w"]), onp.asarray(g_seq[0]),
                        atol=1e-5)
    assert onp.allclose(onp.asarray(g1["w"]), onp.asarray(g_seq[1]),
                        atol=1e-5)


def test_hetero_pipeline_grads_smoke():
    """Tier-1 smoke for the slow remat variant above: same pack/scan/
    grad path, 2 microbatches, no remat."""
    B = 4
    rng = onp.random.RandomState(8)
    w0 = jnp.asarray(rng.randn(4, 6) * 0.3, jnp.float32)
    w1 = jnp.asarray(rng.randn(6, 2) * 0.3, jnp.float32)
    x = jnp.asarray(rng.randn(B, 4), jnp.float32)
    y = jnp.asarray(rng.randn(B, 2), jnp.float32)

    def stage0(p, a):
        return jnp.tanh(a @ p["w"])

    def stage1(p, a):
        return a @ p["w"]

    def seq_loss(ws):
        out = stage1({"w": ws[1]}, stage0({"w": ws[0]}, x))
        return jnp.mean((out - y) ** 2)

    g_seq = jax.grad(seq_loss)((w0, w1))
    mesh = par.make_mesh({"pp": 2, "dp": 2})
    pipe = par.HeteroPipeline(
        [stage0, stage1], [{"w": w0}, {"w": w1}], mesh,
        num_microbatches=2, example_x=x, remat=False)

    def pp_loss(packed):
        out = pipe.apply(packed, x)
        return jnp.mean((out - y) ** 2)

    g0, g1 = pipe.unpack_stage_params(jax.grad(pp_loss)(pipe.packed_params))
    assert onp.allclose(onp.asarray(g0["w"]), onp.asarray(g_seq[0]),
                        atol=1e-5)
    assert onp.allclose(onp.asarray(g1["w"]), onp.asarray(g_seq[1]),
                        atol=1e-5)


def _pp_transformer_setup():
    from mxnet_tpu import models

    cfg = models.TransformerLMConfig(
        vocab_size=64, num_layers=2, num_heads=2, hidden=16, mlp_hidden=32,
        max_len=16, dtype=jnp.float32)
    params = models.init_params(jax.random.PRNGKey(0), cfg)
    rng = onp.random.RandomState(0)
    B, S = 8, 16
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    labels_np = rng.randint(0, cfg.vocab_size, (B, S))
    labels_np[rng.rand(B, S) < 0.5] = -1       # mask half the positions
    labels = jnp.asarray(labels_np, jnp.int32)
    return models, cfg, params, tokens, labels


def test_pp_transformer_loss_smoke():
    """Tier-1 smoke for the flagship pp TransformerLM: the pipelined
    loss matches the unpipelined model (forward compile only; the
    grad-equality + train-step oracle rides the slow lane)."""
    models, cfg, params, tokens, labels = _pp_transformer_setup()
    ref_loss = float(models.loss_fn(params, tokens, labels, cfg))
    mesh = par.make_mesh({"pp": 2, "dp": 2})
    pipe = models.make_pp_pipeline(cfg, params, mesh, num_microbatches=2,
                                   example_tokens=tokens)
    pp_loss = float(models.pp_loss_fn(pipe, pipe.packed_params, tokens,
                                      labels))
    assert abs(pp_loss - ref_loss) < 1e-4, (pp_loss, ref_loss)


@pytest.mark.slow
def test_pp_transformer_loss_matches_unpipelined():
    """Flagship TransformerLM through HeteroPipeline pp=2: loss and grads
    match the unpipelined model (VERDICT round-1 item 3).  ~35s of
    grad/train-step compiles, so slow-marked; tier-1 keeps the
    loss-equality smoke above (ISSUE-17 wall slice 2)."""
    from mxnet_tpu import models

    cfg = models.TransformerLMConfig(
        vocab_size=64, num_layers=2, num_heads=2, hidden=16, mlp_hidden=32,
        max_len=16, dtype=jnp.float32)
    params = models.init_params(jax.random.PRNGKey(0), cfg)
    rng = onp.random.RandomState(0)
    B, S = 8, 16
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32)
    labels_np = rng.randint(0, cfg.vocab_size, (B, S))
    labels_np[rng.rand(B, S) < 0.5] = -1       # mask half the positions
    labels = jnp.asarray(labels_np, jnp.int32)

    ref_loss = float(models.loss_fn(params, tokens, labels, cfg))

    mesh = par.make_mesh({"pp": 2, "dp": 2})
    pipe = models.make_pp_pipeline(cfg, params, mesh, num_microbatches=2,
                                   example_tokens=tokens)
    pp_loss = float(models.pp_loss_fn(pipe, pipe.packed_params, tokens,
                                      labels))
    assert abs(pp_loss - ref_loss) < 1e-4, (pp_loss, ref_loss)

    # gradient equality: per-layer params match; tied embed grad equals
    # stage-0 embed grad + last-stage head grad
    g_ref = jax.grad(
        lambda p: models.loss_fn(p, tokens, labels, cfg))(params)
    g_packed = jax.grad(
        lambda pk: models.pp_loss_fn(pipe, pk, tokens, labels))(
        pipe.packed_params)
    g0, g1 = pipe.unpack_stage_params(g_packed)
    assert onp.allclose(onp.asarray(g0["layer0.attn.qkv.weight"]),
                        onp.asarray(g_ref["layer0.attn.qkv.weight"]),
                        atol=1e-4)
    assert onp.allclose(onp.asarray(g1["layer1.ffn_2.weight"]),
                        onp.asarray(g_ref["layer1.ffn_2.weight"]),
                        atol=1e-4)
    tied = onp.asarray(g0["embed.weight"]) + onp.asarray(g1["head.weight"])
    assert onp.allclose(tied, onp.asarray(g_ref["embed.weight"]), atol=1e-4)

    # one pp train step runs and the loss is finite
    step = models.make_pp_train_step(pipe, optimizer="adam", lr=1e-3)
    m = jnp.zeros_like(pipe.packed_params)
    v = jnp.zeros_like(pipe.packed_params)
    before = onp.asarray(jax.device_get(pipe.packed_params)).copy()
    new_packed, m, v, loss = step(pipe.packed_params, m, v, tokens, labels,
                                  jnp.float32(1))
    assert onp.isfinite(float(loss))
    assert not onp.allclose(onp.asarray(new_packed), before)

    # tied embed/head copies stay exactly tied after the update (grads are
    # summed across stages before the optimizer step)
    n0, n1 = pipe.unpack_stage_params(new_packed)
    assert onp.allclose(onp.asarray(n0["embed.weight"]),
                        onp.asarray(n1["head.weight"]))
    # the update actually incorporated the tied (summed) gradient
    assert not onp.allclose(onp.asarray(n0["embed.weight"]),
                            onp.asarray(params["embed.weight"]))


def test_sharded_trainer_data_parallel_matches_single():
    from mxnet_tpu.gluon import nn

    def build():
        net = nn.Dense(4, in_units=8)
        net.initialize(mx.init.Constant(0.05))
        return net

    def loss_fn(out, label):
        diff = out - label
        return (diff * diff).mean()

    rng = onp.random.RandomState(4)
    data = rng.randn(16, 8).astype(onp.float32)
    label = rng.randn(16, 4).astype(onp.float32)

    # single-device oracle (dp=1 mesh)
    net1 = build()
    mesh1 = par.make_mesh({"dp": 1})
    tr1 = par.ShardedTrainer(net1, loss_fn, mesh1, optimizer="sgd",
                             optimizer_params={"lr": 0.1, "momentum": 0.9})
    # dp=8
    net8 = build()
    mesh8 = par.make_mesh({"dp": 8})
    tr8 = par.ShardedTrainer(net8, loss_fn, mesh8, optimizer="sgd",
                             optimizer_params={"lr": 0.1, "momentum": 0.9})

    for _ in range(3):
        l1 = tr1.step(data, label)
        l8 = tr8.step(data, label)
        assert abs(l1 - l8) < 1e-4
    w1 = onp.asarray(tr1.params["weight"])
    w8 = onp.asarray(tr8.params["weight"])
    assert onp.allclose(w1, w8, atol=1e-5)


def test_sharded_trainer_fsdp_tp():
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(16, in_units=8, activation="relu"))
    net.add(nn.Dense(8, in_units=16))
    net.initialize(mx.init.Xavier())

    def loss_fn(out, label):
        diff = out - label
        return (diff * diff).mean()

    mesh = par.make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    plan = par.fsdp_plan()
    tr = par.ShardedTrainer(net, loss_fn, mesh, plan=plan, optimizer="adam",
                            optimizer_params={"lr": 1e-2})
    rng = onp.random.RandomState(5)
    data = rng.randn(8, 8).astype(onp.float32)
    label = rng.randn(8, 8).astype(onp.float32)
    losses = [tr.step(data, label) for _ in range(4)]
    assert losses[-1] < losses[0]
    tr.sync_to_block()


def test_sharded_trainer_bf16_compute_fp32_master():
    """Mixed precision: compute_dtype=bfloat16 runs fwd/bwd in bf16 (the
    MXU-native path) while params + optimizer state stay fp32 master
    copies; training still converges and tracks the fp32 run loosely."""
    import jax.numpy as jnp

    from mxnet_tpu.gluon import nn

    def build():
        net = nn.HybridSequential()
        net.add(nn.Dense(32, in_units=8, activation="relu"),
                nn.Dense(4, in_units=32))
        net.initialize(mx.init.Constant(0.05))
        return net

    def loss_fn(out, label):
        diff = out - label
        return (diff * diff).mean()

    rng = onp.random.RandomState(5)
    data = rng.randn(16, 8).astype(onp.float32)
    label = rng.randn(16, 4).astype(onp.float32)

    mesh = par.make_mesh({"dp": 1})
    tr32 = par.ShardedTrainer(build(), loss_fn, mesh, optimizer="sgd",
                              optimizer_params={"lr": 0.05})
    trbf = par.ShardedTrainer(build(), loss_fn, mesh, optimizer="sgd",
                              optimizer_params={"lr": 0.05},
                              compute_dtype=jnp.bfloat16)
    l32 = [float(tr32.step(data, label)) for _ in range(6)]
    lbf = [float(trbf.step(data, label)) for _ in range(6)]
    assert lbf[-1] < lbf[0]
    # bf16 tracks fp32 within bf16 resolution-scale error
    assert abs(lbf[-1] - l32[-1]) < 0.1 * max(abs(l32[0]), 1.0)
    # master state stayed fp32
    assert all(v.dtype == jnp.float32 for v in trbf.params.values())
    for st in trbf.opt_state.values():
        assert all(s.dtype == jnp.float32 for s in st)


def test_sharded_trainer_bf16_grad_accum_with_batchnorm():
    """compute_dtype + grad_accum must agree on scan-carry dtypes even
    when BatchNorm running stats (fp32 masters) chain through the bf16
    micro-batch bodies."""
    import jax.numpy as jnp

    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(16, in_units=8), nn.BatchNorm(in_channels=16),
            nn.Activation("relu"), nn.Dense(4, in_units=16))
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((2, 8)))

    def loss_fn(out, label):
        diff = out - label
        return (diff * diff).mean()

    rng = onp.random.RandomState(9)
    data = rng.randn(16, 8).astype(onp.float32)
    label = rng.randn(16, 4).astype(onp.float32)
    mesh = par.make_mesh({"dp": 1})
    tr = par.ShardedTrainer(net, loss_fn, mesh, optimizer="sgd",
                            optimizer_params={"lr": 0.05},
                            grad_accum=2, compute_dtype=jnp.bfloat16)
    losses = [float(tr.step(data, label)) for _ in range(5)]
    assert losses[-1] < losses[0]
    assert all(v.dtype == jnp.float32 for v in tr.params.values())


def _adam_ref_loop(cfg, params, batches, lr=1e-3, beta1=0.9, beta2=0.999,
                   epsilon=1e-8):
    """Unpipelined oracle: loss_fn + tree-space adam matching
    make_pp_train_step's packed-space update (wd=0)."""
    from mxnet_tpu import models

    tmap = jax.tree_util.tree_map
    m = tmap(lambda w: jnp.zeros_like(w), params)
    v = tmap(lambda w: jnp.zeros_like(w), params)
    losses = []
    for t, (tokens, labels) in enumerate(batches, start=1):
        loss, g = jax.value_and_grad(
            lambda p: models.loss_fn(p, tokens, labels, cfg))(params)
        m = tmap(lambda a, b: beta1 * a + (1 - beta1) * b, m, g)
        v = tmap(lambda a, b: beta2 * a + (1 - beta2) * jnp.square(b), v, g)
        lr_t = lr * onp.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
        params = tmap(
            lambda w, a, b: w - lr_t * a / (jnp.sqrt(b) + epsilon),
            params, m, v)
        losses.append(float(loss))
    return params, losses


@pytest.mark.slow
def test_pp_multistep_convergence_matches_unpipelined():
    """VERDICT r3 item 9: ≥10 steps of pp training track the unpipelined
    loss curve — schedule bugs (stale activations, microbatch skew,
    mis-summed tied grads) compound over steps and would diverge."""
    from mxnet_tpu import models

    cfg = models.TransformerLMConfig(
        vocab_size=64, num_layers=2, num_heads=2, hidden=16, mlp_hidden=32,
        max_len=16, dtype=jnp.float32)
    params = models.init_params(jax.random.PRNGKey(0), cfg)
    rng = onp.random.RandomState(3)
    B, S, steps = 8, 16, 10
    batches = []
    for _ in range(steps):
        tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S)),
                             jnp.int32)
        labels_np = rng.randint(0, cfg.vocab_size, (B, S))
        labels_np[rng.rand(B, S) < 0.5] = -1
        batches.append((tokens, jnp.asarray(labels_np, jnp.int32)))

    _, ref_losses = _adam_ref_loop(cfg, params, batches)

    mesh = par.make_mesh({"pp": 2, "dp": 2})
    pipe = models.make_pp_pipeline(cfg, params, mesh, num_microbatches=2,
                                   example_tokens=batches[0][0])
    step = models.make_pp_train_step(pipe, optimizer="adam", lr=1e-3)
    packed = pipe.packed_params
    m = jnp.zeros_like(packed)
    v = jnp.zeros_like(packed)
    pp_losses = []
    for t, (tokens, labels) in enumerate(batches, start=1):
        packed, m, v, loss = step(packed, m, v, tokens, labels,
                                  jnp.float32(t))
        pp_losses.append(float(loss))
    # per-step equality with the oracle is the assertion: any schedule bug
    # compounds into divergence within a few steps (each step uses fresh
    # random batches, so the curve itself need not be monotone)
    onp.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-4, atol=2e-4)


def test_pp_ragged_batch_pad_smoke():
    """Tier-1 smoke for ragged pp batches: pp_pad_batch pads rows with
    label=-1 and the global-valid-count normalization makes the padded
    pipeline's LOSS exactly the unpadded batch's (the grad oracle rides
    the slow lane)."""
    from mxnet_tpu import models

    cfg = models.TransformerLMConfig(
        vocab_size=64, num_layers=2, num_heads=2, hidden=16, mlp_hidden=32,
        max_len=16, dtype=jnp.float32)
    params = models.init_params(jax.random.PRNGKey(0), cfg)
    rng = onp.random.RandomState(4)
    B_ragged, S = 6, 16
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (B_ragged, S)),
                         jnp.int32)
    labels_np = rng.randint(0, cfg.vocab_size, (B_ragged, S))
    labels_np[rng.rand(B_ragged, S) < 0.5] = -1
    labels = jnp.asarray(labels_np, jnp.int32)
    ref_loss = float(models.loss_fn(params, tokens, labels, cfg))
    mesh = par.make_mesh({"pp": 2, "dp": 2})
    ptokens, plabels = models.pp_pad_batch(tokens, labels, 4)
    assert ptokens.shape[0] == 8
    pipe = models.make_pp_pipeline(cfg, params, mesh, num_microbatches=2,
                                   example_tokens=ptokens)
    pp_loss = float(models.pp_loss_fn(pipe, pipe.packed_params, ptokens,
                                      plabels))
    assert abs(pp_loss - ref_loss) < 1e-4, (pp_loss, ref_loss)


@pytest.mark.slow
def test_pp_ragged_batch_pad_and_mask():
    """dp x pp with a ragged batch: pp_pad_batch pads rows with label=-1;
    global-valid-count normalization makes loss/grads EXACTLY the
    unpadded batch's.  Slow-marked for the grad compile; tier-1 keeps
    the loss-equality smoke above (ISSUE-17 wall slice 2)."""
    from mxnet_tpu import models

    cfg = models.TransformerLMConfig(
        vocab_size=64, num_layers=2, num_heads=2, hidden=16, mlp_hidden=32,
        max_len=16, dtype=jnp.float32)
    params = models.init_params(jax.random.PRNGKey(0), cfg)
    rng = onp.random.RandomState(4)
    B_ragged, S = 6, 16          # does not divide num_micro*dp = 4
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (B_ragged, S)),
                         jnp.int32)
    labels_np = rng.randint(0, cfg.vocab_size, (B_ragged, S))
    labels_np[rng.rand(B_ragged, S) < 0.5] = -1
    labels = jnp.asarray(labels_np, jnp.int32)

    ref_loss = float(models.loss_fn(params, tokens, labels, cfg))

    mesh = par.make_mesh({"pp": 2, "dp": 2})
    ptokens, plabels = models.pp_pad_batch(tokens, labels, 4)
    assert ptokens.shape[0] == 8
    pipe = models.make_pp_pipeline(cfg, params, mesh, num_microbatches=2,
                                   example_tokens=ptokens)
    pp_loss = float(models.pp_loss_fn(pipe, pipe.packed_params, ptokens,
                                      plabels))
    assert abs(pp_loss - ref_loss) < 1e-4, (pp_loss, ref_loss)

    # gradients through the padded pipeline equal the unpadded oracle's
    g_ref = jax.grad(
        lambda p: models.loss_fn(p, tokens, labels, cfg))(params)
    g_packed = jax.grad(
        lambda pk: models.pp_loss_fn(pipe, pk, ptokens, plabels))(
        pipe.packed_params)
    g0, _g1 = pipe.unpack_stage_params(g_packed)
    onp.testing.assert_allclose(
        onp.asarray(g0["layer0.attn.qkv.weight"]),
        onp.asarray(g_ref["layer0.attn.qkv.weight"]), atol=1e-4)


def test_sharded_trainer_remat_under_dp8():
    # remat (jax.checkpoint) must be schedule-only under REAL shardings
    # too: dp=8 with and without recompute produce identical losses
    from mxnet_tpu.gluon import nn

    def build():
        net = nn.Dense(4, in_units=8)
        net.initialize(mx.init.Xavier())
        return net

    def loss_fn(out, label):
        diff = out - label
        return (diff * diff).mean()

    rng = onp.random.RandomState(9)
    data = rng.randn(16, 8).astype(onp.float32)
    label = rng.randn(16, 4).astype(onp.float32)

    losses = []
    for remat in (False, True):
        mx.random.seed(3)
        net = build()
        mesh = par.make_mesh({"dp": 8})
        tr = par.ShardedTrainer(net, loss_fn, mesh, optimizer="sgd",
                                optimizer_params={"lr": 0.1},
                                remat=remat)
        run = [float(tr.step(data, label)) for _ in range(3)]
        losses.append(run)
    onp.testing.assert_allclose(losses[0], losses[1], rtol=1e-6)
