"""Test harness.

Mirrors the reference's test strategy (SURVEY.md §4):
- tests run on a *virtual 8-device CPU mesh* so multi-chip sharding logic is
  exercised without TPU hardware (the reference's analog: parametrizing real
  cpu/gpu contexts, multi-process local launcher);
- seed discipline: each test gets a deterministic seed derived from its name,
  printed on failure so flakes are reproducible (reference conftest.py +
  tests/python/unittest/common.py with_seed).
"""
import os
import sys

# Must be set before jax import: virtual 8-device CPU mesh.
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )
if os.environ.get("MXNET_TEST_ALLOW_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
else:
    # @pytest.mark.tpu runs (benchmark/tpu_watch.sh): keep the real
    # backend; strip only the virtual-mesh flag added above, preserving
    # any operator-supplied XLA_FLAGS (dump/tuning)
    os.environ["XLA_FLAGS"] = " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))

if os.environ.get("MXNET_TEST_ALLOW_TPU") != "1":
    # Persistent XLA compile cache for the CPU suite.  Every
    # GenerativeEngine warmup compiles an identical program set per
    # engine (ProgramStore scopes are per-owner, so in-process jit
    # caches never share across engines), and the serving sampler made
    # those compiles the dominant suite cost.  The disk cache keys on
    # HLO, so the 2nd..Nth engine hits it even within one cold run,
    # without perturbing trace/warmup/program counters the tests pin
    # (unlike MXNET_PROGRAM_CACHE_DIR, which changes warmup returns).
    # setdefault: an operator- or CI-supplied dir wins.  Subprocess
    # tests that count fresh compiles scrub this var from child envs.
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     ".jax_test_cache"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon TPU-tunnel sitecustomize (if present) re-registers platforms and
# can override the env var; forcing the config is authoritative and keeps
# the unit suite on the virtual 8-device CPU mesh even when the tunnel is
# down.
import jax  # noqa: E402

if os.environ.get("MXNET_TEST_ALLOW_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")

import hashlib

import numpy as onp
import pytest


@pytest.fixture(autouse=True)
def seed_everything(request):
    """Deterministic per-test seeding, reported for reproducibility."""
    name = request.node.nodeid
    seed = int(hashlib.sha1(name.encode()).hexdigest()[:8], 16)
    override = os.environ.get("MXNET_TEST_SEED")
    if override:
        seed = int(override)
    onp.random.seed(seed)
    import mxnet_tpu as mx

    mx.random.seed(seed)
    yield
    # On failure pytest prints captured stdout; make the seed discoverable.


def pytest_runtest_makereport(item, call):
    if (call.when == "call" and call.excinfo is not None
            and not call.excinfo.errisinstance(pytest.skip.Exception)):
        name = item.nodeid
        seed = int(hashlib.sha1(name.encode()).hexdigest()[:8], 16)
        print(f"\n*** test failed with MXNET_TEST_SEED={seed} "
              f"(set env var to reproduce) ***")
