"""NDArray indexing matrix vs numpy oracle — mirrors the reference's
``test_ndarray.py::test_indexing`` / ``test_setitem`` families
(tests/python/unittest/test_ndarray.py): basic, advanced, and mixed
indexing, for both reads and writes."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

_R = onp.random.RandomState(13)
_SHAPE = (4, 5, 6)


def _fresh():
    host = _R.rand(*_SHAPE).astype("float32")
    return host, nd.array(host)

# index expressions valid for both numpy and the device array
_INDICES = [
    0,
    -1,
    2,
    (1, 2),
    (1, 2, 3),
    (-1, -2, -3),
    slice(None),
    slice(1, 3),
    slice(None, None, 2),
    slice(None, None, -1),
    slice(3, 0, -2),
    (slice(None), slice(1, 4)),
    (slice(0, 2), slice(None), slice(2, 5)),
    (0, slice(None), slice(None, None, -1)),
    Ellipsis,
    (Ellipsis, 0),
    (0, Ellipsis),
    (Ellipsis, slice(1, 3)),
    None,
    (None, 1),
    (slice(None), None, slice(2, 4)),
    onp.array([0, 2, 3]),
    onp.array([[0, 1], [2, 3]]),
    (onp.array([0, 1]), onp.array([1, 2])),
    (onp.array([0, 1]), slice(None), onp.array([1, 2])),
    (slice(None), onp.array([0, 4])),
    onp.array([True, False, True, False]),
    (slice(None), onp.array([True, False, True, False, True])),
]


@pytest.mark.parametrize(
    "idx", _INDICES,
    ids=[f"{i:02d}" for i in range(len(_INDICES))])
def test_getitem_matches_numpy(idx):
    host, dev = _fresh()
    want = host[idx]
    got = dev[idx].asnumpy()
    assert got.shape == want.shape, (got.shape, want.shape)
    onp.testing.assert_allclose(got, want, rtol=1e-6)


@pytest.mark.parametrize(
    "idx", [i for i in _INDICES if i is not None and
            not (isinstance(i, tuple) and any(x is None for x in i))],
    ids=lambda i: str(i)[:40])
def test_setitem_scalar_matches_numpy(idx):
    host, dev = _fresh()
    host[idx] = 7.5
    dev[idx] = 7.5
    onp.testing.assert_allclose(dev.asnumpy(), host, rtol=1e-6)


@pytest.mark.parametrize("idx", [
    0,
    (1, 2),
    slice(1, 3),
    (slice(None), slice(1, 4)),
    (Ellipsis, slice(1, 3)),
    onp.array([0, 2]),
    onp.array([True, False, True, False]),
])
def test_setitem_array_matches_numpy(idx):
    host, dev = _fresh()
    fill = onp.asarray(host[idx] * 2 + 1)
    host[idx] = fill
    dev[idx] = fill
    onp.testing.assert_allclose(dev.asnumpy(), host, rtol=1e-6)


def test_setitem_broadcast_row():
    host, dev = _fresh()
    row = _R.rand(6).astype("float32")
    host[1, 2] = row
    dev[1, 2] = row
    onp.testing.assert_allclose(dev.asnumpy(), host, rtol=1e-6)


def test_chained_views_read_like_numpy():
    host, dev = _fresh()
    onp.testing.assert_allclose(dev[1:3][0].asnumpy(), host[1:3][0],
                                rtol=1e-6)
    onp.testing.assert_allclose(dev[:, 1][2].asnumpy(), host[:, 1][2],
                                rtol=1e-6)


def test_getitem_out_of_range_int_raises():
    _, dev = _fresh()
    with pytest.raises(Exception):
        dev[7].asnumpy()


def test_setitem_full_slice_scalar_and_version():
    _, dev = _fresh()
    v0 = dev._version
    dev[:] = 3.0
    assert dev._version > v0
    onp.testing.assert_allclose(dev.asnumpy(),
                                onp.full(_SHAPE, 3.0, "float32"))


def test_write_through_does_not_alias_previous_reads():
    """Functional buffers: a read taken before a write keeps its value
    (the version-tracked mutation-as-replacement contract)."""
    host, dev = _fresh()
    before = dev[0]
    dev[0] = 0.0
    onp.testing.assert_allclose(before.asnumpy(), host[0], rtol=1e-6)
    assert float(dev[0].asnumpy().sum()) == 0.0


def test_integer_array_indexing_gradients():
    """Fancy-index reads participate in autograd (gather has a VJP)."""
    from mxnet_tpu import autograd

    x = nd.array(_R.rand(5, 3).astype("float32"))
    x.attach_grad()
    sel = onp.array([0, 2, 2, 4])
    with autograd.record():
        y = x[sel]
        loss = (y * y).sum()
    loss.backward()
    want = onp.zeros((5, 3), "float32")
    for i in sel:
        want[i] += 2 * x.asnumpy()[i]
    onp.testing.assert_allclose(x.grad.asnumpy(), want, rtol=1e-5)
