"""Per-op eager jit cache (round-5 VERDICT Weak #4; SURVEY §7 "per-op
jit-compiled XLA computation with a compilation cache").

MXNET_EAGER_JIT=2 forces the path on CPU.  The battery asserts: numeric
equivalence with plain dispatch across representative op families, cache
reuse (one trace per (op, attrs) across calls), permanent fallback for
ops whose python body cannot trace, autograd equivalence through the
jitted forward, and that hybridized traces never route through an inner
jit (fusion preservation).  Reference analog: engine operator bulking,
``src/engine/threaded_engine.h:507-528``.
"""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, config, nd
from mxnet_tpu.ndarray import ndarray as ndmod


@pytest.fixture
def eager_jit(monkeypatch):
    monkeypatch.setenv("MXNET_EAGER_JIT", "2")
    config.refresh("MXNET_EAGER_JIT")
    for store in (ndmod._EAGER_JIT_CACHE, ndmod._EAGER_JIT_BAD,
                  ndmod._EAGER_JIT_KEYCOUNT):
        store.clear()
    yield
    import os

    os.environ.pop("MXNET_EAGER_JIT", None)    # tests flip it mid-test
    config.refresh("MXNET_EAGER_JIT")
    for store in (ndmod._EAGER_JIT_CACHE, ndmod._EAGER_JIT_BAD,
                  ndmod._EAGER_JIT_KEYCOUNT):
        store.clear()


def _battery():
    rng = onp.random.RandomState(0)
    x = nd.array(rng.randn(4, 8).astype(onp.float32))
    w = nd.array(rng.randn(3, 8).astype(onp.float32))
    b = nd.array(rng.randn(3).astype(onp.float32))
    img = nd.array(rng.randn(2, 3, 8, 8).astype(onp.float32))
    k = nd.array(rng.randn(4, 3, 3, 3).astype(onp.float32))
    return [
        ("add", lambda: x + x),
        ("fc", lambda: nd.FullyConnected(x, w, b, num_hidden=3)),
        ("softmax", lambda: nd.softmax(x, axis=-1)),
        ("conv", lambda: nd.Convolution(img, k, kernel=(3, 3), pad=(1, 1),
                                        num_filter=4, no_bias=True)),
        ("norm", lambda: nd.norm(x, ord=2)),
        ("topk", lambda: nd.topk(x, k=3)),
        ("mean", lambda: x.mean(axis=1)),
    ]


def test_jitted_eager_matches_plain_dispatch(eager_jit):
    import os

    jitted = {}
    for name, fn in _battery():
        jitted[name] = fn().asnumpy()
    os.environ["MXNET_EAGER_JIT"] = "0"
    config.refresh("MXNET_EAGER_JIT")
    for name, fn in _battery():
        onp.testing.assert_allclose(fn().asnumpy(), jitted[name],
                                    rtol=1e-5, atol=1e-6, err_msg=name)


def test_cache_reuse_one_trace_per_attrs(eager_jit):
    from mxnet_tpu.ops.registry import get_op

    schema = get_op("softmax")
    traces = {"n": 0}
    orig = schema.fn

    def counting(*a, **k):
        traces["n"] += 1
        return orig(*a, **k)

    schema.fn = counting
    try:
        x = nd.array(onp.random.RandomState(1).randn(4, 6).astype(onp.float32))
        for _ in range(5):
            nd.softmax(x, axis=-1)
        # one jit trace total, not five executions of the python body
        assert traces["n"] == 1
        nd.softmax(x, axis=0)          # different attrs: one more trace
        assert traces["n"] == 2
        nd.softmax(x, axis=0)
        assert traces["n"] == 2
    finally:
        schema.fn = orig
        ndmod._EAGER_JIT_CACHE.clear()


def test_unjittable_op_falls_back_permanently(eager_jit):
    from mxnet_tpu.ops import registry

    calls = {"n": 0}

    @registry.register("_test_dynamic_shape_op", num_inputs=1,
                       differentiable=False)
    def _dyn(data):
        calls["n"] += 1
        import numpy as np

        host = np.asarray(data)          # concretization: fails under trace
        import jax.numpy as jnp

        return jnp.asarray(host[host > 0])

    try:
        x = nd.array(onp.array([-1.0, 2.0, -3.0, 4.0], onp.float32))
        from mxnet_tpu.ndarray.ndarray import invoke

        out = invoke("_test_dynamic_shape_op", [x], {})
        onp.testing.assert_allclose(out.asnumpy(), [2.0, 4.0])
        assert "_test_dynamic_shape_op" in ndmod._EAGER_JIT_BAD
        # second call goes straight to plain dispatch (no re-jit attempt)
        invoke("_test_dynamic_shape_op", [x], {})
    finally:
        registry._OPS.pop("_test_dynamic_shape_op", None)


def test_autograd_through_jitted_forward(eager_jit):
    x = nd.array(onp.random.RandomState(2).randn(4, 5).astype(onp.float32))
    x.attach_grad()
    with autograd.record():
        y = (nd.softmax(x, axis=-1) * nd.softmax(x, axis=-1)).sum()
    y.backward()
    g_jit = x.grad.asnumpy().copy()
    import os

    os.environ["MXNET_EAGER_JIT"] = "0"
    config.refresh("MXNET_EAGER_JIT")
    x.attach_grad()
    with autograd.record():
        y = (nd.softmax(x, axis=-1) * nd.softmax(x, axis=-1)).sum()
    y.backward()
    onp.testing.assert_allclose(g_jit, x.grad.asnumpy(), rtol=1e-5,
                                atol=1e-6)


def test_tracer_inputs_bypass_inner_jit(eager_jit):
    """Inside a hybridized trace the lookup must return None so ops stay
    inline (XLA fusion across op boundaries)."""
    from mxnet_tpu.gluon import nn

    net = nn.Dense(4)
    net.initialize()
    x = nd.array(onp.random.RandomState(3).randn(2, 8).astype(onp.float32))
    net(x)                     # eager shape probe MAY add cache entries
    net.hybridize()
    before_trace = set(ndmod._EAGER_JIT_CACHE)
    net(x)                     # builds + runs the hybridized trace
    net(x)                     # cached-graph re-execution
    # the trace and its re-execution added NO per-op jit entries
    assert set(ndmod._EAGER_JIT_CACHE) == before_trace


def test_input_error_does_not_ban_op(eager_jit):
    """A bad user call (shape mismatch) must not permanently disable the
    jit cache for that op (review finding)."""
    x = nd.array(onp.ones((2, 3), onp.float32))
    y = nd.array(onp.ones((5, 7), onp.float32))
    with pytest.raises(Exception):
        (x + y).asnumpy()
    assert "broadcast_add" not in ndmod._EAGER_JIT_BAD
    out = (x + x).asnumpy()               # still jitted after the bad call
    onp.testing.assert_allclose(out, 2 * onp.ones((2, 3)))
    assert any(k[0] == "broadcast_add" for k in ndmod._EAGER_JIT_CACHE)


def test_attr_cardinality_cutoff(eager_jit):
    """Ops whose attrs vary every call stop being jitted after the
    per-op cutoff instead of compiling forever (review finding)."""
    x = nd.array(onp.random.RandomState(5).randn(200, 4).astype(onp.float32))
    for i in range(ndmod._EAGER_JIT_MAX_PER_OP + 5):
        nd.slice_axis(x, axis=0, begin=i, end=i + 2)
    assert "slice_axis" in ndmod._EAGER_JIT_BAD
    n_keys = sum(1 for k in ndmod._EAGER_JIT_CACHE if k[0] == "slice_axis")
    assert n_keys <= ndmod._EAGER_JIT_MAX_PER_OP


def test_cache_lru_bounded(eager_jit):
    cap = ndmod._EAGER_JIT_MAX_ENTRIES
    assert len(ndmod._EAGER_JIT_CACHE) <= cap


def test_higher_order_grad_through_jitted_ops(eager_jit):
    """create_graph replay must agree with the plain path (the TapeNode
    replay fn is the unjitted body — review finding)."""
    import os

    def d2(flag):
        os.environ["MXNET_EAGER_JIT"] = flag
        config.refresh("MXNET_EAGER_JIT")
        x = nd.array(onp.array([0.3, -0.7, 1.2], onp.float32))
        x.attach_grad()
        with autograd.record():
            y = nd.tanh(x * x)
            g = autograd.grad(y.sum(), [x], create_graph=True)[0]
            gg = g.sum()
        gg.backward()
        return x.grad.asnumpy().copy()

    onp.testing.assert_allclose(d2("2"), d2("0"), rtol=1e-4, atol=1e-5)


def test_multi_output_op_jitted(eager_jit):
    x = nd.array(onp.random.RandomState(4).randn(6, 4).astype(onp.float32))
    outs = nd.split_v2(x, sections=2, axis=0)
    assert len(outs) == 2
    onp.testing.assert_allclose(
        onp.concatenate([o.asnumpy() for o in outs]), x.asnumpy())


def test_default_mode_off_on_cpu():
    """mode 1 (default) must not jit on the CPU backend: the test suite's
    eager path stays plain dispatch (no per-shape compile storms)."""
    if os.environ.get("MXNET_EAGER_JIT") == "2":
        pytest.skip("suite running with eager jit forced on")
    config.refresh("MXNET_EAGER_JIT")
    ndmod._EAGER_JIT_CACHE.clear()
    x = nd.array(onp.ones((3, 3), onp.float32))
    nd.softmax(x, axis=-1)
    assert not ndmod._EAGER_JIT_CACHE


def test_keyless_rng_ops_never_jitted(eager_jit):
    """Ops that draw from the global PRNG chain when ``key`` is omitted
    (the samplers' ``key=None`` default) must stay on plain dispatch:
    tracing the draw would leak a tracer into the chain and bake the key
    into the cached executable (every cache hit returning identical
    "random" numbers).  Caught live on the TPU backend where eager jit
    defaults on."""
    a = nd.random.normal(shape=(16,))
    b = nd.random.normal(shape=(16,))      # second call: chain must be intact
    assert not onp.allclose(a.asnumpy(), b.asnumpy())
    assert not any(k[0] in ("normal", "uniform") for k in ndmod._EAGER_JIT_CACHE)
    u1 = nd.random.uniform(shape=(16,))
    u2 = nd.random.uniform(shape=(16,))
    assert not onp.allclose(u1.asnumpy(), u2.asnumpy())
    # an explicit key is static data: jit is fine there, and the same key
    # must reproduce the same sample through whichever path runs
    import jax

    k = jax.random.PRNGKey(7)
    s1 = nd.random.normal(shape=(8,), key=k)
    s2 = nd.random.normal(shape=(8,), key=k)
    onp.testing.assert_allclose(s1.asnumpy(), s2.asnumpy())


def test_reduction_opt_out_default_and_override(eager_jit, monkeypatch):
    """Single-primitive reductions stay OUT of the per-op cache by
    default (docs/PERF.md: mean(axis) measured 0.62x through the cache
    on chip) and the list is overridable through MXNET_EAGER_JIT_EXCLUDE
    (config.py)."""
    x = nd.array(onp.random.RandomState(2).randn(4, 6).astype(onp.float32))
    x.mean(axis=1)
    x.sum(axis=0)
    assert not any(k[0] in ("mean", "sum") for k in ndmod._EAGER_JIT_CACHE)
    nd.softmax(x, axis=-1)               # non-excluded ops still cache
    assert any(k[0] == "softmax" for k in ndmod._EAGER_JIT_CACHE)
    # empty override re-admits the reductions (knob is uncached: takes
    # effect immediately)
    monkeypatch.setenv("MXNET_EAGER_JIT_EXCLUDE", "")
    m_jit = x.mean(axis=1)
    assert any(k[0] == "mean" for k in ndmod._EAGER_JIT_CACHE)
    monkeypatch.delenv("MXNET_EAGER_JIT_EXCLUDE")
    onp.testing.assert_allclose(m_jit.asnumpy(),
                                x.asnumpy().mean(axis=1),
                                rtol=1e-6, atol=1e-7)
