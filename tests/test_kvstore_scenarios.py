"""KVStore sharding/bucketing edge cases (reference
tests/nightly/dist_sync_kvstore.py big_shape + MXNET_KVSTORE_BIGARRAY_BOUND
assertions, kvstore_dist.h:44 EncodeDefaultKey splitting)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import config, nd


def test_bigarray_bound_push_pull_equivalence(monkeypatch):
    # arrays above the bound take their own collective; values must be
    # IDENTICAL to the small-array path (the reference asserts the same
    # sums across its big_shape/little_shape pairs)
    monkeypatch.setenv("MXNET_KVSTORE_BIGARRAY_BOUND", "64")
    config.refresh("MXNET_KVSTORE_BIGARRAY_BOUND")
    try:
        kv = mx.kv.create("local")
        rng = onp.random.RandomState(0)
        small = rng.rand(4, 4).astype(onp.float32)          # 16 < 64
        big = rng.rand(32, 8).astype(onp.float32)           # 256 > 64
        kv.init("small", nd.zeros(small.shape))
        kv.init("big", nd.zeros(big.shape))
        kv.push(["small", "big"], [nd.array(small), nd.array(big)])
        out_s, out_b = nd.zeros(small.shape), nd.zeros(big.shape)
        kv.pull("small", out=out_s)
        kv.pull("big", out=out_b)
        onp.testing.assert_allclose(out_s.asnumpy(), small, rtol=1e-6)
        onp.testing.assert_allclose(out_b.asnumpy(), big, rtol=1e-6)
    finally:
        config.refresh("MXNET_KVSTORE_BIGARRAY_BOUND")


def test_mixed_dtype_push_buckets_dont_mix():
    # fp32 and fp16 keys pushed together must not be flattened into one
    # buffer (dtype buckets are separate by construction)
    kv = mx.kv.create("local")
    a = onp.ones((8,), onp.float32) * 1.5
    b = onp.ones((8,), onp.float16) * 2.0
    kv.init("a32", nd.zeros((8,)))
    kv.init("b16", nd.zeros((8,), dtype="float16"))
    kv.push(["a32", "b16"], [nd.array(a), nd.array(b, dtype="float16")])
    oa, ob = nd.zeros((8,)), nd.zeros((8,), dtype="float16")
    kv.pull("a32", out=oa)
    kv.pull("b16", out=ob)
    onp.testing.assert_allclose(oa.asnumpy(), a)
    onp.testing.assert_allclose(ob.asnumpy().astype(onp.float32),
                                b.astype(onp.float32))


def test_many_keys_one_push_order_stable():
    # bucketed multi-key push keeps key->value association (offset math)
    kv = mx.kv.create("local")
    keys = [f"k{i}" for i in range(7)]
    vals = [onp.full((3, i + 1), float(i), onp.float32) for i in range(7)]
    for k, v in zip(keys, vals):
        kv.init(k, nd.zeros(v.shape))
    kv.push(keys, [nd.array(v) for v in vals])
    for k, v in zip(keys, vals):
        out = nd.zeros(v.shape)
        kv.pull(k, out=out)
        onp.testing.assert_allclose(out.asnumpy(), v)


def test_push_aggregates_multiple_device_values():
    # reference: pushing a LIST of per-device grads reduces them
    kv = mx.kv.create("local")
    kv.init("g", nd.zeros((4,)))
    kv.push("g", [nd.ones((4,)), nd.ones((4,)) * 2])
    out = nd.zeros((4,))
    kv.pull("g", out=out)
    onp.testing.assert_allclose(out.asnumpy(), onp.full((4,), 3.0))
