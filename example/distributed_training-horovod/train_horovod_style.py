"""Horovod-style data-parallel training — analog of the reference's
``example/distributed_training-horovod/`` (its gluon_mnist.py recipe:
broadcast once, allreduce gradients every step through a Horovod-API
kvstore).

Without the horovod package installed, ``kvstore='horovod'`` transparently
runs the same API over XLA collectives (`kvstore/horovod.py`) — rank/size
come from the jax process view, so the SAME script serves single-host and
`tools/launch.py`-launched multi-host runs.

    python example/distributed_training-horovod/train_horovod_style.py
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def synthetic_digits(n, seed=0):
    rng = onp.random.RandomState(seed)
    y = rng.randint(0, 10, size=n)
    x = rng.uniform(0.0, 0.15, size=(n, 1, 28, 28)).astype("float32")
    for i, k in enumerate(y):
        r, c = divmod(int(k), 4)
        x[i, 0, 7 * r:7 * r + 7, 7 * c:7 * c + 7] += 0.8
    return x, y.astype("int32")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args()

    kv = mx.kv.create("horovod")
    print(f"horovod-style kvstore: rank {kv.rank}/{kv.num_workers}")

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, kernel_size=3, activation="relu"),
            gluon.nn.MaxPool2D(2), gluon.nn.Flatten(),
            gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(10))
    net.initialize()
    net.hybridize()

    # Trainer drives broadcast (step 0) + allreduce (every step) through
    # the Horovod kvstore API, exactly like the reference recipe
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9},
                            kvstore=kv)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    # each rank sees its own shard of the data
    x, y = synthetic_digits(1024, seed=kv.rank)
    for step in range(args.steps):
        i = (step * args.batch_size) % (1024 - args.batch_size)
        data = mx.nd.array(x[i:i + args.batch_size])
        label = mx.nd.array(y[i:i + args.batch_size])
        with autograd.record():
            loss = loss_fn(net(data), label)
        loss.backward()
        trainer.step(args.batch_size)
        if step % 20 == 0:
            print(f"step {step}: loss={loss.mean().asnumpy():.4f}")

    acc = float((net(mx.nd.array(x)).asnumpy().argmax(axis=1) == y).mean())
    print(f"rank {kv.rank} accuracy={acc:.3f}")
    assert acc > 0.9
    print("OK")


if __name__ == "__main__":
    main()
