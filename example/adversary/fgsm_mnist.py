"""Fast Gradient Sign Method adversarial examples — the TPU-native take on
the reference's ``example/adversary/adversary_generation.ipynb``.

Trains a small convnet on synthetic MNIST-like digits, then attacks it with
FGSM: perturb each input by ``eps * sign(dL/dx)`` (gradient taken w.r.t. the
*input*, via ``x.attach_grad()``), and report clean vs adversarial accuracy.
On TPU the attack is one extra jitted backward pass — no graph surgery.

    python example/adversary/fgsm_mnist.py --epochs 1 --eps 0.3
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def build_net():
    net = gluon.nn.HybridSequential()
    net.add(
        gluon.nn.Conv2D(8, kernel_size=3, activation="relu"),
        gluon.nn.MaxPool2D(pool_size=2),
        gluon.nn.Flatten(),
        gluon.nn.Dense(32, activation="relu"),
        gluon.nn.Dense(10),
    )
    return net


def synthetic_digits(n, seed=0):
    """Class k lights a distinct 7x7 patch; separable so one epoch trains."""
    rng = onp.random.RandomState(seed)
    y = rng.randint(0, 10, size=n)
    x = rng.uniform(0.0, 0.15, size=(n, 1, 28, 28)).astype("float32")
    for i, k in enumerate(y):
        r, c = divmod(int(k), 4)
        x[i, 0, 7 * r:7 * r + 7, 7 * c:7 * c + 7] += 0.8
    return x, y.astype("int32")


def accuracy(net, x, y):
    pred = net(mx.nd.array(x)).asnumpy().argmax(axis=1)
    return float((pred == y).mean())


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--eps", type=float, default=0.3)
    p.add_argument("--n", type=int, default=1024)
    args = p.parse_args()

    x, y = synthetic_digits(args.n)
    net = build_net()
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2})

    for epoch in range(args.epochs):
        for i in range(0, args.n, args.batch_size):
            data = mx.nd.array(x[i:i + args.batch_size])
            label = mx.nd.array(y[i:i + args.batch_size])
            with autograd.record():
                loss = loss_fn(net(data), label)
            loss.backward()
            trainer.step(data.shape[0])
        print(f"epoch {epoch}: loss={loss.mean().asnumpy():.4f}")

    clean_acc = accuracy(net, x, y)

    # FGSM: gradient w.r.t. the INPUT.  attach_grad on a non-parameter array
    # marks it as a differentiation root, same as the reference's
    # mark_variables on the data blob.
    adv = onp.empty_like(x)
    for i in range(0, args.n, args.batch_size):
        data = mx.nd.array(x[i:i + args.batch_size])
        label = mx.nd.array(y[i:i + args.batch_size])
        data.attach_grad()
        with autograd.record():
            loss = loss_fn(net(data), label)
        loss.backward()
        perturbed = data + args.eps * mx.nd.sign(data.grad)
        adv[i:i + args.batch_size] = mx.nd.clip(
            perturbed, 0.0, 1.0).asnumpy()

    adv_acc = accuracy(net, adv, y)
    print(f"clean accuracy={clean_acc:.3f} "
          f"adversarial accuracy (eps={args.eps})={adv_acc:.3f}")
    assert clean_acc > 0.9, "model failed to train"
    assert adv_acc < clean_acc, "FGSM should hurt accuracy"
    print("OK")


if __name__ == "__main__":
    main()
