"""Toy SSD: single-shot detection end to end on synthetic data.

Exercises the full detection operator suite the way the reference's SSD
example does (example/ssd in the reference ecosystem): multibox_prior
anchors, multibox_target training targets (matching + negative mining),
a conv backbone predicting class scores + box offsets, SmoothL1 + CE
losses, and multibox_detection (decode + NMS) for inference.

Synthetic task: images contain one bright axis-aligned square (class 1)
on a dark background; the model learns to localize it.

    python example/ssd/train_ssd_toy.py --steps 40
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


class ToySSD(gluon.HybridBlock):
    """Tiny backbone + one prediction head over a coarse feature map."""

    def __init__(self, num_classes=2, num_anchors=3):
        super().__init__()
        self.num_classes = num_classes
        self.num_anchors = num_anchors
        self.backbone = gluon.nn.HybridSequential()
        self.backbone.add(
            gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
        )
        self.cls_head = gluon.nn.Conv2D(num_anchors * num_classes, 3,
                                        padding=1)
        self.loc_head = gluon.nn.Conv2D(num_anchors * 4, 3, padding=1)

    def forward(self, x):
        feat = self.backbone(x)                         # (B, C, H/4, W/4)
        cls = self.cls_head(feat)                       # (B, A*K, h, w)
        loc = self.loc_head(feat)                       # (B, A*4, h, w)
        B = x.shape[0]
        cls = nd.reshape(nd.transpose(cls, axes=(0, 2, 3, 1)),
                         shape=(B, -1, self.num_classes))
        loc = nd.reshape(nd.transpose(loc, axes=(0, 2, 3, 1)),
                         shape=(B, -1))
        return cls, loc, feat


def make_batch(rng, batch, size=32):
    """One bright square per image; label = [cls, x1, y1, x2, y2] norm."""
    x = rng.rand(batch, 1, size, size).astype(onp.float32) * 0.2
    labels = onp.zeros((batch, 1, 5), onp.float32)
    for i in range(batch):
        s = rng.randint(8, 16)
        x0 = rng.randint(0, size - s)
        y0 = rng.randint(0, size - s)
        x[i, 0, y0:y0 + s, x0:x0 + s] += 0.8
        # class id 0 -> multibox_target emits class 1 (0 is background)
        labels[i, 0] = [0, x0 / size, y0 / size, (x0 + s) / size,
                        (y0 + s) / size]
    return nd.array(x), nd.array(labels)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    rng = onp.random.RandomState(0)
    # anchors per cell = len(sizes) + len(ratios) - 1 = 3
    net = ToySSD(num_anchors=3)
    net.initialize(mx.init.Xavier())
    x0, _ = make_batch(rng, 2)
    _, _, feat = net(x0)
    anchors = nd.multibox_prior(feat, sizes=(0.3, 0.45), ratios=(1.0, 2.0))
    num_anchors_total = anchors.shape[1]
    print(f"feature map {tuple(feat.shape[2:])}, "
          f"{num_anchors_total} anchors")

    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    smooth_l1 = gluon.loss.HuberLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})

    t0 = time.time()
    first = last = None
    for step in range(args.steps):
        data, labels = make_batch(rng, args.batch_size)
        with autograd.record():
            cls_pred, loc_pred, _ = net(data)
            # targets computed from anchors + ground truth (no grad)
            with autograd.pause():
                cls_pred_t = nd.transpose(cls_pred, axes=(0, 2, 1))
                loc_t, loc_mask, cls_t = nd.multibox_target(
                    anchors, labels, cls_pred_t)
            cls_loss = ce(
                nd.reshape(cls_pred, shape=(-1, net.num_classes)),
                nd.reshape(cls_t, shape=(-1,)))
            loc_loss = smooth_l1(loc_pred * loc_mask, loc_t)
            loss = cls_loss.mean() + loc_loss.mean()
        loss.backward()
        trainer.step(args.batch_size)
        lv = float(loss.asscalar())
        first = lv if first is None else first
        last = lv
        if step % 10 == 0:
            print(f"step {step}: loss {lv:.4f}")
    print(f"loss {first:.4f} -> {last:.4f} "
          f"({args.steps * args.batch_size / (time.time() - t0):.0f} img/s)")

    # inference: decode + NMS, check the detection lands on the square
    data, labels = make_batch(rng, 4)
    cls_pred, loc_pred, _ = net(data)
    cls_prob = nd.softmax(nd.transpose(cls_pred, axes=(0, 2, 1)), axis=1)
    dets = nd.multibox_detection(cls_prob, loc_pred, anchors,
                                 nms_threshold=0.45)
    kept = (dets.asnumpy()[:, :, 0] >= 0).sum(axis=1)
    print(f"detections kept per image: {kept.tolist()}")
    assert last < first, "loss did not decrease"
    assert (kept >= 1).all(), "no detections produced"
    print("OK")


if __name__ == "__main__":
    main()
