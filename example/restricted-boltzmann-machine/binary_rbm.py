"""Binary Restricted Boltzmann Machine trained with contrastive divergence —
TPU-native analog of the reference's
``example/restricted-boltzmann-machine/binary_rbm.py``.

An RBM is an energy model, not a feed-forward net: the CD-k gradient comes
from Gibbs-sampling statistics rather than backprop, so this example drives
the NDArray API directly (dot, sigmoid, bernoulli sampling) with manual
parameter updates — the same imperative style the reference example uses,
but every step's math runs as fused XLA ops on device.

    python example/restricted-boltzmann-machine/binary_rbm.py --epochs 3
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd


def synthetic_binary_digits(n, seed=0):
    """Binarized patch-digits: same generator family as the other examples."""
    rng = onp.random.RandomState(seed)
    y = rng.randint(0, 10, size=n)
    x = onp.zeros((n, 28 * 28), dtype="float32")
    img = x.reshape(n, 28, 28)
    for i, k in enumerate(y):
        r, c = divmod(int(k), 4)
        img[i, 7 * r:7 * r + 7, 7 * c:7 * c + 7] = 1.0
    return x


class BinaryRBM:
    def __init__(self, n_visible, n_hidden, seed=0):
        rng = onp.random.RandomState(seed)
        self.w = nd.array(rng.normal(scale=0.01,
                                     size=(n_visible, n_hidden)))
        self.bv = nd.zeros((n_visible,))
        self.bh = nd.zeros((n_hidden,))

    def hidden_prob(self, v):
        return nd.sigmoid(nd.dot(v, self.w) + self.bh)

    def visible_prob(self, h):
        return nd.sigmoid(nd.dot(h, self.w, transpose_b=True) + self.bv)

    def _sample(self, prob):
        return (mx.nd.random.uniform(shape=prob.shape) < prob).astype(
            "float32")

    def cd1_update(self, v0, lr):
        """One step of CD-1: positive phase on data, negative phase after a
        single Gibbs round trip; update with the statistics difference."""
        ph0 = self.hidden_prob(v0)
        h0 = self._sample(ph0)
        pv1 = self.visible_prob(h0)
        v1 = self._sample(pv1)
        ph1 = self.hidden_prob(v1)

        batch = float(v0.shape[0])
        self.w += lr / batch * (nd.dot(v0, ph0, transpose_a=True)
                                - nd.dot(v1, ph1, transpose_a=True))
        self.bv += lr * (v0 - v1).mean(axis=0)
        self.bh += lr * (ph0 - ph1).mean(axis=0)
        # reconstruction error is the standard RBM training monitor
        return float(((v0 - pv1) ** 2).mean().asnumpy())


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.1)
    args = p.parse_args()

    x = synthetic_binary_digits(1024)
    rbm = BinaryRBM(n_visible=x.shape[1], n_hidden=args.hidden)

    first = last = None
    for epoch in range(args.epochs):
        errs = []
        for i in range(0, len(x), args.batch_size):
            v0 = nd.array(x[i:i + args.batch_size])
            errs.append(rbm.cd1_update(v0, args.lr))
        err = sum(errs) / len(errs)
        if first is None:
            first = err
        last = err
        print(f"epoch {epoch}: recon_err={err:.5f}")

    print(f"recon_err first={first:.5f} last={last:.5f}")
    assert last < first, "CD-1 should reduce reconstruction error"
    print("OK")


if __name__ == "__main__":
    main()
