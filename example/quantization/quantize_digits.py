"""INT8 post-training quantization, end to end (reference
example/quantization/imagenet_gen_qsym_onednn.py workflow, TPU-native).

Loads the shipped REAL-data pretrained mobilenet (92.8% test accuracy on
scikit-learn's bundled handwritten digits), calibrates on a handful of
batches, converts to an int8 graph (conv+BN+relu folded, requantize
fused), and reports int8-vs-fp32 top-1 agreement and accuracy on the
held-out split.

On a TPU chip set MXNET_INT8_PALLAS=1 to route eligible convs through
the explicit s8 MXU kernels (ops/pallas_kernels.py); the default lax
path runs everywhere.

    python example/quantization/quantize_digits.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.contrib import quantization as q
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.test_utils import load_digits_split


def main():
    net = vision.get_model("mobilenet0.25", pretrained=True)
    net.hybridize()
    Xtr, _, Xte, Yte = load_digits_split()

    # calibrate on TRAIN data — the scored split stays held out
    calib = [nd.array(Xtr[i:i + 32]) for i in range(0, 96, 32)]
    qnet = q.quantize_net(net, calib, calib_mode="naive")

    agree = correct_fp = correct_q = 0
    for i in range(0, len(Xte), 64):
        x = nd.array(Xte[i:i + 64])
        y = Yte[i:i + 64]
        ref = net(x).asnumpy().argmax(1)
        got = onp.asarray(qnet(x)).argmax(1)
        agree += int((ref == got).sum())
        correct_fp += int((ref == y).sum())
        correct_q += int((got == y).sum())
    n = len(Xte)
    print(f"fp32 accuracy:  {correct_fp / n:.4f}")
    print(f"int8 accuracy:  {correct_q / n:.4f}")
    print(f"top-1 agreement: {agree / n:.4f}")
    assert agree / n >= 0.97, "int8 predictions diverged from fp32"
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
