"""LeNet on MNIST with Gluon — the reference's canonical first example
(example/gluon/mnist/mnist.py) on the TPU-native stack.

Runs end to end on any backend; uses the synthetic MNIST iterator when the
dataset isn't on disk (zero-egress environments).

    python example/gluon/train_mnist.py --epochs 1 --batch-size 64
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def build_lenet():
    net = gluon.nn.HybridSequential()
    net.add(
        gluon.nn.Conv2D(6, kernel_size=5, activation="relu"),
        gluon.nn.MaxPool2D(pool_size=2),
        gluon.nn.Conv2D(16, kernel_size=5, activation="relu"),
        gluon.nn.MaxPool2D(pool_size=2),
        gluon.nn.Flatten(),
        gluon.nn.Dense(120, activation="relu"),
        gluon.nn.Dense(84, activation="relu"),
        gluon.nn.Dense(10),
    )
    return net


def synthetic_mnist(batch_size, batches=50, seed=0):
    """Deterministic class-separable synthetic digits: class k lights a
    distinct patch, so a working train loop reaches ~100% quickly."""
    rng = onp.random.RandomState(seed)
    for _ in range(batches):
        y = rng.randint(0, 10, batch_size).astype(onp.int32)
        x = rng.rand(batch_size, 1, 28, 28).astype(onp.float32) * 0.1
        for i, k in enumerate(y):
            r, c = divmod(int(k), 4)
            x[i, 0, 4 + r * 8:10 + r * 8, 2 + c * 6:8 + c * 6] += 1.0
        yield mx.nd.array(x), mx.nd.array(y)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.02)
    ap.add_argument("--hybridize", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="--no-hybridize runs the imperative path")
    ap.add_argument("--device", default=None, choices=[None, "cpu", "tpu"],
                    help="pin the training device (default: jax's default)")
    args = ap.parse_args()

    if args.device:
        ctx = mx.tpu(0) if args.device == "tpu" else mx.cpu(0)
        ctx.__enter__()                 # process-wide default context
        print(f"device: {ctx}")

    net = build_lenet()
    net.initialize(mx.init.Xavier())
    if args.hybridize:
        net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    metric = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        metric.reset()
        tic = time.time()
        n = 0
        for data, label in synthetic_mnist(args.batch_size):
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(args.batch_size)
            metric.update([label], [out])
            n += args.batch_size
        name, acc = metric.get()
        print(f"epoch {epoch}: {name}={acc:.4f} "
              f"({n / (time.time() - tic):.0f} img/s)")
    return metric.get()[1]


if __name__ == "__main__":
    acc = main()
    assert acc > 0.5, f"LeNet failed to learn (acc={acc})"
    print("OK")
