"""Character-level language model with the fused RNN stack.

Reference analog: example/rnn (char-rnn training over the fused RNN op,
the cuDNN-backed path).  Here the fused op is a lax.scan lowering
(`ops/rnn.py`), wrapped by `gluon.rnn.LSTM`; training goes through the
standard Gluon loop with hybridization.

Synthetic corpus: a repeating pattern with long-range structure, so a
learning LSTM drives perplexity far below the uniform baseline.

    python example/rnn/char_lm.py --steps 60
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


class CharLM(gluon.HybridBlock):
    def __init__(self, vocab, embed=32, hidden=64, layers=1):
        super().__init__()
        self.embedding = gluon.nn.Embedding(vocab, embed)
        self.lstm = gluon.rnn.LSTM(hidden, num_layers=layers)
        self.head = gluon.nn.Dense(vocab, flatten=False)

    def forward(self, x):
        # x: (seq, batch) int tokens -> logits (seq, batch, vocab)
        emb = self.embedding(x)
        out = self.lstm(emb)
        return self.head(out)


def make_corpus(n=4096, period=17, vocab=16, seed=0):
    """Deterministic long-period sequence + noise tokens."""
    rng = onp.random.RandomState(seed)
    base = onp.arange(n) % period % vocab
    noise = rng.randint(0, vocab, n) * (rng.rand(n) < 0.05)
    return ((base + noise) % vocab).astype(onp.int32)


def batches(corpus, seq, batch, steps, rng):
    for _ in range(steps):
        starts = rng.randint(0, len(corpus) - seq - 1, batch)
        x = onp.stack([corpus[s:s + seq] for s in starts], axis=1)
        y = onp.stack([corpus[s + 1:s + seq + 1] for s in starts], axis=1)
        yield nd.array(x, dtype="int32"), nd.array(y, dtype="int32")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--seq", type=int, default=48)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=16)
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args()

    rng = onp.random.RandomState(1)
    corpus = make_corpus(vocab=args.vocab)
    net = CharLM(args.vocab)
    net.initialize(mx.init.Xavier())
    x0 = nd.zeros((args.seq, args.batch_size), dtype="int32")
    net(x0)
    net.hybridize()

    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    uniform_ppl = args.vocab
    t0 = time.time()
    first = last = None
    for step, (x, y) in enumerate(
            batches(corpus, args.seq, args.batch_size, args.steps, rng)):
        with autograd.record():
            logits = net(x)
            loss = ce(nd.reshape(logits, shape=(-1, args.vocab)),
                      nd.reshape(y, shape=(-1,))).mean()
        loss.backward()
        trainer.step(1)
        lv = float(loss.asscalar())
        first = lv if first is None else first
        last = lv
        if step % 20 == 0:
            print(f"step {step}: loss {lv:.4f} "
                  f"(ppl {onp.exp(lv):.2f} vs uniform {uniform_ppl})")
    toks = args.steps * args.seq * args.batch_size
    print(f"loss {first:.4f} -> {last:.4f}, "
          f"{toks / (time.time() - t0):.0f} tokens/s")
    assert onp.exp(last) < uniform_ppl * 0.6, "LSTM failed to learn"
    print("OK")


if __name__ == "__main__":
    main()
