"""Multi-threaded inference — TPU-native analog of the reference's
``example/multi_threaded_inference/multi_threaded_inference.cc`` (its
thread-safe CachedOp demo).

The reference needed a dedicated ``CachedOpThreadSafe`` because its graph
executor kept mutable per-invoke state.  Here the hybridized forward is a
pure compiled XLA program — same executable called from many Python threads
concurrently; the PJRT client serializes device execution safely.  The test:
N threads hammer one shared hybridized model and every thread must get
bit-identical results to the single-threaded reference answers.

    python example/multi_threaded_inference/multi_threaded_inference.py
"""
import argparse
import os
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon.model_zoo import vision


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--threads", type=int, default=4)
    p.add_argument("--iters", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=4)
    args = p.parse_args()

    net = vision.get_model("squeezenet1.1", classes=10)
    net.initialize()
    net.hybridize(static_alloc=True)

    rng = onp.random.RandomState(0)
    batches = [rng.uniform(size=(args.batch_size, 3, 64, 64))
               .astype("float32") for _ in range(args.iters)]

    # single-threaded reference answers (also triggers the one-time trace,
    # so worker threads race only on the steady-state compiled path)
    expect = [net(mx.nd.array(b)).asnumpy() for b in batches]

    errors = []

    def worker(tid):
        try:
            order = list(range(args.iters))
            if tid % 2:                     # different orders per thread
                order.reverse()
            for i in order:
                got = net(mx.nd.array(batches[i])).asnumpy()
                if not onp.array_equal(got, expect[i]):
                    errors.append((tid, i, float(
                        onp.abs(got - expect[i]).max())))
        except Exception as exc:            # surface, don't deadlock
            errors.append((tid, "exception", repr(exc)))

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(args.threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    assert not errors, f"cross-thread mismatches: {errors[:5]}"
    print(f"{args.threads} threads x {args.iters} batches: "
          f"all results bit-identical to single-threaded run")
    print("OK")


if __name__ == "__main__":
    main()
