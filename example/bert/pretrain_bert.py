"""BERT-style masked-LM pretraining with elastic fault tolerance.

Reference analog: the BERT+LAMB pretrain configuration (BASELINE config 4).
Demonstrates the flagship transformer with a tp x dp mesh sharding, LAMB,
micro-batch gradient accumulation, and crash-safe checkpointing
(parallel/elastic.py — capability the reference does not have).

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python example/bert/pretrain_bert.py --tp 2 --dp 4 --steps 6
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--grad-accum", type=int, default=1)
    ap.add_argument("--checkpoint-dir", default="",
                    help="persistent checkpoint dir enabling cross-run "
                         "resume (MUST match the model config); default: "
                         "a fresh temp dir per run")
    ap.add_argument("--save-every", type=int, default=2)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from mxnet_tpu import models
    from mxnet_tpu import parallel as par

    mesh = par.make_mesh({"tp": args.tp, "dp": args.dp})
    cfg = models.TransformerLMConfig(
        vocab_size=1024, num_layers=args.layers, num_heads=args.heads,
        hidden=args.hidden, mlp_hidden=args.hidden * 4, max_len=args.seq,
        dtype=jnp.float32)
    params = models.init_params(jax.random.PRNGKey(0), cfg)
    plan = models.sharding_plan(cfg)

    ckpt_dir = args.checkpoint_dir
    cleanup_dir = None
    if not ckpt_dir:
        import tempfile

        ckpt_dir = cleanup_dir = tempfile.mkdtemp(prefix="bert_ckpt_")
    ckpt = par.CheckpointManager(ckpt_dir, keep=2)
    rng = onp.random.RandomState(0)

    with mesh:
        params = plan.shard_tree(params, mesh)
        m, v = models.init_opt_state(params)
        m, v = plan.shard_tree(m, mesh), plan.shard_tree(v, mesh)
        step = models.make_train_step(cfg, mesh, optimizer="lamb", lr=1e-3,
                                      grad_accum=args.grad_accum)

        def make_batch():
            toks = rng.randint(0, cfg.vocab_size, (args.batch, args.seq))
            return jnp.asarray(toks, jnp.int32)

        batches = [make_batch() for _ in range(args.steps)]

        def train_one(state, tokens):
            p, mm, vv, step_no = state
            p, mm, vv, loss = step(p, mm, vv, tokens, tokens,
                                   jnp.float32(1))
            print(f"  step {step_no + 1}: loss {float(loss):.4f}")
            return (p, mm, vv, step_no + 1)

        tic = time.time()
        state, steps, restarts = par.run_elastic(
            train_one, (params, m, v, 0), batches, ckpt,
            save_every=args.save_every)
        dt = time.time() - tic

    toks_per_s = args.batch * args.seq * steps / dt
    print(f"{steps} steps ({restarts} restarts), "
          f"{toks_per_s:.0f} tokens/s global, "
          f"checkpoints at {ckpt.all_steps()}")
    ckpt.close()
    if cleanup_dir is not None:
        import shutil

        shutil.rmtree(cleanup_dir, ignore_errors=True)
    print("OK")


if __name__ == "__main__":
    main()
