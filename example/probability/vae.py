"""Variational autoencoder — TPU-native analog of the reference's
``example/probability/VAE`` demo.

Dense encoder produces (mu, log-variance); the reparameterization trick
``z = mu + exp(logvar/2) * eps`` keeps sampling differentiable; the loss is
Bernoulli reconstruction NLL + the analytic diagonal-Gaussian KL to the
standard-normal prior.  ``mxnet_tpu.gluon.probability.Normal`` +
``kl_divergence`` verify the hand-written KL at the end.

    python example/probability/vae.py --steps 120
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.probability import Normal, kl_divergence


class VAE(gluon.HybridBlock):
    def __init__(self, latent=8, hidden=128, n_out=28 * 28):
        super().__init__()
        self.latent = latent
        self.encoder = gluon.nn.HybridSequential()
        self.encoder.add(gluon.nn.Dense(hidden, activation="relu"),
                         gluon.nn.Dense(2 * latent))
        self.decoder = gluon.nn.HybridSequential()
        self.decoder.add(gluon.nn.Dense(hidden, activation="relu"),
                         gluon.nn.Dense(n_out))

    def forward(self, x, eps):
        stats = self.encoder(x)
        mu = stats[:, :self.latent]
        logvar = stats[:, self.latent:]
        z = mu + mx.nd.exp(0.5 * logvar) * eps      # reparameterization
        logits = self.decoder(z)
        return logits, mu, logvar


def elbo_loss(logits, x, mu, logvar):
    # Bernoulli NLL via numerically-stable logits form
    recon = mx.nd.relu(logits) - logits * x + \
        mx.nd.log(1 + mx.nd.exp(-mx.nd.abs(logits)))
    recon = recon.sum(axis=1)
    # KL(N(mu, sigma^2) || N(0, 1)), analytic diagonal form
    kl = 0.5 * (mx.nd.exp(logvar) + mu ** 2 - 1 - logvar).sum(axis=1)
    return (recon + kl).mean(), kl.mean()


def synthetic_binary_digits(n, seed=0):
    rng = onp.random.RandomState(seed)
    y = rng.randint(0, 10, size=n)
    x = onp.zeros((n, 28, 28), dtype="float32")
    for i, k in enumerate(y):
        r, c = divmod(int(k), 4)
        x[i, 7 * r:7 * r + 7, 7 * c:7 * c + 7] = 1.0
    return x.reshape(n, -1)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=120)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--latent", type=int, default=8)
    args = p.parse_args()

    x = synthetic_binary_digits(1024)
    net = VAE(latent=args.latent)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})

    first = last = None
    for step in range(args.steps):
        i = (step * args.batch_size) % (1024 - args.batch_size)
        data = mx.nd.array(x[i:i + args.batch_size])
        eps = mx.nd.random.normal(shape=(data.shape[0], args.latent))
        with autograd.record():
            logits, mu, logvar = net(data, eps)
            loss, kl = elbo_loss(logits, data, mu, logvar)
        loss.backward()
        trainer.step(data.shape[0])
        val = float(loss.asnumpy())
        if first is None:
            first = val
        last = val
        if step % 30 == 0:
            print(f"step {step}: -elbo={val:.2f} kl={float(kl.asnumpy()):.3f}")

    # cross-check the hand-written KL against gluon.probability on the last
    # batch's posterior
    post = Normal(loc=mu, scale=mx.nd.exp(0.5 * logvar))
    prior = Normal(loc=mx.nd.zeros(mu.shape), scale=mx.nd.ones(mu.shape))
    kl_lib = float(kl_divergence(post, prior).sum(axis=1).mean().asnumpy())
    assert abs(kl_lib - float(kl.asnumpy())) < 1e-3 * max(1.0, kl_lib), \
        (kl_lib, float(kl.asnumpy()))

    print(f"-elbo first={first:.2f} last={last:.2f} (library KL={kl_lib:.3f})")
    assert last < first, "ELBO should improve"

    # generate: decode prior samples — just proves the decoder runs standalone
    z = mx.nd.random.normal(shape=(16, args.latent))
    samples = mx.nd.sigmoid(net.decoder(z))
    assert samples.shape == (16, 28 * 28)
    print("OK")


if __name__ == "__main__":
    main()
