"""Sorting with a bidirectional LSTM — TPU-native analog of the reference's
``example/bi-lstm-sort/bi-lstm-sort.ipynb``.

The network reads a sequence of random digits and must emit the same digits
in sorted order: each output position is a classification over the
vocabulary, supervised with the sorted sequence.  A bidirectional LSTM sees
the whole sequence at every position, which is exactly what the task needs.
On TPU the recurrence lowers to a single ``lax.scan`` per direction.

    python example/bi-lstm-sort/bi_lstm_sort.py --steps 150
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


class SortNet(gluon.HybridBlock):
    def __init__(self, vocab=10, hidden=64):
        super().__init__()
        self.embed = gluon.nn.Embedding(vocab, 32)
        self.lstm = gluon.rnn.LSTM(hidden, num_layers=1,
                                   bidirectional=True, layout="NTC")
        self.out = gluon.nn.Dense(vocab, flatten=False)

    def forward(self, x):
        h = self.lstm(self.embed(x))
        return self.out(h)          # (N, T, vocab) logits per position


def batches(batch_size, seq_len, vocab, seed):
    rng = onp.random.RandomState(seed)
    while True:
        seq = rng.randint(0, vocab, size=(batch_size, seq_len))
        yield seq.astype("int32"), onp.sort(seq, axis=1).astype("int32")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=150)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--seq-len", type=int, default=8)
    p.add_argument("--vocab", type=int, default=10)
    args = p.parse_args()

    mx.random.seed(42)              # deterministic init for the smoke run
    net = SortNet(vocab=args.vocab)
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2})

    gen = batches(args.batch_size, args.seq_len, args.vocab, seed=0)
    for step in range(args.steps):
        seq, tgt = next(gen)
        data, label = mx.nd.array(seq), mx.nd.array(tgt)
        with autograd.record():
            logits = net(data)
            loss = loss_fn(logits.reshape(-1, args.vocab), label.reshape(-1))
        loss.backward()
        trainer.step(data.shape[0])
        if step % 30 == 0:
            print(f"step {step}: loss={loss.mean().asnumpy():.4f}")

    # evaluate exact-position accuracy on held-out sequences
    seq, tgt = next(batches(256, args.seq_len, args.vocab, seed=99))
    pred = net(mx.nd.array(seq)).asnumpy().argmax(axis=-1)
    acc = float((pred == tgt).mean())
    print(f"sorted-position accuracy={acc:.3f}")
    assert acc > 0.75, "bi-LSTM should learn to sort short digit sequences"
    print("OK")


if __name__ == "__main__":
    main()
