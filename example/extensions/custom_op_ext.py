"""Example runtime extension: custom ops + an optimize_for backend.

Reference analog: example/extensions/lib_custom_op (gemm_lib.cc /
relu_lib.cu registered through lib_api.h and loaded with
``mx.library.load('libcustom.so')``).  The TPU-native extension is a
Python module using the same public API; load it with::

    import mxnet_tpu as mx
    mx.library.load("example/extensions/custom_op_ext.py")
    y = mx.nd.my_gemm(a, b)

Everything registered here works eagerly, under autograd, hybridized, and
inside pjit — one registration, every execution path.
"""
import jax
import jax.numpy as jnp

from mxnet_tpu import library


@library.register_op("my_gemm", num_inputs=2)
def my_gemm(a, b):
    """Custom GEMM (the gemm_lib.cc example, as an MXU-friendly einsum)."""
    return jnp.matmul(a, b, preferred_element_type=jnp.float32)


def _my_relu_grad(res, ct):
    (x,), _out = res
    return (ct * (x > 0).astype(ct.dtype),)


@library.register_op("my_relu", grad=_my_relu_grad, num_inputs=1)
def my_relu(x):
    """Custom ReLU with an explicit VJP (the relu_lib.cu example)."""
    return jnp.maximum(x, 0)


@library.register_backend("example_bf16")
def example_bf16(fn, **flags):
    """optimize_for backend: run the whole cached graph with bf16 params
    (a whole-function rewrite where the reference would partition
    subgraphs — XLA handles the fusion)."""

    def wrapped(param_arrays, input_arrays, rng_key):
        cast = [p.astype(jnp.bfloat16) if jnp.issubdtype(p.dtype, jnp.floating)
                else p for p in param_arrays]
        outs, muts = fn(cast, input_arrays, rng_key)
        return [o.astype(jnp.float32) if jnp.issubdtype(o.dtype, jnp.floating)
                else o for o in outs], muts

    return wrapped
