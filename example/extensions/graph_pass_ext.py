"""Example runtime extension: a custom GRAPH PASS.

Reference analog: ``example/extensions/lib_pass`` (pass_lib.cc registers a
``myPass`` through lib_api.h; users run it with
``optimize_for(backend='myPass')``).  Here a pass is a whole-function
transform over the traced pure function of a hybridized block — it runs
BEFORE jax.jit, so whatever it emits is compiled into the one XLA program.

This pass does two things, mirroring the reference example's spirit:

1. counts the ops it flows through (observability), and
2. rewrites the computation to bf16 compute with an fp32 result — a real
   TPU-shaped rewrite (the MXU's native dtype), not a toy.

Usage::

    import mxnet_tpu as mx
    mx.library.load("example/extensions/graph_pass_ext.py")
    net.hybridize(backend="bf16_pass")
"""
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                                  "..", ".."))

import jax
import jax.numpy as jnp

from mxnet_tpu import library

STATS = {"calls": 0}


@library.register_backend("bf16_pass")
def bf16_pass(fn, **flags):
    """transform(fn) -> fn; signature of fn is
    (param_arrays, input_arrays, rng_key) -> (outputs, mutated)."""

    def cast_tree(tree, dt):
        return jax.tree_util.tree_map(
            lambda a: a.astype(dt)
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype,
                                                      jnp.floating)
            else a, tree)

    def wrapped(params, inputs, key):
        STATS["calls"] += 1
        p16 = cast_tree(params, jnp.bfloat16)
        i16 = cast_tree(inputs, jnp.bfloat16)
        outs, mutated = fn(p16, i16, key)
        return cast_tree(outs, jnp.float32), mutated

    return wrapped


if __name__ == "__main__":
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon

    net = gluon.nn.Dense(4, in_units=8)
    net.initialize()
    x = mx.nd.array(onp.random.RandomState(0).rand(2, 8).astype("f"))
    ref = net(x).asnumpy()
    net.hybridize(backend="bf16_pass")
    out = net(x)
    assert STATS["calls"] >= 1
    err = float(onp.abs(out.asnumpy() - ref).max())
    print(f"bf16_pass applied; max |bf16 - fp32| = {err:.4f}")
    assert err < 0.1
    print("OK")
