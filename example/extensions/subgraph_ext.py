"""Example runtime extension: a custom SUBGRAPH PARTITIONER.

Reference analog: ``example/extensions/lib_subgraph`` (subgraph_lib.cc —
a SubgraphProperty matching op chains, replacing each match with a
fused node).  Here the property pattern-matches ``FullyConnected ->
Activation(relu)`` chains in a Symbol and rewrites each into one
``FullyConnected(fused_relu=True)`` node — the epilogue fusion the int8
pass also uses.

Usage::

    import mxnet_tpu as mx
    mx.library.load("example/extensions/subgraph_ext.py")
    new_sym, new_params = sym.optimize_for(FCReluProperty(), params)
"""
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                                  "..", ".."))

from mxnet_tpu.symbol.subgraph import (OpChainSelector, SubgraphProperty,
                                       SubgraphSelector)
from mxnet_tpu.symbol.symbol import SymNode, Symbol


class FCReluProperty(SubgraphProperty):
    """Match FullyConnected -> relu; emit fused_relu FullyConnected."""

    name = "FUSE_FC_RELU"

    def create_selector(self) -> SubgraphSelector:
        class _Sel(OpChainSelector):
            def __init__(self):
                super().__init__(("FullyConnected", "Activation"))

            def select_output(self, cur, out_node):
                if cur.op == "FullyConnected" and out_node.op == "relu":
                    self._pos = 1
                    return True
                return super().select_output(cur, out_node)

            def filter(self, candidates):
                ops = {c.op for c in candidates}
                if "FullyConnected" not in ops or not \
                        (ops & {"Activation", "relu"}):
                    return []
                acts = [c for c in candidates
                        if c.op == "Activation" and
                        c.attrs.get("act_type", "relu") != "relu"]
                return [] if acts else candidates

        return _Sel()

    def create_subgraph_node(self, sub_sym: Symbol, subgraph_id: int,
                             params):
        order = sub_sym._topo()
        fc = next((n for n in order if n.op == "FullyConnected"), None)
        if fc is None or len(fc.inputs) < 2:
            return None                     # decline the match
        attrs = dict(fc.attrs)
        attrs["fused_relu"] = True
        node = SymNode("FullyConnected",
                       f"{fc.name}_fused_relu{subgraph_id}",
                       attrs, list(fc.inputs), num_outputs=1)
        return Symbol([(node, 0)])


if __name__ == "__main__":
    import numpy as onp

    import mxnet_tpu as mx

    x = mx.sym.var("x")
    w1 = mx.sym.var("w1")
    b1 = mx.sym.var("b1")
    w2 = mx.sym.var("w2")
    b2 = mx.sym.var("b2")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(x, w1, b1, num_hidden=16), act_type="relu")
    out = mx.sym.FullyConnected(h, w2, b2, num_hidden=4)

    R = onp.random.RandomState(0)
    params = {"w1": mx.nd.array(R.rand(16, 8).astype("f")),
              "b1": mx.nd.array(R.rand(16).astype("f")),
              "w2": mx.nd.array(R.rand(4, 16).astype("f")),
              "b2": mx.nd.array(R.rand(4).astype("f"))}
    data = {"x": mx.nd.array(R.rand(3, 8).astype("f")), **params}

    ref = out.bind(args=dict(data)).forward()[0].asnumpy()
    new_sym, new_params = out.optimize_for(FCReluProperty(), params)
    ops = [n.op for n in new_sym._topo()]
    assert "Activation" not in ops, ops     # the relu folded away
    fused = new_sym.bind(args={**{"x": data["x"]}, **new_params}) \
        .forward()[0].asnumpy()
    onp.testing.assert_allclose(fused, ref, rtol=1e-5, atol=1e-5)
    print(f"fused graph ops: {ops}")
    print("OK")
