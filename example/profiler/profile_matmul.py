"""Profiler demo — TPU-native analog of the reference's
``example/profiler/profiler_matmul.py`` / ``profiler_ndarray.py``.

Brackets a burst of matmuls and NDArray ops with ``mx.profiler``, adds user
scopes (Task/Event), and dumps a Chrome-trace JSON you can open at
chrome://tracing.  With ``--xla-trace DIR`` it also captures a real
XLA/TPU trace via ``jax.profiler`` (TensorBoard-viewable) — the TPU analog
of the reference's engine-level op bracketing.

    python example/profiler/profile_matmul.py --iters 20
"""
import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import nd, profiler


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--size", type=int, default=256)
    p.add_argument("--trace-file", default=None)
    p.add_argument("--xla-trace", default=None,
                   help="directory for a TensorBoard XLA trace (optional)")
    args = p.parse_args()

    trace = args.trace_file or os.path.join(tempfile.gettempdir(),
                                            "profile_matmul.json")
    profiler.set_config(filename=trace, profile_all=True,
                        xla_trace_dir=args.xla_trace)
    profiler.set_state("run")

    a = nd.random.uniform(shape=(args.size, args.size))
    b = nd.random.uniform(shape=(args.size, args.size))

    with profiler.Task("matmul-burst"):
        for _ in range(args.iters):
            a = nd.dot(a, b)
        a.wait_to_read()                    # sync point ends the burst

    with profiler.Task("elemwise-burst"):
        c = a
        for _ in range(args.iters):
            c = nd.tanh(c) + 0.5 * c
        c.wait_to_read()

    profiler.Marker("done").mark()           # instant user marker
    profiler.set_state("stop")
    profiler.dump()

    with open(trace) as f:
        events = json.load(f)["traceEvents"]
    names = {e.get("name") for e in events}
    print(f"trace: {trace} ({len(events)} events)")
    assert any("matmul-burst" in (n or "") for n in names), names
    assert any("dot" in (n or "") for n in names), "op events missing"
    print(profiler.dumps(reset=False)[:400])
    print("OK")


if __name__ == "__main__":
    main()
