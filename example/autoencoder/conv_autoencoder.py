"""Convolutional autoencoder — TPU-native analog of the reference's
``example/autoencoder/convolutional_autoencoder.ipynb``.

Encoder downsamples with strided convs, decoder upsamples with
``Conv2DTranspose``; trained with L2 reconstruction loss.  The whole
train step compiles to one XLA program once hybridized.

    python example/autoencoder/conv_autoencoder.py --steps 60
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def build_autoencoder(latent=16):
    net = gluon.nn.HybridSequential()
    net.add(
        # encoder: 28x28 -> 14x14 -> 7x7
        gluon.nn.Conv2D(8, kernel_size=3, strides=2, padding=1,
                        activation="relu"),
        gluon.nn.Conv2D(latent, kernel_size=3, strides=2, padding=1,
                        activation="relu"),
        # decoder: 7x7 -> 14x14 -> 28x28
        gluon.nn.Conv2DTranspose(8, kernel_size=4, strides=2, padding=1,
                                 activation="relu"),
        gluon.nn.Conv2DTranspose(1, kernel_size=4, strides=2, padding=1,
                                 activation="sigmoid"),
    )
    return net


def synthetic_images(n, seed=0):
    """Smooth blobs: each image is a Gaussian bump at a random location."""
    rng = onp.random.RandomState(seed)
    yy, xx = onp.mgrid[0:28, 0:28].astype("float32")
    cy = rng.uniform(6, 22, size=n)
    cx = rng.uniform(6, 22, size=n)
    imgs = onp.exp(-(((yy[None] - cy[:, None, None]) ** 2
                      + (xx[None] - cx[:, None, None]) ** 2) / 18.0))
    return imgs[:, None].astype("float32")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--batch-size", type=int, default=32)
    args = p.parse_args()

    x = synthetic_images(512)
    net = build_autoencoder()
    net.initialize()
    net.hybridize()
    loss_fn = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})

    first = last = None
    for step in range(args.steps):
        i = (step * args.batch_size) % (512 - args.batch_size)
        data = mx.nd.array(x[i:i + args.batch_size])
        with autograd.record():
            recon = net(data)
            loss = loss_fn(recon, data)
        loss.backward()
        trainer.step(data.shape[0])
        val = float(loss.mean().asnumpy())
        if first is None:
            first = val
        last = val
        if step % 20 == 0:
            print(f"step {step}: recon_loss={val:.5f}")

    print(f"recon_loss first={first:.5f} last={last:.5f}")
    assert last < first * 0.7, "reconstruction loss should drop"
    print("OK")


if __name__ == "__main__":
    main()
