"""Migrating a real Apache MXNet model into this framework.

A reference user has two files on disk:

    model-symbol.json      # nnvm graph JSON (mx.sym.save / export)
    model-0000.params      # binary NDArray map ("arg:..."/"aux:..." keys)

Both load directly — the JSON importer understands the nnvm layout
(3-element inputs/heads, string attrs, version upgrades) and resolves
every reference registration spelling (`_npi_*`, `_contrib_*`, legacy
internals), and the .params reader parses the reference's binary format.
The imported graph runs as ONE jitted XLA program on TPU.

Run:  python example/migration/import_mxnet_model.py [symbol.json params]
(defaults to the repo's checked-in reference-format fixture).
"""
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import SymbolBlock

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
DEFAULT_JSON = os.path.join(REPO, "tests", "fixtures",
                            "ref_cnn-symbol.json")
DEFAULT_PARAMS = os.path.join(REPO, "tests", "fixtures",
                              "ref_cnn-0000.params")


def main():
    if len(sys.argv) == 1:
        sym_file, param_file = DEFAULT_JSON, DEFAULT_PARAMS
    elif len(sys.argv) == 3:
        sym_file, param_file = sys.argv[1], sys.argv[2]
    else:
        sys.exit("usage: import_mxnet_model.py [model-symbol.json "
                 "model-0000.params]  (both or neither)")

    # 1. the one-call path (reference gluon.SymbolBlock.imports contract)
    net = SymbolBlock.imports(sym_file, input_names=["data"],
                              param_file=param_file)
    x = nd.array(onp.random.RandomState(0)
                 .rand(2, 3, 8, 8).astype(onp.float32))
    out = net(x)
    print("SymbolBlock.imports ->", out.shape, "on", mx.current_context())

    # 2. the symbol-level path: inspect, then re-export in EITHER format
    sym = mx.sym.load(sym_file)
    print("arguments:", sym.list_arguments())
    sym.save("/tmp/migrated-symbol.json", ref_format=True)   # nnvm layout
    sym.save("/tmp/migrated_native-symbol.json")             # native layout
    print("re-exported both formats under /tmp/")

    # 3. params round-trip: read reference binary, write it back
    params = nd.load(param_file)
    nd.save_legacy("/tmp/migrated-0000.params", params)
    print("params round-tripped:", len(params), "tensors")
    print("MIGRATION_OK")


if __name__ == "__main__":
    main()
