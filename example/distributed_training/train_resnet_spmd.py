"""SPMD data-parallel ResNet training over a device mesh.

The reference's example/distributed_training uses Horovod/kvstore dist
workers; the TPU-native answer is one jitted train step whose gradient
all-reduce is a sharding-induced XLA collective over the mesh
(kvstore='tpu' north star, SURVEY §2.3).  Runs identically on real chips
and on the virtual CPU mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python example/distributed_training/train_resnet_spmd.py --dp 8
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import parallel as par
from mxnet_tpu.gluon.model_zoo import vision


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--dp", type=int, default=0,
                    help="data-parallel width (0 = all devices)")
    ap.add_argument("--batch-size", type=int, default=32,
                    help="GLOBAL batch (split across dp)")
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--checkpoint-dir", default="")
    ap.add_argument("--engine", default="sharded",
                    choices=("sharded", "trainer"),
                    help="'sharded' = explicit ShardedTrainer/plan API; "
                    "'trainer' = the unchanged Gluon Trainer with "
                    "kvstore='tpu' (mesh sharding inside compile_step, "
                    "MXNET_SPMD_MESH resolves the mesh)")
    args = ap.parse_args()

    import jax

    dp = args.dp or len(jax.devices())
    mesh = par.make_mesh({"dp": dp})
    print(f"mesh: dp={dp} over {len(jax.devices())} {jax.default_backend()} "
          f"devices")

    net = vision.get_model(args.model, classes=args.classes)
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((1, 3, args.image_size, args.image_size)))
    ce = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    if args.engine == "trainer":
        # the kvstore='tpu' path: EXISTING Gluon Trainer code, mesh
        # sharding happens inside the one donated compiled step
        os.environ["MXNET_SPMD_MESH"] = str(dp)
        trainer = mx.gluon.Trainer(
            net.collect_params(), "sgd",
            {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4},
            kvstore="tpu")
        step = trainer.compile_step(
            net, lambda n, x, l: ce(n(x), l).mean())
        rng = onp.random.RandomState(0)
        data = mx.nd.array(rng.rand(args.batch_size, 3, args.image_size,
                                    args.image_size).astype(onp.float32))
        label = mx.nd.array(rng.randint(
            0, args.classes, (args.batch_size,)).astype(onp.int32))
        loss0 = float(step(data, label,
                           batch_size=args.batch_size).asnumpy())
        tic = time.time()
        for _s in range(args.steps):
            loss = step(data, label, batch_size=args.batch_size)
        loss = float(loss.asnumpy())
        dt = time.time() - tic
        assert step.last_step_compiled, step.last_fallback_reason
        w = net.collect_params()["features.0.weight"] \
            if "features.0.weight" in net.collect_params() else \
            next(iter(net.collect_params().values()))
        print(f"params replicated over "
              f"{len(w.data()._data.sharding.device_set)} devices")
        print(f"loss {loss0:.4f} -> {loss:.4f}, "
              f"{args.batch_size * args.steps / dt:.1f} img/s global")
        assert loss < loss0, "loss did not decrease"
        print("OK")
        return

    tr = par.ShardedTrainer(
        net, lambda o, l: ce(o, l).mean(), mesh, optimizer="sgd",
        optimizer_params={"lr": 0.1, "momentum": 0.9, "wd": 1e-4})

    ckpt = None
    if args.checkpoint_dir:
        ckpt = par.CheckpointManager(args.checkpoint_dir, keep=2)

    rng = onp.random.RandomState(0)
    data = rng.rand(args.batch_size, 3, args.image_size,
                    args.image_size).astype(onp.float32)
    label = rng.randint(0, args.classes, (args.batch_size,)).astype(onp.int32)
    data, label = tr.stage(data, label)   # host -> sharded device arrays

    loss0 = float(tr.step(data, label))
    tic = time.time()
    loss = loss0
    for s in range(args.steps):
        loss = tr.step(data, label)
        if ckpt is not None and (s + 1) % 4 == 0:
            ckpt.save(s + 1, tr.params)
    dt = time.time() - tic
    print(f"loss {loss0:.4f} -> {float(loss):.4f}, "
          f"{args.batch_size * args.steps / dt:.1f} img/s global")
    if ckpt is not None:
        ckpt.wait()
        print(f"checkpoints: steps {ckpt.all_steps()}")
    assert float(loss) < loss0, "loss did not decrease"
    print("OK")


if __name__ == "__main__":
    main()
