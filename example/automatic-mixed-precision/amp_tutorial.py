"""Automatic mixed precision — TPU-native analog of the reference's
``example/automatic-mixed-precision`` tutorial (its AMP SSD-finetune demo).

Two AMP entry points, same as the reference:

1. ``amp.init()`` — global cast policy: matmul/conv-class ops run in the
   low-precision dtype (bfloat16, the TPU MXU's native type; fp16+LossScaler
   also supported for parity), reductions stay fp32.
2. ``amp.convert_hybrid_block(net)`` — convert a trained fp32 model for
   low-precision *inference*.

Trains a small convnet under AMP (step 1), converts it (step 2), and checks
the converted model agrees with the fp32 one to bf16 tolerance.

    python example/automatic-mixed-precision/amp_tutorial.py --steps 60
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import amp, autograd, gluon


def build_net():
    net = gluon.nn.HybridSequential()
    net.add(
        gluon.nn.Conv2D(8, kernel_size=3, activation="relu"),
        gluon.nn.MaxPool2D(pool_size=2),
        gluon.nn.Flatten(),
        gluon.nn.Dense(32, activation="relu"),
        gluon.nn.Dense(10),
    )
    return net


def synthetic_digits(n, seed=0):
    rng = onp.random.RandomState(seed)
    y = rng.randint(0, 10, size=n)
    x = rng.uniform(0.0, 0.15, size=(n, 1, 28, 28)).astype("float32")
    for i, k in enumerate(y):
        r, c = divmod(int(k), 4)
        x[i, 0, 7 * r:7 * r + 7, 7 * c:7 * c + 7] += 0.8
    return x, y.astype("int32")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--dtype", default="bfloat16",
                   choices=["bfloat16", "float16"])
    args = p.parse_args()

    x, y = synthetic_digits(1024)

    # ---- 1. AMP training -------------------------------------------------
    amp.init(target_dtype=args.dtype)
    net = build_net()
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9},
                            update_on_kvstore=False)
    if args.dtype == "float16":
        amp.init_trainer(trainer)       # dynamic loss scaling for fp16

    for step in range(args.steps):
        i = (step * args.batch_size) % (1024 - args.batch_size)
        data = mx.nd.array(x[i:i + args.batch_size])
        label = mx.nd.array(y[i:i + args.batch_size])
        with autograd.record():
            loss = loss_fn(net(data), label)
            if args.dtype == "float16":
                with amp.scale_loss(loss, trainer) as scaled:
                    scaled.backward()
            else:
                loss.backward()
        trainer.step(data.shape[0])
        if step % 20 == 0:
            print(f"step {step}: loss={loss.mean().asnumpy():.4f}")

    acc = float((net(mx.nd.array(x)).asnumpy().argmax(axis=1) == y).mean())
    amp.uninit()
    print(f"AMP-trained accuracy={acc:.3f}")
    assert acc > 0.9

    # ---- 2. convert a trained net for low-precision inference ----------
    ref = net(mx.nd.array(x[:64])).asnumpy()    # fp32 answers BEFORE casting
    lp_net = amp.convert_hybrid_block(net, target_dtype=args.dtype)
    low_out = lp_net(mx.nd.array(x[:64]))
    assert args.dtype in str(low_out.dtype), low_out.dtype
    low = low_out.asnumpy().astype("float32")
    err = float(onp.max(onp.abs(ref - low)) / (onp.max(onp.abs(ref)) + 1e-6))
    print(f"fp32-vs-{args.dtype} converted-model relative error={err:.4f}")
    assert err < 0.1, "converted model should agree to low-precision tolerance"
    print("OK")


if __name__ == "__main__":
    main()
