"""Multi-task learning — TPU-native analog of the reference's
``example/multi-task/multi-task-learning.ipynb``.

One shared convolutional trunk, two heads: 10-way digit classification and
binary odd/even.  Both losses are summed and backpropagated through the
shared trunk in a single backward pass (one XLA program when hybridized).

    python example/multi-task/multi_task_mnist.py --steps 80
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


class MultiTaskNet(gluon.HybridBlock):
    def __init__(self):
        super().__init__()
        self.trunk = gluon.nn.HybridSequential()
        self.trunk.add(
            gluon.nn.Conv2D(8, kernel_size=3, activation="relu"),
            gluon.nn.MaxPool2D(pool_size=2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(48, activation="relu"),
        )
        self.digit_head = gluon.nn.Dense(10)
        self.parity_head = gluon.nn.Dense(1)

    def forward(self, x):
        h = self.trunk(x)
        return self.digit_head(h), self.parity_head(h)


def synthetic_digits(n, seed=0):
    rng = onp.random.RandomState(seed)
    y = rng.randint(0, 10, size=n)
    x = rng.uniform(0.0, 0.15, size=(n, 1, 28, 28)).astype("float32")
    for i, k in enumerate(y):
        r, c = divmod(int(k), 4)
        x[i, 0, 7 * r:7 * r + 7, 7 * c:7 * c + 7] += 0.8
    return x, y.astype("int32")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=80)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--parity-weight", type=float, default=0.5)
    args = p.parse_args()

    x, y = synthetic_digits(1024)
    parity = (y % 2).astype("float32")

    net = MultiTaskNet()
    net.initialize()
    digit_loss = gluon.loss.SoftmaxCrossEntropyLoss()
    parity_loss = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2})

    for step in range(args.steps):
        i = (step * args.batch_size) % (1024 - args.batch_size)
        data = mx.nd.array(x[i:i + args.batch_size])
        dlabel = mx.nd.array(y[i:i + args.batch_size])
        plabel = mx.nd.array(parity[i:i + args.batch_size])
        with autograd.record():
            dlogits, plogits = net(data)
            loss = (digit_loss(dlogits, dlabel)
                    + args.parity_weight
                    * parity_loss(plogits.reshape(-1), plabel))
        loss.backward()
        trainer.step(data.shape[0])
        if step % 20 == 0:
            print(f"step {step}: joint_loss={loss.mean().asnumpy():.4f}")

    dlogits, plogits = net(mx.nd.array(x))
    digit_acc = float((dlogits.asnumpy().argmax(axis=1) == y).mean())
    parity_acc = float(
        ((plogits.asnumpy().reshape(-1) > 0) == (parity > 0.5)).mean())
    print(f"digit accuracy={digit_acc:.3f} parity accuracy={parity_acc:.3f}")
    assert digit_acc > 0.9 and parity_acc > 0.9
    print("OK")


if __name__ == "__main__":
    main()
