"""Matrix factorization recommender — TPU-native analog of the reference's
``example/recommenders/matrix_fact.py`` (MovieLens MF demo).

Classic embedding-dot-product MF: rating(u, i) ≈ <p_u, q_i> + b_u + b_i,
trained with L2 loss on observed entries.  Embedding lookups become XLA
gathers; with a real dataset the user/item gradient rows are sparse — the
framework's ``sgd(lazy_update=True)`` skips untouched rows the same way the
reference's row_sparse path does.

Uses a synthetic low-rank ratings matrix (zero-egress environment), so the
model can drive train RMSE toward the noise floor — the assertion checks
exactly that.

    python example/recommenders/matrix_fact.py --steps 200
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


class MFNet(gluon.HybridBlock):
    def __init__(self, n_users, n_items, rank=8):
        super().__init__()
        self.user = gluon.nn.Embedding(n_users, rank)
        self.item = gluon.nn.Embedding(n_items, rank)
        self.user_bias = gluon.nn.Embedding(n_users, 1)
        self.item_bias = gluon.nn.Embedding(n_items, 1)

    def forward(self, uid, iid):
        dot = (self.user(uid) * self.item(iid)).sum(axis=-1)
        return dot + self.user_bias(uid).reshape(-1) \
                   + self.item_bias(iid).reshape(-1)


def synthetic_ratings(n_users, n_items, n_obs, rank=4, seed=0):
    rng = onp.random.RandomState(seed)
    p = rng.normal(scale=0.8, size=(n_users, rank))
    q = rng.normal(scale=0.8, size=(n_items, rank))
    uid = rng.randint(0, n_users, size=n_obs)
    iid = rng.randint(0, n_items, size=n_obs)
    r = (p[uid] * q[iid]).sum(axis=1) + rng.normal(scale=0.1, size=n_obs)
    return uid.astype("int32"), iid.astype("int32"), r.astype("float32")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--users", type=int, default=200)
    p.add_argument("--items", type=int, default=300)
    p.add_argument("--rank", type=int, default=8)
    args = p.parse_args()

    uid, iid, r = synthetic_ratings(args.users, args.items, n_obs=8192)
    net = MFNet(args.users, args.items, rank=args.rank)
    net.initialize(mx.init.Normal(0.05))
    loss_fn = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-2})

    n = len(r)
    for step in range(args.steps):
        i = (step * args.batch_size) % (n - args.batch_size)
        bu = mx.nd.array(uid[i:i + args.batch_size])
        bi = mx.nd.array(iid[i:i + args.batch_size])
        br = mx.nd.array(r[i:i + args.batch_size])
        with autograd.record():
            loss = loss_fn(net(bu, bi), br)
        loss.backward()
        trainer.step(args.batch_size)
        if step % 40 == 0:
            print(f"step {step}: loss={loss.mean().asnumpy():.4f}")

    pred = net(mx.nd.array(uid), mx.nd.array(iid)).asnumpy()
    rmse = float(onp.sqrt(onp.mean((pred - r) ** 2)))
    print(f"train RMSE={rmse:.4f}")
    assert rmse < 0.5, "MF should recover the low-rank structure"
    print("OK")


if __name__ == "__main__":
    main()
