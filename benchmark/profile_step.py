"""Capture an XLA op-level time breakdown of the ResNet train step.

Usage:
    python benchmark/profile_step.py [--model resnet50_v1] [--batch 128]
        [--layout NHWC] [--s2d 1] [--bf16 1] [--steps 5] [--top 30]
        [--step-mode {sharded,eager,compiled}]

``--step-mode eager`` profiles the Gluon eager-tape train step
(record/backward/trainer.step); ``--step-mode compiled`` profiles the
same model through ``Trainer.compile_step`` (cached_step.TrainStep, one
donated program) — the A/B for the whole-step fusion claim.  Each run
appends its header + by-kind table to
``benchmark/artifacts/profile_step_<mode>.log``.

Writes a jax.profiler trace to --logdir (default /tmp/jaxprof) and then
parses the Chrome-trace export (plugins/profile/*/…trace.json.gz) to print
the top ops by total self time on the device track, grouped by a coarse
kind (conv / fusion / reduce / copy-layout / matmul / other).  This is the
measurement tool behind docs/PERF.md's MFU analysis; it exists so kernel
work is guided by the actual step texture rather than FLOP models.

Reference analog: the profiler flow of docs/static_site/.../profiler.md
(reference python/mxnet/profiler.py) — here the source of truth is the
XLA device trace rather than engine-push brackets.
"""
import argparse
import collections
import glob
import gzip
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# the persistent XLA cache (bench.py sets the same) — a profile run of the
# bench's own step must hit the bench's cache, not redo a cold multi-minute
# tunnel compile.  The env var alone is NOT enough here: on tunnel-attached
# hosts sitecustomize imports jax before this module body runs and jax reads
# the var at import only, so the config is also set through jax.config.
_CACHE_DIR = os.path.abspath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _CACHE_DIR)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "5")
import jax  # noqa: E402

if jax.config.jax_compilation_cache_dir is None:
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)


def build_step(model_name, batch, layout, s2d, bf16, img=224):
    import jax
    import jax.numpy as jnp
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import parallel as par
    from mxnet_tpu.gluon.model_zoo import vision

    kw = {}
    if model_name.startswith("resnet"):
        kw = {"layout": layout, "input_layout": layout, "stem_s2d": s2d}
    net = vision.get_model(model_name, classes=1000, **kw)
    net.initialize(mx.init.Xavier())
    probe = (1, img, img, 3) if layout == "NHWC" else (1, 3, img, img)
    cpus = jax.devices("cpu") if jax.default_backend() != "cpu" else None
    if cpus:
        with jax.default_device(cpus[0]):
            net(mx.nd.zeros(probe))
    else:
        net(mx.nd.zeros(probe))
    ce = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    mesh = par.make_mesh({"dp": 1})
    tr = par.ShardedTrainer(
        net, lambda o, l: ce(o, l).mean(), mesh, optimizer="sgd",
        optimizer_params={"lr": 0.1, "momentum": 0.9, "wd": 1e-4},
        compute_dtype=jnp.bfloat16 if bf16 else None)
    rng = onp.random.RandomState(0)
    shape = (batch, img, img, 3) if layout == "NHWC" else (batch, 3, img, img)
    data = rng.rand(*shape).astype(onp.float32)
    label = rng.randint(0, 1000, (batch,)).astype(onp.int32)
    data, label = tr.stage(data, label)
    return tr, data, label


def build_gluon_step(model_name, batch, layout, s2d, bf16, step_mode,
                     img=224):
    """Eager-tape vs compiled-TrainStep A/B builder (--step-mode): the
    same Gluon model/optimizer driven either through record()/backward()/
    trainer.step() (one XLA program per tape node + group programs) or
    through trainer.compile_step() (ONE donated program).  This is the
    measurement lane for the PR-3 fusion claim: the by-kind table should
    show the reduce+copy share dropping in compiled mode, where XLA sees
    BN batch-stats forward and the dy reductions backward together."""
    import jax
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import amp, gluon
    from mxnet_tpu.gluon.model_zoo import vision

    kw = {}
    if model_name.startswith("resnet"):
        kw = {"layout": layout, "input_layout": layout, "stem_s2d": s2d}
    net = vision.get_model(model_name, classes=1000, **kw)
    net.initialize(mx.init.Xavier())
    if bf16:
        amp.init("bfloat16")
    probe = (1, img, img, 3) if layout == "NHWC" else (1, 3, img, img)
    net(mx.nd.zeros(probe))
    net.hybridize()
    ce = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(
        net.collect_params(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4})
    rng = onp.random.RandomState(0)
    shape = (batch, img, img, 3) if layout == "NHWC" \
        else (batch, 3, img, img)
    data = mx.nd.array(rng.rand(*shape).astype(onp.float32))
    label = mx.nd.array(
        rng.randint(0, 1000, (batch,)).astype(onp.int32))
    loss_fn = lambda n, d, l: ce(n(d), l).mean()
    if step_mode == "compiled":
        step = trainer.compile_step(net, loss_fn)

        def run_step():
            return step(data, label, batch_size=batch)
    else:
        def run_step():
            with mx.autograd.record():
                loss = loss_fn(net, data, label)
            loss.backward()
            trainer.step(batch)
            return loss

    return run_step


def build_decode_step(batch, seq):
    """``--step-mode decode``: profile the continuous-batching decode
    program (serving_decode.GenerativeEngine) — ``run_step()`` is one
    concurrent token-generation burst (``batch`` requests × 4 tokens),
    so the trace shows the ONE fused decode program's page gather /
    attention / scatter texture rather than per-request host noise."""
    import threading

    import numpy as onp

    from mxnet_tpu import serving_decode as sd

    model = sd.TinyCausalLM(vocab=512, d_model=256, n_layers=4,
                            n_heads=8, max_seq=max(seq, 64))
    pool = sd.PagePool(pages=max(64, batch * (seq // 16 + 2)), page=16)
    eng = sd.GenerativeEngine(model, pool=pool, max_rows=batch,
                              name="profile")
    eng.warmup(max_len=seq)
    rng = onp.random.RandomState(0)
    prompts = [rng.randint(0, 512, size=seq // 2).tolist()
               for _ in range(batch)]

    def run_step():
        errs = []

        def fire(p):
            try:
                eng.generate(p, max_new_tokens=4)
            except BaseException as e:
                errs.append(e)
        threads = [threading.Thread(target=fire, args=(p,))
                   for p in prompts]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise errs[0]
        return float(batch * 4)          # tokens generated

    return run_step


def classify(name):
    n = name.lower()
    if "conv" in n:
        return "conv"
    if n.startswith("fusion") or ".fusion" in n:
        return "fusion"
    if "reduce" in n:
        return "reduce"
    if "copy" in n or "transpose" in n or "bitcast" in n:
        return "copy/layout"
    if "dot" in n or "matmul" in n:
        return "matmul"
    if "dynamic" in n or "scatter" in n or "gather" in n:
        return "gather/scatter"
    return "other"


def parse_trace(logdir, top, save_path=None):
    """Print the by-kind/by-op device-time tables; with ``save_path``
    also append them to an artifact log (the --step-mode A/B evidence)."""
    lines = []

    def emit(*parts):
        line = " ".join(str(p) for p in parts)
        lines.append(line)
        print(line)

    def flush():
        if save_path:
            os.makedirs(os.path.dirname(save_path), exist_ok=True)
            with open(save_path, "a") as f:
                f.write("\n".join(lines) + "\n")
            print(f"(appended to {save_path})")

    paths = sorted(glob.glob(os.path.join(
        logdir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not paths:
        emit("no trace.json.gz found under", logdir)
        flush()
        return
    with gzip.open(paths[-1], "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    # device-track pids: their thread names look like "XLA Ops" / TensorFlow
    # op tracks; host python tracks are excluded by requiring the 'dur' field
    # and picking pids whose process name mentions TPU / device.
    pid_names = {}
    tid_names = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pid_names[ev["pid"]] = ev["args"].get("name", "")
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            tid_names[(ev["pid"], ev.get("tid"))] = ev["args"].get("name", "")
    device_pids = {p for p, n in pid_names.items()
                   if any(k in n for k in ("TPU", "Device", "/device:"))}
    if not device_pids:
        emit("WARNING: no device track found in the trace — counting ALL "
             "tracks (host rows included); op totals are not device time")
    per_op = collections.Counter()
    per_kind = collections.Counter()
    # per-fusion cost accounting (the ROADMAP-2 MFU substrate): XLA op
    # events carry per-execution "flops" / "bytes accessed" args on
    # device traces — summed per op name they give each fusion's
    # achieved FLOP/s and HBM bandwidth, which is what decides whether
    # a fusion is compute- or memory-bound and worth a Pallas kernel
    per_flops = collections.Counter()
    per_bytes = collections.Counter()
    total = 0.0
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        if device_pids and ev.get("pid") not in device_pids:
            continue
        tname = tid_names.get((ev.get("pid"), ev.get("tid")), "")
        # XLA op-level rows live on "XLA Ops"-style threads; step/module
        # rows would double count
        if tname and ("step" in tname.lower() or "module" in tname.lower()):
            continue
        dur = ev["dur"]  # us
        per_op[ev["name"]] += dur
        per_kind[classify(ev["name"])] += dur
        total += dur
        for k, v in (ev.get("args") or {}).items():
            lk = k.lower()
            try:
                val = float(str(v).replace(",", ""))
            except (TypeError, ValueError):
                continue
            if "flop" in lk and "util" not in lk:
                per_flops[ev["name"]] += val
            elif "bytes" in lk and ("accessed" in lk or lk == "bytes"):
                per_bytes[ev["name"]] += val
    emit(f"\n== device op time (total {total/1e3:.2f} ms across "
         f"{len(per_op)} op names; trace {os.path.basename(paths[-1])}) ==")
    emit("\n-- by kind --")
    for kind, dur in per_kind.most_common():
        emit(f"  {kind:<16} {dur/1e3:10.2f} ms  "
             f"{100*dur/max(total,1e-9):5.1f}%")
    emit(f"\n-- top {top} ops --")
    for name, dur in per_op.most_common(top):
        emit(f"  {dur/1e3:9.2f} ms  {100*dur/max(total,1e-9):5.1f}%  "
             f"{name[:110]}")
    # top-N FUSION cost table: time + bytes-accessed + flops columns,
    # with derived GFLOP/s / GB/s so the top offender's roofline
    # position reads straight off the log
    fusions = [(n, d) for n, d in per_op.most_common()
               if classify(n) == "fusion"][:top]
    if fusions:
        emit(f"\n-- top {len(fusions)} fusions by device time "
             "(bytes/flops from trace args; '-' = not reported) --")
        emit(f"  {'ms':>9} {'%':>5} {'GFLOP':>9} {'GB':>8} "
             f"{'GFLOP/s':>9} {'GB/s':>8}  name")
        for name, dur in fusions:
            fl, by = per_flops.get(name), per_bytes.get(name)
            sec = dur / 1e6
            emit("  "
                 f"{dur/1e3:9.2f} {100*dur/max(total,1e-9):5.1f} "
                 + (f"{fl/1e9:9.2f} " if fl else f"{'-':>9} ")
                 + (f"{by/1e9:8.3f} " if by else f"{'-':>8} ")
                 + (f"{fl/sec/1e9:9.1f} " if fl and sec else f"{'-':>9} ")
                 + (f"{by/sec/1e9:8.1f}  " if by and sec
                    else f"{'-':>8}  ")
                 + name[:80])
    else:
        emit("\n-- no fusion ops in this trace (CPU traces name kernels "
             "differently; run on device for the fusion table) --")
    flush()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50_v1")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--layout", default="NHWC")
    ap.add_argument("--s2d", type=int, default=1)
    ap.add_argument("--bf16", type=int, default=1)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--logdir", default="/tmp/jaxprof")
    ap.add_argument("--step-mode", default="sharded",
                    choices=("sharded", "eager", "compiled", "decode"),
                    help="sharded = the ShardedTrainer compiled step "
                         "(historical default); eager vs compiled A/B the "
                         "Gluon tape against cached_step.TrainStep — the "
                         "reduce+copy share should drop in compiled mode; "
                         "decode profiles the serving_decode continuous-"
                         "batching token-decode program (--batch rows, "
                         "BENCH_SEQ-ish --seq context)")
    ap.add_argument("--seq", type=int, default=128,
                    help="decode mode: max context length (prompt seq/2)")
    ap.add_argument("--parse-only", action="store_true",
                    help="just parse an existing --logdir trace")
    args = ap.parse_args()

    artifact = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "artifacts",
        f"profile_step_{args.step_mode}.log")
    if not args.parse_only:
        import jax
        if args.step_mode == "sharded":
            tr, data, label = build_step(args.model, args.batch,
                                         args.layout, bool(args.s2d),
                                         bool(args.bf16))
            run_step = lambda: tr.step(data, label, sync=False)
            print("compiling…")
            t0 = time.perf_counter()
            tr.step(data, label)
            print(f"compiled in {time.perf_counter()-t0:.1f}s; warming")
        elif args.step_mode == "decode":
            # decode rows default smaller than a train batch; the
            # img/s figures below then read as requests/s-ish (each
            # run_step = batch requests x 4 tokens)
            args.batch = args.batch if args.batch != 128 else 16
            run_step = build_decode_step(args.batch, args.seq)
            print(f"warming (decode step, {args.batch} rows)…")
        else:
            run_step = build_gluon_step(args.model, args.batch,
                                        args.layout, bool(args.s2d),
                                        bool(args.bf16), args.step_mode)
            print(f"warming ({args.step_mode} step)…")
        for _ in range(2):
            loss = run_step()
        loss = getattr(loss, "asnumpy", lambda: loss)()
        float(loss if getattr(loss, "ndim", 0) == 0 else loss.ravel()[0])
        os.makedirs(args.logdir, exist_ok=True)
        jax.profiler.start_trace(args.logdir)
        t0 = time.perf_counter()
        for _ in range(args.steps):
            loss = run_step()
        loss = getattr(loss, "asnumpy", lambda: loss)()
        v = float(loss if getattr(loss, "ndim", 0) == 0
                  else loss.ravel()[0])
        dt = time.perf_counter() - t0
        jax.profiler.stop_trace()
        print(f"[{args.step_mode}] {args.steps} steps in {dt*1e3:.1f} ms "
              f"({args.batch*args.steps/dt:.1f} img/s, loss {v:.3f})")
        os.makedirs(os.path.dirname(artifact), exist_ok=True)
        with open(artifact, "a") as f:
            f.write(f"\n== {time.strftime('%Y-%m-%d %H:%M:%S')} "
                    f"{args.model} bs{args.batch} {args.layout} "
                    f"bf16={args.bf16} mode={args.step_mode}: "
                    f"{args.steps} steps {dt*1e3:.1f} ms "
                    f"({args.batch*args.steps/dt:.1f} img/s) ==\n")
    parse_trace(args.logdir, args.top, save_path=artifact)


if __name__ == "__main__":
    main()
