"""Compiled-batch transform vs per-sample Python transform.

The TPU-native answer to the reference's C++ ``LazyTransformDataset`` +
``ThreadedDataLoader`` (src/io/dataset.cc:542, src/io/dataloader.cc:35) is
``dataset.transform(fn, compiled=True)``: the DataLoader batches RAW
samples and runs ``fn`` once per batch as a jitted XLA program.  This
bench times both paths over an ImageRecord-shaped pipeline (decode-free:
uniform HWC float images) and prints the speedup.

    python benchmark/transform_bench.py --n 2048 --batch-size 64
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu.gluon import data as gdata

MEAN = onp.array([0.485, 0.456, 0.406], onp.float32).reshape(3, 1, 1)
STD = onp.array([0.229, 0.224, 0.225], onp.float32).reshape(3, 1, 1)


def transform_fn(img, label):
    """ToTensor + normalize + pad-crop — mx ops only, so it traces."""
    x = mx.nd.transpose(img, axes=(2, 0, 1)) / 255.0
    x = (x - mx.nd.array(MEAN)) / mx.nd.array(STD)
    return x, label


def run(loader, epochs=1):
    t0 = time.time()
    n = 0
    for _ in range(epochs):
        for data, label in loader:
            n += data.shape[0]
    # fence: read a value so async work drains
    float(data.asnumpy().ravel()[0])
    return n / (time.time() - t0)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=2048)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--img", type=int, default=64)
    args = p.parse_args()

    rng = onp.random.RandomState(0)
    imgs = rng.randint(0, 255, size=(args.n, args.img, args.img, 3)) \
        .astype("float32")
    labels = rng.randint(0, 10, size=args.n).astype("int32")
    ds = gdata.ArrayDataset(mx.nd.array(imgs), mx.nd.array(labels))

    per_sample = gdata.DataLoader(ds.transform(transform_fn),
                                  batch_size=args.batch_size)
    compiled = gdata.DataLoader(ds.transform(transform_fn, compiled=True),
                                batch_size=args.batch_size)

    run(compiled)                       # warm both (compile once)
    run(per_sample)
    ps = run(per_sample)
    cp = run(compiled)
    print(f"per-sample python transform: {ps:,.0f} img/s")
    print(f"compiled batch transform:    {cp:,.0f} img/s")
    print(f"speedup: {cp / ps:.2f}x")


if __name__ == "__main__":
    main()
