"""Pipelined-train-loop A/B: the async pipeline engine (PR 5) vs the
synchronous loop, SAME model / batches / optimizer.

Synchronous lane (the pre-pipeline loop): per batch — a blocking
device_put (`mx.nd.array`), one compiled train-step dispatch, and a
host-side metric update (`MXNET_METRIC_DEVICE=0`, the silent per-batch
``float()`` sync).  Pipelined lane: `engine.prefetch` stages batch N+1
into HBM on the transfer thread while step N runs, and the Loss metric
accumulates ON DEVICE (host read only at the final ``.get()``).

Both lanes run under a ``profiler.StepTimeline``; the headline metric is
``device_idle_gap_us`` — mean per-step host time OUTSIDE the dispatch
phase (the window in which the one-program-per-step device can run dry).
The lane also reports the steady-state dispatch-ahead depth (how many
batches were already staged each time the loop took one — the PR-5
acceptance bar is >= 2) and host syncs per step (budget: 0 in the
pipelined steady state).

Counter-based + wall-clock: equally meaningful on the CPU backend,
honest about platform either way.

Usage: python benchmark/pipeline_latency.py [--pipeline-only] [--json]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STEPS = int(os.environ.get("PIPELINE_STEPS", "30"))
BATCH = 32
FEAT = 64
DEPTH = 3


def _build():
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    class Net(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.d1 = nn.Dense(128, in_units=FEAT, activation="relu")
            self.d2 = nn.Dense(16, in_units=128)

        def forward(self, x):
            return self.d2(self.d1(x))

    net = Net()
    net.initialize(mx.init.Xavier())
    rng = onp.random.RandomState(0)
    for _n, p in sorted(net.collect_params().items()):
        p.data()._set_data(mx.nd.array(rng.randn(*p.shape) * 0.1)._data)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    loss_fn = lambda n, x, y: ((n(x) - y) ** 2).mean()
    return net, trainer, loss_fn


def _host_batches(seed=7, n=STEPS):
    import numpy as onp

    rng = onp.random.RandomState(seed)
    return [(rng.randn(BATCH, FEAT).astype(onp.float32),
             rng.randn(BATCH, 16).astype(onp.float32)) for _ in range(n)]


def _run_loop(pipelined: bool) -> dict:
    import mxnet_tpu as mx
    from mxnet_tpu import engine, metric, profiler
    from mxnet_tpu.ndarray import ndarray as _ndmod

    os.environ["MXNET_METRIC_DEVICE"] = "1" if pipelined else "0"
    try:
        net, trainer, loss_fn = _build()
        step = trainer.compile_step(net, loss_fn)
        batches = _host_batches()
        # warm: trace + compile outside the timed region
        wx, wy = batches[0]
        t_c = time.perf_counter()
        loss = step(mx.nd.array(wx), mx.nd.array(wy), batch_size=BATCH)
        float(loss.asnumpy().ravel()[0])
        compile_s = time.perf_counter() - t_c
        engine.waitall()

        loss_metric = metric.Loss()
        # warm the metric path too (the device kernel's first update
        # traces/compiles) — trace cost must not book as steady-state
        loss_metric.update(0, loss)
        loss_metric.get()
        loss_metric.reset()
        tl = profiler.StepTimeline("pipeline" if pipelined else "sync")
        pf = None
        if pipelined:
            pf = engine.DevicePrefetcher(iter(batches), depth=DEPTH)
            time.sleep(0.05)         # let the transfer thread fill HBM
            it = pf
        else:
            it = iter(batches)
        h0 = _ndmod.host_sync_count()
        ms0 = metric.host_sync_count()
        t_wall0 = time.perf_counter_ns()
        last = None
        for _ in range(len(batches)):
            with tl.phase("h2d"):
                if pipelined:
                    x, y = next(it)
                else:
                    hx, hy = next(it)
                    x, y = mx.nd.array(hx), mx.nd.array(hy)
            with tl.phase("dispatch"):
                last = step(x, y, batch_size=BATCH)
            with tl.phase("read"):
                loss_metric.update(0, last)
            tl.step()
        last.wait_to_read()          # device fence FIRST: the final fold
        # must not book the last step's in-flight compute as host time
        with tl.phase("read"):
            name, value = loss_metric.get()     # the ONE pipelined read
        wall_us = (time.perf_counter_ns() - t_wall0) / 1000.0
        out = tl.summary()
        out.update({
            "mode": "pipelined" if pipelined else "sync",
            "loss_metric": round(float(value), 6),
            "host_syncs_per_step":
                round((_ndmod.host_sync_count() - h0) / len(batches), 2),
            "metric_host_syncs":
                metric.host_sync_count() - ms0,
            "wall_us": round(wall_us, 1),
            "compiled": step.last_step_compiled,
            "compile_s": round(compile_s, 3),
        })
        if pf is not None:
            s = pf.stats()
            out["steady_ahead_depth"] = s["steady_ahead"]
            out["max_ahead_depth"] = s["max_ahead"]
            pf.close()
        return out
    finally:
        os.environ.pop("MXNET_METRIC_DEVICE", None)


def run() -> dict:
    import jax

    from mxnet_tpu import program_store

    from mxnet_tpu import telemetry

    tel0 = telemetry.snapshot()
    sync = _run_loop(False)
    pipe = _run_loop(True)
    gap_s, gap_p = sync["device_idle_gap_us"], pipe["device_idle_gap_us"]
    disk = program_store.disk_stats()
    return {
        "platform": jax.default_backend(),
        # full namespaced counter delta across both loops; the
        # hand-picked keys below stay as aliases for BENCH_* continuity
        "telemetry": {k: v for k, v in telemetry.delta(tel0).items()
                      if v},
        "steps": STEPS,
        "depth": DEPTH,
        "compile_s": round(sync["compile_s"] + pipe["compile_s"], 3),
        "cache_hits": disk["hits"],
        "cache_misses": disk["misses"],
        "sync": sync,
        "pipelined": pipe,
        "steady_ahead_depth": pipe.get("steady_ahead_depth", 0),
        "device_idle_gap_us": gap_p,
        "device_idle_gap_us_sync": gap_s,
        "idle_gap_reduction": round(gap_s / max(gap_p, 0.1), 2),
        "wall_speedup": round(sync["wall_us"] / max(pipe["wall_us"], 1), 3),
    }


def main():
    res = {"pipeline": run()}
    if "--json" in sys.argv:
        print(json.dumps(res), flush=True)
    else:
        p = res["pipeline"]
        print(f"platform {p['platform']}, {p['steps']} steps, "
              f"depth {p['depth']}")
        for mode in ("sync", "pipelined"):
            r = p[mode]
            print(f"  {mode:<10} idle-gap {r['device_idle_gap_us']:>8.1f} "
                  f"us/step  wall {r['wall_us_per_step']:>8.1f} us/step  "
                  f"host-syncs/step {r['host_syncs_per_step']}")
        print(f"  dispatch-ahead depth (steady) {p['steady_ahead_depth']}, "
              f"idle-gap reduction {p['idle_gap_reduction']}x, "
              f"wall speedup {p['wall_speedup']}x")


if __name__ == "__main__":
    main()
