#!/usr/bin/env python
"""Elastic-recovery bench lane: measure what a preemption actually costs.

Runs the `mxnet_tpu.drills` sigterm_drain scenario — a real SIGTERM mid
compiled-SPMD-step with async checkpointing and a depth-k prefetcher,
then a restart warm-started from the persistent compile cache — and
reports the recovery-time budget numbers ROADMAP 4(c) asks for:

- ``recovery_s``       checkpoint restore (degradation walk + load +
                       re-placement)
- ``recovery_wall_s``  restart process start -> first resumed step done
- ``steps_replayed``   steps re-executed after restore (graceful drain:
                       0 by contract)
- ``drain_s``          SIGTERM -> queues drained + final blocking save
- ``fresh_compiles`` / ``disk_hits``  restart's persistent-cache
                       behavior (warm recovery compiles nothing fresh)

``--json`` emits one machine-readable line (the bench.py ``elastic``
lane contract); the full namespaced telemetry snapshot of the RESUMED
process rides along like every other lane's.  Standalone:
``python benchmark/elastic_drill.py --json``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--scenario", default="sigterm_drain")
    ap.add_argument("--root", default=None,
                    help="drill workdir (default: fresh temp dir)")
    a = ap.parse_args()

    from mxnet_tpu.drills import run_drill

    root = a.root or tempfile.mkdtemp(prefix="mxnet-bench-elastic-")
    rep = run_drill(a.scenario, root)
    out = {
        "elastic": {
            "scenario": rep["scenario"],
            "ok": rep["ok"],
            "failures": rep["failures"],
            "recovery_s": rep.get("recovery_s"),
            "recovery_wall_s": rep.get("recovery_wall_s"),
            "steps_replayed": rep.get("steps_replayed"),
            "drain_s": rep.get("drain_s"),
            "fresh_compiles": rep.get("fresh_compiles"),
            "disk_hits": rep.get("disk_hits"),
            "restored_at": rep.get("restored_at"),
            "exit_code_c1": rep.get("exit_code_c1"),
            "leaked_tmp": rep.get("leaked_tmp", []),
            "drill_wall_s": rep.get("drill_wall_s"),
            "platform": "cpu",   # drill children force JAX_PLATFORMS=cpu
            "telemetry": rep.get("resume_telemetry"),
        }
    }
    if a.json:
        print(json.dumps(out, default=str))
    else:
        pretty = dict(out["elastic"])
        pretty.pop("telemetry", None)
        print(json.dumps(pretty, indent=2, default=str))
    return 0 if rep["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
