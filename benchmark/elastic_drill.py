#!/usr/bin/env python
"""Elastic-recovery bench lane: measure what a preemption actually costs.

Runs the `mxnet_tpu.drills` sigterm_drain scenario — a real SIGTERM mid
compiled-SPMD-step with async checkpointing and a depth-k prefetcher,
then a restart warm-started from the persistent compile cache — and
reports the recovery-time budget numbers ROADMAP 4(c) asks for:

- ``recovery_s``       checkpoint restore (degradation walk + load +
                       re-placement)
- ``recovery_wall_s``  restart process start -> first resumed step done
- ``steps_replayed``   steps re-executed after restore (graceful drain:
                       0 by contract)
- ``drain_s``          SIGTERM -> queues drained + final blocking save
- ``fresh_compiles`` / ``disk_hits``  restart's persistent-cache
                       behavior (warm recovery compiles nothing fresh)
- ``sentinel_overhead_pct``  ISSUE-13 training-integrity sentinel A/B:
                       median step time with the sentinel at its
                       default cadence (20) vs off, same process, same
                       compiled program — the digest rides an
                       in-program lax.cond, so the measured delta is
                       the real cost of attestation (acceptance:
                       < 1% on the train lane, evaluated on-chip)

``--json`` emits one machine-readable line (the bench.py ``elastic``
lane contract); the full namespaced telemetry snapshot of the RESUMED
process rides along like every other lane's.  Standalone:
``python benchmark/elastic_drill.py --json``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure_sentinel_overhead(steps: int = 150, every: int = 20) -> dict:
    """A/B the sentinel's cost on the drill workload, in-process: the
    SAME compiled program runs ``steps`` timed steps with no sentinel
    attached, then with a Sentinel at cadence ``every`` — the want-flag
    is a traced arg, so both phases dispatch one identical executable
    and the delta isolates the lax.cond digest branch + the deferred
    reads.  Median-of-batches timing so one scheduler hiccup cannot
    fake a regression."""
    import statistics
    import time

    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import drills, gluon, sentinel

    net = drills._drill_net(seed=0)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9},
                            kvstore="device")
    step = trainer.compile_step(net, drills._drill_loss)
    rng = onp.random.RandomState(0)
    x = mx.nd.array(rng.randn(drills.ROWS, 8).astype(onp.float32))
    y = mx.nd.array(rng.randn(drills.ROWS, 4).astype(onp.float32))

    def timed(n):
        # batches of 10 steps; per-batch wall / 10, median across
        samples = []
        for _ in range(n // 10):
            t0 = time.perf_counter()
            for _ in range(10):
                loss = step(x, y, batch_size=drills.ROWS)
            float(loss.asnumpy().ravel()[0])     # fence
            samples.append((time.perf_counter() - t0) / 10)
        return statistics.median(samples)

    for _ in range(10):                          # warm + state settle
        loss = step(x, y, batch_size=drills.ROWS)
    float(loss.asnumpy().ravel()[0])
    base_s = timed(steps)
    snt = sentinel.Sentinel(step=step, every=every)
    on_s = timed(steps)
    snt.flush()
    assert step.last_step_compiled, step.last_fallback_reason
    return {
        "sentinel_every": every,
        "step_us_off": round(base_s * 1e6, 2),
        "step_us_on": round(on_s * 1e6, 2),
        "sentinel_overhead_pct": round((on_s - base_s) / base_s * 100, 3),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--scenario", default="sigterm_drain")
    ap.add_argument("--root", default=None,
                    help="drill workdir (default: fresh temp dir)")
    a = ap.parse_args()

    from mxnet_tpu.drills import run_drill

    root = a.root or tempfile.mkdtemp(prefix="mxnet-bench-elastic-")
    rep = run_drill(a.scenario, root)
    rep["sentinel_ab"] = measure_sentinel_overhead()
    from mxnet_tpu import telemetry

    # the drill children each flushed a shard (drills._child_env sets
    # MXNET_TELEMETRY_DIR); flush the orchestrator's own so bench.py's
    # fleet merge sees every process of the drill
    telemetry.flush()
    out = {
        "elastic": {
            "scenario": rep["scenario"],
            "ok": rep["ok"],
            "failures": rep["failures"],
            "recovery_s": rep.get("recovery_s"),
            "recovery_wall_s": rep.get("recovery_wall_s"),
            "steps_replayed": rep.get("steps_replayed"),
            "drain_s": rep.get("drain_s"),
            "fresh_compiles": rep.get("fresh_compiles"),
            "disk_hits": rep.get("disk_hits"),
            "restored_at": rep.get("restored_at"),
            "exit_code_c1": rep.get("exit_code_c1"),
            "leaked_tmp": rep.get("leaked_tmp", []),
            "drill_wall_s": rep.get("drill_wall_s"),
            "sentinel_overhead_pct":
                rep["sentinel_ab"]["sentinel_overhead_pct"],
            "sentinel_ab": rep["sentinel_ab"],
            "platform": "cpu",   # drill children force JAX_PLATFORMS=cpu
            "telemetry": rep.get("resume_telemetry"),
        }
    }
    if a.json:
        print(json.dumps(out, default=str))
    else:
        pretty = dict(out["elastic"])
        pretty.pop("telemetry", None)
        print(json.dumps(pretty, indent=2, default=str))
    return 0 if rep["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
