"""Targeted TPU microbenchmarks behind docs/PERF.md's roofline analysis.

    python benchmark/microbench_tpu.py [--which all|dot|conv|bn|int8|
                                               fused|epilogue]

Measures, with the bench fencing discipline (warm + host read, fenced
timed region):
  - dot:      8192^3 matmul, bf16 vs s8xs8->s32 (does int8 hit the 2x MXU?)
  - conv:     a resnet-core conv chain, bf16 NHWC vs int8 NHWC, with the
              requantize epilogue on/off (where does the int8 lane lose?)
  - bn:       conv chain with batch-stat BatchNorm vs without (what do the
              stats reductions + normalize passes cost the train step?)
  - fused:    the round-5 matmul+BN-stats producer kernel vs XLA
  - epilogue: the round-9 fused conv/BN/ReLU EPILOGUE pair (stats-only
              pass + in-register scale-shift/residual/relu) vs XLA — the
              MXNET_FUSED_EPILOGUE decision bench
  - int8:     the rebuilt fused int8 matmul vs lax s8 dot (+ requantize
              rows) — the MXNET_INT8_PALLAS re-entry bench

Each result prints one line: name, ms/iter, TFLOP/s (or TOP/s), ratio
to the section's baseline.  Keep runs short: the tunnel budget matters
more than tight confidence intervals.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.abspath(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "..", ".jax_cache")))
import jax
import jax.numpy as jnp
import numpy as onp

if jax.config.jax_compilation_cache_dir is None:
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])


def timeit(fn, *args, iters=20, warm=3):
    out = fn(*args)
    jax.block_until_ready(out)
    for _ in range(warm):
        out = fn(*args)
    _ = float(jnp.asarray(out).ravel()[0].astype(jnp.float32))  # drain
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _ = float(jnp.asarray(out).ravel()[0].astype(jnp.float32))  # fence
    return (time.perf_counter() - t0) / iters


def section_dot():
    n = 8192
    flops = 2 * n ** 3
    key = jax.random.PRNGKey(0)
    a16 = jax.random.normal(key, (n, n), jnp.bfloat16)
    b16 = jax.random.normal(key, (n, n), jnp.bfloat16)

    f_bf16 = jax.jit(lambda a, b: (a @ b).sum())
    dt = timeit(f_bf16, a16, b16)
    base = flops / dt / 1e12
    print(f"dot bf16 {n}^3: {dt*1e3:8.2f} ms  {base:6.1f} TFLOP/s  1.00x")

    a8 = (jax.random.normal(key, (n, n)) * 10).astype(jnp.int8)
    b8 = (jax.random.normal(key, (n, n)) * 10).astype(jnp.int8)
    f_s8 = jax.jit(lambda a, b: jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32).sum())
    dt = timeit(f_s8, a8, b8)
    tops = flops / dt / 1e12
    print(f"dot s8s8s32 {n}^3: {dt*1e3:6.2f} ms  {tops:6.1f} TOP/s   "
          f"{tops/base:.2f}x vs bf16")


def _mkconv(dtype, epilogue):
    """One resnet-core 3x3 conv (NHWC), optionally with the int8 lane's
    requantize epilogue shape."""
    dn = jax.lax.conv_dimension_numbers((1, 1, 1, 1), (1, 1, 1, 1),
                                        ("NHWC", "OHWI", "NHWC"))

    def f(x, w):
        out = jax.lax.conv_general_dilated(
            x, w, (1, 1), [(1, 1), (1, 1)], dimension_numbers=dn,
            preferred_element_type=jnp.int32 if dtype == jnp.int8
            else jnp.float32)
        if epilogue == "requant":
            out = out.astype(jnp.float32) * 0.01
            out = jnp.maximum(out, 0)
            out = jnp.clip(jnp.round(out * 31.0), -127, 127).astype(jnp.int8)
        elif epilogue == "relu":
            out = jnp.maximum(out, 0).astype(dtype)
        return out

    return jax.jit(lambda x, w: f(x, w).astype(jnp.int32).sum())


def section_conv():
    # resnet stage-3 texture: bs64 (the int8 lane), 28x28x256 -> 256
    key = jax.random.PRNGKey(1)
    shape_x, shape_w = (64, 28, 28, 256), (256, 3, 3, 256)
    flops = 2 * 64 * 28 * 28 * 256 * 3 * 3 * 256
    x16 = jax.random.normal(key, shape_x, jnp.bfloat16)
    w16 = jax.random.normal(key, shape_w, jnp.bfloat16)
    dt = timeit(_mkconv(jnp.bfloat16, "relu"), x16, w16)
    base = flops / dt / 1e12
    print(f"conv bf16+relu: {dt*1e3:8.2f} ms  {base:6.1f} TFLOP/s  1.00x")

    x8 = (jax.random.normal(key, shape_x) * 10).astype(jnp.int8)
    w8 = (jax.random.normal(key, shape_w) * 10).astype(jnp.int8)
    for epi in ("none", "requant"):
        dt = timeit(_mkconv(jnp.int8, epi), x8, w8)
        tops = flops / dt / 1e12
        print(f"conv s8 epi={epi:<8}: {dt*1e3:6.2f} ms  {tops:6.1f} TOP/s"
              f"   {tops/base:.2f}x vs bf16")


def section_bn():
    # 4-deep conv chain, with vs without batch-stat BN between convs —
    # the delta is what BN costs the bf16 train step's forward texture
    key = jax.random.PRNGKey(2)
    bs = 128
    x = jax.random.normal(key, (bs, 28, 28, 256), jnp.bfloat16)
    ws = [jax.random.normal(jax.random.PRNGKey(i), (256, 3, 3, 256),
                            jnp.bfloat16) for i in range(4)]
    dn = jax.lax.conv_dimension_numbers(x.shape, ws[0].shape,
                                        ("NHWC", "OHWI", "NHWC"))
    flops = 4 * 2 * bs * 28 * 28 * 256 * 3 * 3 * 256

    def chain(x, ws, use_bn):
        for w in ws:
            x = jax.lax.conv_general_dilated(
                x, w, (1, 1), [(1, 1), (1, 1)], dimension_numbers=dn)
            if use_bn:
                x32 = x.astype(jnp.float32)
                mean = jnp.mean(x32, axis=(0, 1, 2))
                var = jnp.maximum(
                    jnp.mean(x32 * x32, axis=(0, 1, 2)) - mean * mean, 0.0)
                sc = jax.lax.rsqrt(var + 1e-5)
                x = (x * sc.astype(x.dtype)
                     - (mean * sc).astype(x.dtype))
            x = jnp.maximum(x, 0)
        return x.astype(jnp.float32).sum()

    for use_bn in (False, True):
        f = jax.jit(lambda x, *ws: chain(x, ws, use_bn))
        dt = timeit(f, x, *ws, iters=10)
        tf = flops / dt / 1e12
        print(f"conv-chain bn={use_bn!s:<5}: {dt*1e3:7.2f} ms  "
              f"{tf:6.1f} TFLOP/s")


def section_fused_stats():
    # A/B: XLA matmul + separate stats reduction vs the Pallas fused
    # producer+stats kernel (ops/pallas_kernels.matmul_bn_stats) — the
    # resnet stage-2 1x1-conv texture at bs128 (M = 128*28*28)
    from mxnet_tpu.ops.pallas_kernels import matmul_bn_stats

    key = jax.random.PRNGKey(3)
    m, k, n = 128 * 28 * 28, 512, 128
    x = jax.random.normal(key, (m, k), jnp.bfloat16)
    w = jax.random.normal(key, (k, n), jnp.bfloat16)
    flops = 2 * m * k * n

    def xla_ref(x, w):
        y = jnp.maximum((x @ w), 0)
        y32 = y.astype(jnp.float32)
        return y, jnp.sum(y32, 0), jnp.sum(y32 * y32, 0)

    def fence_all(out):
        y, s, ss = out
        # keep ALL outputs live on both sides — otherwise XLA dead-code-
        # eliminates the unfenced reductions and the A/B measures
        # different work
        return y.astype(jnp.float32).sum() + s.sum() + ss.sum()

    f = jax.jit(lambda x, w: fence_all(xla_ref(x, w)))
    dt = timeit(f, x, w, iters=10)
    base = flops / dt / 1e12
    print(f"mm+stats XLA:    {dt*1e3:8.2f} ms  {base:6.1f} TFLOP/s  1.00x")

    g = jax.jit(lambda x, w: fence_all(matmul_bn_stats(x, w, relu=True)))
    dt = timeit(g, x, w, iters=10)
    tf = flops / dt / 1e12
    print(f"mm+stats pallas: {dt*1e3:8.2f} ms  {tf:6.1f} TFLOP/s  "
          f"{tf/base:.2f}x vs XLA")


def section_fused_epilogue():
    # The round-9 decision bench for MXNET_FUSED_EPILOGUE: the
    # bottleneck-final texture conv1x1 + train-BN + residual-add + relu
    # as (a) plain XLA (conv write + stats read + normalize read/write —
    # whatever XLA fuses of it) vs (b) the fused-epilogue pair
    # (matmul_stats + matmul_epilogue: ONE HBM pass over the conv
    # output at 2x matmul FLOPs).  If (b) wins on chip, the knob flips
    # to default 1 and bench.py ResNet lanes stamp fused_epilogue=true.
    from mxnet_tpu.ops.pallas_kernels import (fused_blocks, matmul_stats,
                                              matmul_epilogue)

    key = jax.random.PRNGKey(4)
    # resnet stage-3 bottleneck-final: bs128, 14x14, 256 -> 1024
    m, k, n = 128 * 14 * 14, 256, 1024
    flops = 2 * m * k * n
    x = jax.random.normal(key, (m, k), jnp.bfloat16)
    w = jax.random.normal(key, (k, n), jnp.bfloat16) * 0.05
    gamma = jnp.abs(jax.random.normal(key, (n,), jnp.float32)) + 0.5
    beta = jax.random.normal(key, (n,), jnp.float32)
    r = jax.random.normal(key, (m, n), jnp.bfloat16)
    blocks = fused_blocks(m, k, n)
    assert blocks is not None

    def xla_ref(x, w, gamma, beta, r):
        z = (x @ w).astype(jnp.float32)
        mean = jnp.mean(z, axis=0)
        var = jnp.maximum(jnp.mean(z * z, axis=0) - mean * mean, 0.0)
        inv = jax.lax.rsqrt(var + 1e-5)
        y = z * (inv * gamma) + (beta - mean * inv * gamma)
        out = jnp.maximum(y + r.astype(jnp.float32), 0.0)
        return out.astype(x.dtype)

    f = jax.jit(lambda *a: xla_ref(*a).astype(jnp.float32).sum())
    dt = timeit(f, x, w, gamma, beta, r, iters=10)
    base = flops / dt / 1e12
    print(f"c1x1+bn+add+relu XLA:    {dt*1e3:8.2f} ms  {base:6.1f} "
          f"TFLOP/s  1.00x")

    def fused(x, w, gamma, beta, r):
        s, ss = matmul_stats(x, w, **blocks)
        mean = s / m
        var = jnp.maximum(ss / m - mean * mean, 0.0)
        inv = jax.lax.rsqrt(var + 1e-5)
        sc = inv * gamma
        return matmul_epilogue(x, w, sc, beta - mean * sc, residual=r,
                               relu=True, **blocks)

    g = jax.jit(lambda *a: fused(*a).astype(jnp.float32).sum())
    dt = timeit(g, x, w, gamma, beta, r, iters=10)
    tf = flops / dt / 1e12        # model FLOPs; the fused path pays 2x
    print(f"c1x1+bn+add+relu fused:  {dt*1e3:8.2f} ms  {tf:6.1f} "
          f"TFLOP/s  {tf/base:.2f}x vs XLA (2x matmul FLOPs inside)")

    # inference texture: scale/shift known ahead — epilogue pass only
    sc = gamma * 0.3
    bi = beta
    fi = jax.jit(lambda x, w: jnp.maximum(
        (x @ w).astype(jnp.float32) * sc + bi, 0.0)
        .astype(jnp.float32).sum())
    dt = timeit(fi, x, w, iters=10)
    base_i = flops / dt / 1e12
    gi = jax.jit(lambda x, w: matmul_epilogue(x, w, sc, bi, relu=True,
                                              **blocks)
                 .astype(jnp.float32).sum())
    dt = timeit(gi, x, w, iters=10)
    tf = flops / dt / 1e12
    print(f"c1x1+scale+relu XLA:     {base_i:6.1f} TFLOP/s  1.00x | "
          f"epilogue kernel: {tf:6.1f} TFLOP/s  {tf/base_i:.2f}x")


def section_int8_pallas():
    # Round-9 re-measurement bench for the int8 verdict: the REBUILT
    # fused int8 matmul ((m,n,k) grid, s32 VMEM accumulator,
    # in-register requantize — ops/pallas_kernels.int8_matmul) vs lax
    # s8 dot, with the bf16 reference row.  The round-5 conv-level
    # kernels measured 0.345x of lax on chip (BENCH_builder_r05) and
    # were DELETED; MXNET_INT8_PALLAS refuses until THIS bench beats
    # lax on chip (contrib/quantization._INT8_PALLAS_VERDICT).
    from mxnet_tpu.ops.pallas_kernels import int8_blocks, int8_matmul

    key = jax.random.PRNGKey(5)
    # the 1x1-conv-as-matmul texture: bs32 28x28, 512 -> 128
    m, k, n = 32 * 28 * 28, 512, 128
    flops = 2 * m * k * n
    qx = jax.random.randint(key, (m, k), -127, 128, jnp.int8)
    qw = jax.random.randint(key, (k, n), -127, 128, jnp.int8)
    scale = 3e-4
    blocks = int8_blocks(m, k, n)
    assert blocks is not None

    def lax_s8(qx, qw):
        acc = jax.lax.dot_general(qx, qw, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        return (acc.astype(jnp.float32) * scale).sum()

    f = jax.jit(lax_s8)
    dt = timeit(f, qx, qw, iters=10)
    base = flops / dt / 1e12
    print(f"mm s8 lax dot:    {dt*1e3:8.2f} ms  {base:6.1f} TOP/s  1.00x")

    g = jax.jit(lambda qx, qw: int8_matmul(qx, qw, scale, **blocks).sum())
    dt = timeit(g, qx, qw, iters=10)
    tf = flops / dt / 1e12
    print(f"mm s8 pallas:     {dt*1e3:8.2f} ms  {tf:6.1f} TOP/s  "
          f"{tf/base:.2f}x vs lax")

    # fused requantize epilogue row (the production int8 graph texture)
    def lax_rq(qx, qw):
        acc = jax.lax.dot_general(qx, qw, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        out = jnp.maximum(acc.astype(jnp.float32) * scale, 0.0)
        return jnp.clip(jnp.round(out * 31.0), -127, 127) \
            .astype(jnp.int8).astype(jnp.int32).sum()

    f2 = jax.jit(lax_rq)
    dt = timeit(f2, qx, qw, iters=10)
    base2 = flops / dt / 1e12
    g2 = jax.jit(lambda qx, qw: int8_matmul(
        qx, qw, scale, relu=True, out_scale=31.0, **blocks)
        .astype(jnp.int32).sum())
    dt = timeit(g2, qx, qw, iters=10)
    tf = flops / dt / 1e12
    print(f"mm s8+requant lax {base2:6.1f} TOP/s 1.00x | pallas "
          f"{tf:6.1f} TOP/s {tf/base2:.2f}x")

    bx = (qx.astype(jnp.float32) * scale).astype(jnp.bfloat16)
    bw = qw.astype(jnp.bfloat16)
    h2 = jax.jit(lambda x, w: (x @ w).astype(jnp.float32).sum())
    dt = timeit(h2, bx, bw, iters=10)
    tf = flops / dt / 1e12
    print(f"mm bf16 matmul:   {dt*1e3:8.2f} ms  {tf:6.1f} TFLOP/s  "
          f"{tf/base:.2f}x vs lax-s8")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--which", default="all",
                    choices=["all", "dot", "conv", "bn", "int8", "fused",
                             "epilogue"])
    args = ap.parse_args()
    print(f"backend: {jax.default_backend()}  {jax.devices()}")
    if args.which in ("all", "dot", "int8"):
        section_dot()
    if args.which in ("all", "conv", "int8"):
        section_conv()
    if args.which in ("all", "bn"):
        section_bn()
    if args.which in ("all", "fused"):
        section_fused_stats()
    if args.which in ("all", "epilogue"):
        section_fused_epilogue()
    if args.which in ("all", "int8"):
        section_int8_pallas()


if __name__ == "__main__":
    main()
