"""Eager per-op dispatch latency: plain dispatch vs the per-op jit cache
(MXNET_EAGER_JIT).  Run on the chip to fill docs/PERF.md's eager table
(round-5 VERDICT Weak #4); CPU runs are still meaningful A/Bs of python
dispatch overhead.

Method per op: warm (compile + cache) with host-value reads, then time N
invocations fenced by a host read — the tunnel exerts no backpressure
until a sync, so unfenced loops measure enqueue rate, not latency
(docs/PERF.md round-4 lesson).

Usage: python benchmark/eager_latency.py [--ops N] [--json]
Each mode runs in a SUBPROCESS so the jit cache and config are clean.
"""
import json
import os
import subprocess
import sys
import time

_WORKER = r"""
import json, os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))) if "__file__" in dir() else "/root/repo")
import numpy as onp
import mxnet_tpu as mx
from mxnet_tpu import nd

N = int(os.environ.get("EAGER_N", "100"))
rng = onp.random.RandomState(0)
x = nd.array(rng.randn(128, 256).astype(onp.float32))
w = nd.array(rng.randn(256, 256).astype(onp.float32))
b = nd.array(rng.randn(256).astype(onp.float32))
img = nd.array(rng.randn(8, 32, 32, 64).astype(onp.float32))
k = nd.array(rng.randn(64, 3, 3, 64).astype(onp.float32))
gamma = nd.ones((64,)); beta = nd.zeros((64,))
rm = nd.zeros((64,)); rv = nd.ones((64,))

OPS = {
    "elemwise_add": lambda: x + x,
    "FullyConnected": lambda: nd.FullyConnected(x, w, b, num_hidden=256),
    "softmax": lambda: nd.softmax(x, axis=-1),
    "Convolution3x3": lambda: nd.Convolution(
        img, k, kernel=(3, 3), pad=(1, 1), num_filter=64, no_bias=True,
        layout="NHWC"),
    "BatchNorm(infer)": lambda: nd.BatchNorm(
        img, gamma, beta, rm, rv, eps=1e-5, momentum=0.9, fix_gamma=False,
        use_global_stats=True, axis=3),
    "mean_axis": lambda: x.mean(axis=1),
}

rows = {}
def _first(o):
    return o[0] if isinstance(o, (list, tuple)) else o

for name, fn in OPS.items():
    for _ in range(5):                       # warm: compile + caches
        out = fn()
    _ = float(_first(out).asnumpy().ravel()[0])  # drain the dispatch queue
    t0 = time.perf_counter()
    for _ in range(N):
        out = fn()
    _ = float(_first(out).asnumpy().ravel()[0])  # fence
    dt = time.perf_counter() - t0
    rows[name] = dt / N * 1e6                # us/op incl. device time

import jax
print(json.dumps({"platform": jax.default_backend(),
                  "eager_jit": os.environ.get("MXNET_EAGER_JIT", "default"),
                  "us_per_op": rows}))
"""


def run(mode: str, n: int) -> dict:
    env = dict(os.environ)
    env["MXNET_EAGER_JIT"] = mode
    env["EAGER_N"] = str(n)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
    r = subprocess.run([sys.executable, "-u", "-c", _WORKER],
                       capture_output=True, text=True, timeout=900, env=env,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))) or ".")
    if r.returncode != 0:
        raise RuntimeError(f"mode {mode} failed:\n{r.stderr[-2000:]}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def main() -> None:
    n = 100
    as_json = "--json" in sys.argv
    if "--ops" in sys.argv:
        n = int(sys.argv[sys.argv.index("--ops") + 1])
    off = run("0", n)
    on = run("2", n)
    result = {"platform": off["platform"], "n": n,
              "plain_us": off["us_per_op"], "jit_us": on["us_per_op"],
              "speedup": {k: round(off["us_per_op"][k] / on["us_per_op"][k], 2)
                          for k in off["us_per_op"]}}
    if as_json:
        print(json.dumps(result))
        return
    print(f"eager dispatch latency ({off['platform']}, {n} calls/op, "
          "us/op incl. device time)")
    print(f"{'op':<20} {'plain':>10} {'per-op jit':>12} {'speedup':>9}")
    for k in off["us_per_op"]:
        print(f"{k:<20} {off['us_per_op'][k]:>10.1f} "
              f"{on['us_per_op'][k]:>12.1f} {result['speedup'][k]:>8.2f}x")


if __name__ == "__main__":
    main()
