"""Eager per-op dispatch latency: plain dispatch vs the per-op jit cache
(MXNET_EAGER_JIT), plus the Trainer-step lane comparing the fused
multi-tensor optimizer path (MXNET_FUSED_OPTIMIZER, optimizer/fused.py)
against the per-parameter scalar loop.  Run on the chip to fill
docs/PERF.md's eager table (round-5 VERDICT Weak #4); CPU runs are still
meaningful A/Bs of python dispatch overhead.

Method per op: warm (compile + cache) with host-value reads, then time N
invocations fenced by a host read — the tunnel exerts no backpressure
until a sync, so unfenced loops measure enqueue rate, not latency
(docs/PERF.md round-4 lesson).

The trainer lane reports ``dispatches_per_step`` = eager op dispatches
(ndarray.invoke_count) + compiled group-program launches
(fused.dispatch_count) per ``trainer.step()``: the fused path must stay
at <= 1 + (number of distinct parameter groups) while the loop path pays
>= 1 per parameter (the acceptance bar for PR 1).

The train_step_compiled lane rides next to it (PR 3): a hybridized MLP
trained through ``Trainer.compile_step`` (cached_step.TrainStep), whose
whole step — forward+backward+update — must land at 1 dispatch/step with
retrace count 0 after warm-up; it also reports program-cache hits/misses.
``--train-step-only`` emits just that lane (bench.py's lanes[] entry).

Usage: python benchmark/eager_latency.py [--ops N] [--json]
                                         [--trainer-params P] [--no-trainer]
                                         [--train-step-only]
Each mode runs in a SUBPROCESS so the jit cache and config are clean.
"""
import json
import os
import subprocess
import sys
import time

_WORKER = r"""
import json, os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))) if "__file__" in dir() else "/root/repo")
import numpy as onp
import mxnet_tpu as mx
from mxnet_tpu import nd

N = int(os.environ.get("EAGER_N", "100"))
rng = onp.random.RandomState(0)
x = nd.array(rng.randn(128, 256).astype(onp.float32))
w = nd.array(rng.randn(256, 256).astype(onp.float32))
b = nd.array(rng.randn(256).astype(onp.float32))
img = nd.array(rng.randn(8, 32, 32, 64).astype(onp.float32))
k = nd.array(rng.randn(64, 3, 3, 64).astype(onp.float32))
gamma = nd.ones((64,)); beta = nd.zeros((64,))
rm = nd.zeros((64,)); rv = nd.ones((64,))

OPS = {
    "elemwise_add": lambda: x + x,
    "FullyConnected": lambda: nd.FullyConnected(x, w, b, num_hidden=256),
    "softmax": lambda: nd.softmax(x, axis=-1),
    "Convolution3x3": lambda: nd.Convolution(
        img, k, kernel=(3, 3), pad=(1, 1), num_filter=64, no_bias=True,
        layout="NHWC"),
    "BatchNorm(infer)": lambda: nd.BatchNorm(
        img, gamma, beta, rm, rv, eps=1e-5, momentum=0.9, fix_gamma=False,
        use_global_stats=True, axis=3),
    "mean_axis": lambda: x.mean(axis=1),
}

rows = {}
def _first(o):
    return o[0] if isinstance(o, (list, tuple)) else o

for name, fn in OPS.items():
    for _ in range(5):                       # warm: compile + caches
        out = fn()
    _ = float(_first(out).asnumpy().ravel()[0])  # drain the dispatch queue
    t0 = time.perf_counter()
    for _ in range(N):
        out = fn()
    _ = float(_first(out).asnumpy().ravel()[0])  # fence
    dt = time.perf_counter() - t0
    rows[name] = dt / N * 1e6                # us/op incl. device time

import jax
print(json.dumps({"platform": jax.default_backend(),
                  "eager_jit": os.environ.get("MXNET_EAGER_JIT", "default"),
                  "us_per_op": rows}))
"""


# Trainer-step lane: a flat >=50-parameter "model" (grads pre-filled so
# the measurement is pure step() cost), stepped with the fused
# multi-tensor path on/off.  Dispatch counts come from the in-tree
# counters, not wall clock, so the lane is meaningful on any backend.
_TRAINER_WORKER = r"""
import json, os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))) if "__file__" in dir() else "/root/repo")
import numpy as onp
import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.ndarray import ndarray as _ndmod
from mxnet_tpu.optimizer import fused as _fused

NPARAM = int(os.environ.get("TRAINER_PARAMS", "56"))
STEPS = int(os.environ.get("TRAINER_STEPS", "20"))
OPT = os.environ.get("TRAINER_OPT", "sgd")
rng = onp.random.RandomState(0)
params = {}
for i in range(NPARAM):
    p = gluon.Parameter(f"w{i}", shape=(32, 32))
    p.initialize(init=mx.init.Xavier())
    params[f"w{i}"] = p
opt_kw = {"learning_rate": 0.01}
if OPT == "sgd":
    opt_kw["momentum"] = 0.9
trainer = gluon.Trainer(params, OPT, opt_kw)

def fill_grads():
    for p in params.values():
        g = p.list_grad()[0]
        g._set_data(mx.nd.array(
            rng.randn(*g.shape).astype("float32") * 0.01)._data)

fill_grads()
trainer.step(1)                          # warm: state create + compile
for p in params.values():                # drain
    _ = p.data().asnumpy()

inv0, fus0 = _ndmod.invoke_count(), _fused.dispatch_count()
t0 = time.perf_counter()
for _ in range(STEPS):
    trainer.step(1)
_ = next(iter(params.values())).data().asnumpy()   # fence
dt = time.perf_counter() - t0
inv = _ndmod.invoke_count() - inv0
fus = _fused.dispatch_count() - fus0

import jax
print(json.dumps({
    "platform": jax.default_backend(),
    "fused": bool(_fused.enabled(trainer._optimizer)),
    "n_params": NPARAM,
    "n_groups": 1,
    "steps": STEPS,
    "dispatches_per_step": (inv + fus) / STEPS,
    "compiled_group_dispatches_per_step": fus / STEPS,
    "us_per_step": dt / STEPS * 1e6,
}))
"""


# Compiled whole-train-step lane (cached_step.TrainStep): a small
# hybridized MLP trained via trainer.compile_step — forward+backward+
# update as ONE donated program.  Reports dispatches/step (the bar: 1,
# +1 host read under AMP), program-cache hits/misses, and the retrace
# count across constant-shape steps (the bar: 0 after warm).  Counter-
# based, so the lane is meaningful on any backend; us/step additionally
# shows the tunnel RTT win on chip.
_TRAIN_STEP_WORKER = r"""
import json, os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))) if "__file__" in dir() else "/root/repo")
import numpy as onp
import mxnet_tpu as mx
from mxnet_tpu import cached_step, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.ndarray import ndarray as _ndmod
from mxnet_tpu.optimizer import fused as _fused

WIDTH = int(os.environ.get("TRAIN_STEP_WIDTH", "64"))
DEPTH = int(os.environ.get("TRAIN_STEP_DEPTH", "4"))
STEPS = int(os.environ.get("TRAIN_STEP_STEPS", "20"))
OPT = os.environ.get("TRAINER_OPT", "sgd")

class Net(gluon.HybridBlock):
    def __init__(self):
        super().__init__()
        for i in range(DEPTH):
            setattr(self, f"d{i}", nn.Dense(
                WIDTH, in_units=WIDTH, activation="relu"))
        self.out = nn.Dense(WIDTH, in_units=WIDTH)
    def forward(self, x):
        for i in range(DEPTH):
            x = getattr(self, f"d{i}")(x)
        return self.out(x)

net = Net()
net.initialize(mx.init.Xavier())
net.hybridize()
rng = onp.random.RandomState(0)
opt_kw = {"learning_rate": 0.01}
if OPT == "sgd":
    opt_kw["momentum"] = 0.9
trainer = gluon.Trainer(net.collect_params(), OPT, opt_kw)
loss_fn = lambda n, x, y: ((n(x) - y) ** 2).mean()
step = trainer.compile_step(net, loss_fn)
x = mx.nd.array(rng.randn(128, WIDTH).astype(onp.float32))
y = mx.nd.array(rng.randn(128, WIDTH).astype(onp.float32))

t_c = time.perf_counter()
loss = step(x, y, batch_size=128)          # warm: trace + compile
_ = float(loss.asnumpy().ravel()[0])       # drain
compile_s = time.perf_counter() - t_c
inv0, d0, f0, t0 = (_ndmod.invoke_count(), cached_step.dispatch_count(),
                    _fused.dispatch_count(), cached_step.trace_count())
c0 = dict(cached_step.cache_stats())
from mxnet_tpu import telemetry
_tel0 = telemetry.snapshot()               # steady-state baseline
t_start = time.perf_counter()
for _ in range(STEPS):
    loss = step(x, y, batch_size=128)
_ = float(loss.asnumpy().ravel()[0])       # fence
dt = time.perf_counter() - t_start
c1 = cached_step.cache_stats()
# the full namespaced steady-state counter delta (every registry
# counter); the hand-picked keys below stay as aliases so BENCH_*
# rounds remain comparable
_tel = {k: v for k, v in telemetry.delta(_tel0).items() if v}

import jax
from mxnet_tpu import program_store
_disk = program_store.disk_stats()
print(json.dumps({
    "platform": jax.default_backend(),
    "compiled": step.last_fallback_reason is None,
    "n_params": len(trainer._params),
    "steps": STEPS,
    "dispatches_per_step":
        (_ndmod.invoke_count() - inv0 + cached_step.dispatch_count() - d0
         + _fused.dispatch_count() - f0) / STEPS,
    "compiled_launches_per_step":
        (cached_step.dispatch_count() - d0) / STEPS,
    "retrace_count": cached_step.trace_count() - t0,
    "program_cache_hits": c1["hits"] - c0["hits"],
    "program_cache_misses": c1["misses"] - c0["misses"],
    "compile_s": round(compile_s, 3),
    "cache_hits": _disk["hits"],
    "cache_misses": _disk["misses"],
    "us_per_step": dt / STEPS * 1e6,
    "telemetry": _tel,
}))
"""


def run(mode: str, n: int) -> dict:
    env = dict(os.environ)
    env["MXNET_EAGER_JIT"] = mode
    env["EAGER_N"] = str(n)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
    r = subprocess.run([sys.executable, "-u", "-c", _WORKER],
                       capture_output=True, text=True, timeout=900, env=env,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))) or ".")
    if r.returncode != 0:
        raise RuntimeError(f"mode {mode} failed:\n{r.stderr[-2000:]}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def run_trainer(fused: bool, n_params: int, steps: int = 20,
                opt: str = "sgd") -> dict:
    env = dict(os.environ)
    env["MXNET_FUSED_OPTIMIZER"] = "1" if fused else "0"
    env["TRAINER_PARAMS"] = str(n_params)
    env["TRAINER_STEPS"] = str(steps)
    env["TRAINER_OPT"] = opt
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
    r = subprocess.run([sys.executable, "-u", "-c", _TRAINER_WORKER],
                       capture_output=True, text=True, timeout=900, env=env,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))) or ".")
    if r.returncode != 0:
        raise RuntimeError(
            f"trainer lane (fused={fused}) failed:\n{r.stderr[-2000:]}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def run_train_step(steps: int = 20, opt: str = "sgd") -> dict:
    env = dict(os.environ)
    env["TRAIN_STEP_STEPS"] = str(steps)
    env["TRAINER_OPT"] = opt
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
    r = subprocess.run([sys.executable, "-u", "-c", _TRAIN_STEP_WORKER],
                       capture_output=True, text=True, timeout=900, env=env,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))) or ".")
    if r.returncode != 0:
        raise RuntimeError(
            f"train_step_compiled lane failed:\n{r.stderr[-2000:]}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def main() -> None:
    n = 100
    as_json = "--json" in sys.argv
    if "--train-step-only" in sys.argv:
        # bench.py's lanes[] entry point: just the compiled-step lane
        lane = run_train_step()
        print(json.dumps({"train_step_compiled": lane}) if as_json
              else lane)
        return
    if "--ops" in sys.argv:
        n = int(sys.argv[sys.argv.index("--ops") + 1])
    trainer_params = 56
    if "--trainer-params" in sys.argv:
        trainer_params = int(
            sys.argv[sys.argv.index("--trainer-params") + 1])
    off = run("0", n)
    on = run("2", n)
    result = {"platform": off["platform"], "n": n,
              "plain_us": off["us_per_op"], "jit_us": on["us_per_op"],
              "speedup": {k: round(off["us_per_op"][k] / on["us_per_op"][k], 2)
                          for k in off["us_per_op"]}}
    if "--no-trainer" not in sys.argv:
        t_fused = run_trainer(True, trainer_params)
        t_loop = run_trainer(False, trainer_params)
        result["trainer_step"] = {
            "n_params": trainer_params,
            "fused": t_fused, "loop": t_loop,
            "dispatch_reduction": round(
                t_loop["dispatches_per_step"]
                / max(t_fused["dispatches_per_step"], 1e-9), 1)}
        # the compiled whole-train-step lane rides next to the trainer
        # lane: same counters, but forward+backward fold in too
        result["train_step_compiled"] = run_train_step()
    if as_json:
        print(json.dumps(result))
        return
    print(f"eager dispatch latency ({off['platform']}, {n} calls/op, "
          "us/op incl. device time)")
    print(f"{'op':<20} {'plain':>10} {'per-op jit':>12} {'speedup':>9}")
    for k in off["us_per_op"]:
        print(f"{k:<20} {off['us_per_op'][k]:>10.1f} "
              f"{on['us_per_op'][k]:>12.1f} {result['speedup'][k]:>8.2f}x")
    if "trainer_step" in result:
        ts = result["trainer_step"]
        print(f"\ntrainer step ({ts['n_params']} params, sgd+momentum, "
              "dispatches per step())")
        print(f"{'path':<8} {'dispatches':>11} {'group-progs':>12} "
              f"{'us/step':>10}")
        for name, lane in (("fused", ts["fused"]), ("loop", ts["loop"])):
            print(f"{name:<8} {lane['dispatches_per_step']:>11.1f} "
                  f"{lane['compiled_group_dispatches_per_step']:>12.1f} "
                  f"{lane['us_per_step']:>10.1f}")
        print(f"dispatch reduction: {ts['dispatch_reduction']}x")
    if "train_step_compiled" in result:
        c = result["train_step_compiled"]
        print(f"\ncompiled train step ({c['n_params']} params, "
              f"{'compiled' if c['compiled'] else 'FELL BACK'}, "
              f"{c['steps']} steps)")
        print(f"dispatches/step {c['dispatches_per_step']:.1f} "
              f"(compiled launches {c['compiled_launches_per_step']:.1f}), "
              f"retraces {c['retrace_count']}, program cache "
              f"{c['program_cache_hits']}h/{c['program_cache_misses']}m, "
              f"compile {c['compile_s']:.1f}s (disk "
              f"{c['cache_hits']}h/{c['cache_misses']}m), "
              f"{c['us_per_step']:.1f} us/step")


if __name__ == "__main__":
    main()
