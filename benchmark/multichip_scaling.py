"""1→N device scaling of the SPMD compiled train step (kvstore='tpu').

The headline distributed claim (SNIPPETS.md / PAPER.md): Gluon Trainer
push/pull as an ICI-collective all-reduce INSIDE the one donated XLA
program, scaling ResNet-class training across a pod.  This lane measures
the claim directly: the SAME model and per-chip batch run on meshes of
1, 2, 4, ... N devices (subset meshes over the visible device world, the
``MXNET_SPMD_MESH=<n>`` knob), weak scaling — the global batch grows
with the mesh, so perfect scaling holds img/s/chip FLAT.

Per mesh size the lane reports:

- ``img_s_per_chip`` — samples/sec divided by mesh size (the headline;
  the ISSUE-1 bar is the 1→8 curve staying near-flat on ICI)
- ``step_ms_p50`` / ``step_ms_std`` — per-step wall time and its
  variance (collective jitter shows up here first)
- ``efficiency`` — img/s/chip relative to the 1-device lane
- ``param_bytes_per_device`` / ``opt_bytes_per_device`` — the
  memory-per-chip column (ISSUE-18), stamped from the ``spmd.*``
  computed gauges: flat across the data-parallel curve (replicated
  params) and ~1/N on the model-parallel sub-lane

Counter-based sanity rides along: every lane asserts ONE compiled launch
per step (no host-driven fan-out) and zero steady-state reshards.

The MODEL-PARALLEL sub-lane (ISSUE-18, docs/PERF.md "Sharded
training") holds the GLOBAL parameter count fixed while the fsdp axis
grows (``MXNET_SPMD_MESH=dp=1,fsdp=N`` for N = 1, 2, ... n): the
memory-per-chip claim is ``param_bytes_per_device`` and
``opt_bytes_per_device`` dropping ~1/N while the step stays one launch
with zero steady-state reshards.

On CPU the virtual 8-device world
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``, set below for
standalone runs) exercises the identical partitioned-program path; the
numbers are honest about ``platform`` either way.

Usage: python benchmark/multichip_scaling.py [--json] [--out FILE]
       [--per-chip N] [--steps N]
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "jax" not in sys.modules and "--xla_force_host_platform_device_count" \
        not in os.environ.get("XLA_FLAGS", "") \
        and os.environ.get("JAX_PLATFORMS", "") == "cpu":
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

PER_CHIP = int(os.environ.get("MULTICHIP_PER_CHIP", "32"))
STEPS = int(os.environ.get("MULTICHIP_STEPS", "20"))
WARMUP = 3
FEAT = 64


def _build(rows):
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    class Net(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.d1 = nn.Dense(256, in_units=FEAT, activation="relu")
            self.d2 = nn.Dense(64, in_units=256, activation="relu")
            self.d3 = nn.Dense(16, in_units=64)

        def forward(self, x):
            return self.d3(self.d2(self.d1(x)))

    net = Net()
    net.initialize(mx.init.Xavier())
    rng = onp.random.RandomState(0)
    for _n, p in sorted(net.collect_params().items()):
        p.data()._set_data(mx.nd.array(rng.randn(*p.shape) * 0.1)._data)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            kvstore="tpu")
    x = mx.nd.array(rng.randn(rows, FEAT))
    y = mx.nd.array(rng.randn(rows, 16))
    loss_fn = lambda n, a, b: ((n(a) - b) ** 2).mean()
    return net, trainer, loss_fn, x, y


def _lane(n_dev: int, per_chip: int, steps: int) -> dict:
    import jax

    from mxnet_tpu import cached_step
    from mxnet_tpu.parallel import spmd

    prev = os.environ.get("MXNET_SPMD_MESH")
    os.environ["MXNET_SPMD_MESH"] = str(n_dev)
    try:
        rows = per_chip * n_dev
        net, trainer, loss_fn, x, y = _build(rows)
        step = trainer.compile_step(net, loss_fn)
        for _ in range(WARMUP):
            loss = step(x, y, batch_size=rows)
        jax.block_until_ready(loss._data)
        d0 = cached_step.dispatch_count()
        r0 = spmd.reshard_count()
        times = []
        t_all = time.perf_counter()
        for _ in range(steps):
            t0 = time.perf_counter()
            loss = step(x, y, batch_size=rows)
            jax.block_until_ready(loss._data)   # per-step fence: the
            times.append(time.perf_counter() - t0)  # variance is the point
        elapsed = time.perf_counter() - t_all
        assert step.last_step_compiled, step.last_fallback_reason
        launches = (cached_step.dispatch_count() - d0) / steps
        times_ms = sorted(t * 1e3 for t in times)
        mean = sum(times_ms) / len(times_ms)
        std = (sum((t - mean) ** 2 for t in times_ms) / len(times_ms)) ** 0.5
        return {
            "devices": n_dev,
            "global_batch": rows,
            "img_s": rows * steps / elapsed,
            "img_s_per_chip": rows * steps / elapsed / n_dev,
            "step_ms_p50": times_ms[len(times_ms) // 2],
            "step_ms_mean": mean,
            "step_ms_std": std,
            "launches_per_step": launches,
            "reshards_after_warm": spmd.reshard_count() - r0,
            "mesh_devices": len(
                net.collect_params()["d1.weight"].data()
                ._data.sharding.device_set),
            # memory-per-chip column: replicated params hold this flat
            # across the data-parallel curve
            "param_bytes_per_device": spmd.param_bytes_per_device(),
            "opt_bytes_per_device": spmd.opt_bytes_per_device(),
        }
    finally:
        if prev is None:
            os.environ.pop("MXNET_SPMD_MESH", None)
        else:
            os.environ["MXNET_SPMD_MESH"] = prev


def _model_lane(n_fsdp: int, per_chip: int, steps: int) -> dict:
    """Model-parallel sub-lane: GLOBAL params fixed, fsdp axis grows —
    the claim is memory per chip dropping ~1/N, not throughput."""
    import jax

    from mxnet_tpu import cached_step
    from mxnet_tpu.parallel import spmd

    prev = os.environ.get("MXNET_SPMD_MESH")
    prev_min = os.environ.get("MXNET_FSDP_MIN_SIZE")
    os.environ["MXNET_SPMD_MESH"] = f"dp=1,fsdp={n_fsdp}"
    os.environ["MXNET_FSDP_MIN_SIZE"] = "1"     # the bench MLP is small
    try:
        rows = per_chip                          # fixed global batch too
        net, trainer, loss_fn, x, y = _build(rows)
        step = trainer.compile_step(net, loss_fn)
        for _ in range(WARMUP):
            loss = step(x, y, batch_size=rows)
        jax.block_until_ready(loss._data)
        d0 = cached_step.dispatch_count()
        r0 = spmd.reshard_count()
        t_all = time.perf_counter()
        for _ in range(steps):
            loss = step(x, y, batch_size=rows)
            jax.block_until_ready(loss._data)
        elapsed = time.perf_counter() - t_all
        assert step.last_step_compiled, step.last_fallback_reason
        total = sum(p.data()._data.nbytes
                    for _n, p in sorted(net.collect_params().items()))
        return {
            "fsdp": n_fsdp,
            "global_batch": rows,
            "img_s": rows * steps / elapsed,
            "step_ms_mean": elapsed * 1e3 / steps,
            "launches_per_step":
                (cached_step.dispatch_count() - d0) / steps,
            "reshards_after_warm": spmd.reshard_count() - r0,
            "param_bytes_global": total,
            "param_bytes_per_device": spmd.param_bytes_per_device(),
            "opt_bytes_per_device": spmd.opt_bytes_per_device(),
        }
    finally:
        if prev is None:
            os.environ.pop("MXNET_SPMD_MESH", None)
        else:
            os.environ["MXNET_SPMD_MESH"] = prev
        if prev_min is None:
            os.environ.pop("MXNET_FSDP_MIN_SIZE", None)
        else:
            os.environ["MXNET_FSDP_MIN_SIZE"] = prev_min


def _moe_lane(steps: int) -> dict:
    """Expert-parallel MoE sub-lane (ISSUE 20, docs/PERF.md "Every-axis
    mesh"): an MoEBlock under MXNET_SPMD_MESH='ep=4,dp=2' — the value is
    routed tokens/s/chip through the ONE donated step (gating, dispatch/
    combine, ep-sharded expert einsums, folded aux head, fused update).
    Capacity-drop counters ride along (host recomputation of the same
    deterministic gating state), stamped as ``moe.*`` gauges so
    check_perf_delta defends both the throughput and the drop rate."""
    import jax
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import cached_step, gluon, telemetry
    from mxnet_tpu.parallel import moe as moe_mod, spmd

    n_dev = len(jax.devices())
    if n_dev < 8:
        return {"skipped": f"only {n_dev} device(s)"}
    G, S, M, H, E = 8, 16, 32, 64, 4
    prev = os.environ.get("MXNET_SPMD_MESH")
    prev_min = os.environ.get("MXNET_FSDP_MIN_SIZE")
    os.environ["MXNET_SPMD_MESH"] = "ep=4,dp=2"
    os.environ["MXNET_FSDP_MIN_SIZE"] = "1"
    try:
        net = moe_mod.MoEBlock(units=M, hidden=H, num_experts=E, k=2)
        net.initialize(mx.init.Xavier())
        rng = onp.random.RandomState(0)
        for _n, p in sorted(net.collect_params().items()):
            p.data()._set_data(
                mx.nd.array(rng.randn(*p.shape).astype(onp.float32)
                            * 0.1)._data)
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.01, "momentum": 0.9},
                                kvstore="tpu")
        loss_fn = lambda n, a: ((n(a)) ** 2).mean()
        x_host = rng.randn(G, S, M).astype(onp.float32)
        x = mx.nd.array(x_host)
        step = trainer.compile_step(net, loss_fn)
        for _ in range(WARMUP):
            loss = step(x, batch_size=G)
        jax.block_until_ready(loss._data)
        assert step.last_step_compiled, step.last_fallback_reason
        d0, r0 = cached_step.dispatch_count(), spmd.reshard_count()
        t_all = time.perf_counter()
        for _ in range(steps):
            loss = step(x, batch_size=G)
            jax.block_until_ready(loss._data)
        elapsed = time.perf_counter() - t_all
        tokens_s = G * S * steps / elapsed
        # drop counters: recompute the deterministic gating state on the
        # host with the trained gate — survivors vs G*S*k routed slots
        import jax.numpy as jnp

        gate_w = net.collect_params()["gate.weight"].data()._data
        disp, _comb, _aux = moe_mod.top_k_gating(
            jnp.asarray(x_host), gate_w, num_experts=E, k=2)
        routed = G * S * 2
        survivors = int(onp.asarray(disp).sum())
        ew = net.collect_params()["expert.ffn_1.weight"].data()._data
        lane = {
            "skipped": None,
            "devices": n_dev,
            "tokens_per_step": G * S,
            "tokens_s": tokens_s,
            "tokens_s_per_chip": tokens_s / n_dev,
            "step_ms_mean": elapsed * 1e3 / steps,
            "launches_per_step":
                (cached_step.dispatch_count() - d0) / steps,
            "reshards_after_warm": spmd.reshard_count() - r0,
            "expert_sharded": bool(ew.sharding.spec
                                   and ew.sharding.spec[0] == "ep"),
            "routed_slots": routed,
            "dropped_slots": routed - survivors,
            "drop_rate": (routed - survivors) / routed,
        }
        telemetry.gauge(
            "moe.tokens_per_s_per_chip",
            "MoE bench lane: routed tokens/s/chip through the one "
            "donated ep-sharded step").set(lane["tokens_s_per_chip"])
        telemetry.gauge(
            "moe.dropped_slots",
            "MoE bench lane: over-capacity slots dropped by the "
            "deterministic top-k gating on the bench batch").set(
            lane["dropped_slots"])
        return lane
    finally:
        if prev is None:
            os.environ.pop("MXNET_SPMD_MESH", None)
        else:
            os.environ["MXNET_SPMD_MESH"] = prev
        if prev_min is None:
            os.environ.pop("MXNET_FSDP_MIN_SIZE", None)
        else:
            os.environ["MXNET_FSDP_MIN_SIZE"] = prev_min


def _pp_lane(steps: int) -> dict:
    """Pipeline-parallel sub-lane (ISSUE 20): a 2-stage PipelineBlock
    under MXNET_SPMD_MESH='pp=2,dp=2,fsdp=2', stepped at two microbatch
    counts (M=2, M=4).  The per-microbatch ramp cost falls out of the
    step-time slope over 1/M — T(M) = A + B/M with B the fill/drain
    (bubble) term — giving a MEASURED bubble fraction next to the
    GPipe closed form (S-1)/(M+S-1).  Stamped as ``pp.*`` gauges so
    check_perf_delta catches a bubble regression even when wall-clock
    noise hides it."""
    import jax
    import jax.numpy as jnp
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import cached_step, gluon, telemetry
    from mxnet_tpu.parallel import pipeline as pipe_mod, spmd

    n_dev = len(jax.devices())
    if n_dev < 8:
        return {"skipped": f"only {n_dev} device(s)"}
    S_STAGES, DIM, BATCH = 2, 64, 8
    prev = os.environ.get("MXNET_SPMD_MESH")
    prev_min = os.environ.get("MXNET_FSDP_MIN_SIZE")
    os.environ["MXNET_SPMD_MESH"] = "pp=2,dp=2,fsdp=2"
    os.environ["MXNET_FSDP_MIN_SIZE"] = "1"
    try:
        def measure(num_micro: int) -> dict:
            mesh = spmd.resolve_mesh()
            rng = onp.random.RandomState(1)
            ws = [jnp.asarray((rng.randn(DIM, DIM) * 0.2)
                              .astype(onp.float32))
                  for _ in range(S_STAGES)]

            def stage(params, xx):
                return jnp.tanh(xx @ params["w"])

            pipe = pipe_mod.HeteroPipeline(
                [stage] * S_STAGES, [{"w": w} for w in ws], mesh,
                num_microbatches=num_micro,
                example_x=jnp.zeros((BATCH, DIM), jnp.float32))
            blk = pipe_mod.PipelineBlock(pipe)
            trainer = gluon.Trainer(blk.collect_params(), "sgd",
                                    {"learning_rate": 0.05,
                                     "momentum": 0.9}, kvstore="tpu")
            loss_fn = lambda n, a: ((n(a)) ** 2).sum()
            x = mx.nd.array(rng.randn(BATCH, DIM).astype(onp.float32))
            step = trainer.compile_step(blk, loss_fn)
            for _ in range(WARMUP):
                loss = step(x, batch_size=BATCH)
            jax.block_until_ready(loss._data)
            assert step.last_step_compiled, step.last_fallback_reason
            d0, r0 = cached_step.dispatch_count(), spmd.reshard_count()
            t_all = time.perf_counter()
            for _ in range(steps):
                loss = step(x, batch_size=BATCH)
                jax.block_until_ready(loss._data)
            elapsed = time.perf_counter() - t_all
            return {
                "num_microbatches": num_micro,
                "step_ms_mean": elapsed * 1e3 / steps,
                "launches_per_step":
                    (cached_step.dispatch_count() - d0) / steps,
                "reshards_after_warm": spmd.reshard_count() - r0,
                "bubble_fraction_theoretical":
                    pipe_mod.bubble_fraction(S_STAGES, num_micro),
            }

        m2 = measure(2)
        m4 = measure(4)
        # T(M) = A + B/M: B/M is the fill/drain ramp's share of the step
        b_term = (m2["step_ms_mean"] - m4["step_ms_mean"]) / (0.5 - 0.25)
        measured = (max(0.0, b_term) / 4) / m4["step_ms_mean"] \
            if m4["step_ms_mean"] else 0.0
        lane = {
            "skipped": None,
            "devices": n_dev,
            "stages": S_STAGES,
            "step_ms_mean": m4["step_ms_mean"],
            "launches_per_step": m4["launches_per_step"],
            "reshards_after_warm": (m2["reshards_after_warm"]
                                    + m4["reshards_after_warm"]),
            "bubble_fraction_measured": measured,
            "bubble_fraction_theoretical":
                m4["bubble_fraction_theoretical"],
            "points": [m2, m4],
        }
        telemetry.gauge(
            "pp.bubble_fraction_measured",
            "pp bench lane: fill/drain share of step time from the "
            "T(M) = A + B/M slope fit at M=4").set(measured)
        telemetry.gauge(
            "pp.step_ms_mean",
            "pp bench lane: mean step wall-time (ms) at M=4 on the "
            "pp=2,dp=2,fsdp=2 mesh").set(lane["step_ms_mean"])
        return lane
    finally:
        if prev is None:
            os.environ.pop("MXNET_SPMD_MESH", None)
        else:
            os.environ["MXNET_SPMD_MESH"] = prev
        if prev_min is None:
            os.environ.pop("MXNET_FSDP_MIN_SIZE", None)
        else:
            os.environ["MXNET_FSDP_MIN_SIZE"] = prev_min


def run_moe(steps: int = STEPS) -> dict:
    import jax

    from mxnet_tpu import program_store, telemetry

    t_c0 = program_store.compile_seconds()
    lane = _moe_lane(steps)
    disk = program_store.disk_stats()
    telemetry.flush()
    out = {
        "metric": "moe_tokens_per_s_per_chip",
        "value": lane.get("tokens_s_per_chip", 0.0),
        "unit": "tokens/s/chip",
        "n_devices": len(jax.devices()),
        "steps": steps,
        "platform": jax.default_backend(),
        "compile_s": round(program_store.compile_seconds() - t_c0, 3),
        "cache_hits": disk["hits"],
        "cache_misses": disk["misses"],
        "telemetry": telemetry.snapshot(),
    }
    out.update({k: v for k, v in lane.items() if k != "telemetry"})
    return out


def run_pp(steps: int = STEPS) -> dict:
    import jax

    from mxnet_tpu import program_store, telemetry

    t_c0 = program_store.compile_seconds()
    lane = _pp_lane(steps)
    disk = program_store.disk_stats()
    telemetry.flush()
    out = {
        "metric": "pp_bubble_fraction",
        "value": lane.get("bubble_fraction_measured", 0.0),
        "unit": "fraction",
        "n_devices": len(jax.devices()),
        "steps": steps,
        "platform": jax.default_backend(),
        "compile_s": round(program_store.compile_seconds() - t_c0, 3),
        "cache_hits": disk["hits"],
        "cache_misses": disk["misses"],
        "telemetry": telemetry.snapshot(),
    }
    out.update({k: v for k, v in lane.items() if k != "telemetry"})
    return out


def run(per_chip: int = PER_CHIP, steps: int = STEPS,
        sizes=None) -> dict:
    import jax

    n = len(jax.devices())
    if sizes is None:
        sizes = [s for s in (1, 2, 4, 8, 16, 32, 64) if s <= n]
        if n not in sizes:
            sizes.append(n)
    from mxnet_tpu import program_store

    t_c0 = program_store.compile_seconds()
    curve = [_lane(s, per_chip, steps) for s in sizes]
    base = curve[0]["img_s_per_chip"]
    for lane in curve:
        lane["efficiency"] = lane["img_s_per_chip"] / base if base else 0.0
    # model-parallel sub-lane: fixed global params, growing fsdp axis
    model_curve = [_model_lane(s, per_chip, steps) for s in sizes]
    mp_base = model_curve[0]["param_bytes_per_device"]
    for lane in model_curve:
        lane["param_bytes_frac"] = (
            lane["param_bytes_per_device"] / mp_base if mp_base else 1.0)
    head = curve[-1]
    disk = program_store.disk_stats()
    from mxnet_tpu import telemetry

    telemetry.flush()   # flight-recorder shard for the lane's fleet merge
    return {
        "metric": "multichip_img_s_per_chip",
        "value": head["img_s_per_chip"],
        "unit": "img/s/chip",
        "n_devices": n,
        "per_chip_batch": per_chip,
        "steps": steps,
        "platform": jax.default_backend(),
        "scaling_efficiency": head["efficiency"],
        "step_ms_std_max": max(l["step_ms_std"] for l in curve),
        # one program per mesh size: the cold-start tax this lane pays
        "compile_s": round(program_store.compile_seconds() - t_c0, 3),
        "cache_hits": disk["hits"],
        "cache_misses": disk["misses"],
        # memory-per-chip headline: per-device param bytes on the
        # largest fsdp mesh as a fraction of the 1-device footprint
        "model_parallel_param_bytes_frac":
            model_curve[-1]["param_bytes_frac"],
        "curve": curve,
        "model_parallel_curve": model_curve,
    }


def main():
    argv = sys.argv[1:]

    def _val(flag, default):
        if flag in argv:
            return int(argv[argv.index(flag) + 1])
        return default

    if "--moe" in argv:
        result = run_moe(steps=_val("--steps", STEPS))
        if "--json" in argv:
            print(json.dumps(result))
        elif result.get("skipped"):
            print(f"moe lane SKIPPED ({result['skipped']})")
        else:
            print(f"moe (ep=4,dp=2, {result['platform']}): "
                  f"{result['value']:.0f} tokens/s/chip, "
                  f"{result['step_ms_mean']:.2f} ms/step, "
                  f"{result['launches_per_step']:.1f} launches/step, "
                  f"{result['dropped_slots']}/{result['routed_slots']} "
                  f"slots dropped")
        return 0
    if "--pp" in argv:
        result = run_pp(steps=_val("--steps", STEPS))
        if "--json" in argv:
            print(json.dumps(result))
        elif result.get("skipped"):
            print(f"pp lane SKIPPED ({result['skipped']})")
        else:
            print(f"pp (pp=2,dp=2,fsdp=2, {result['platform']}): "
                  f"bubble {result['value']:.2f} measured / "
                  f"{result['bubble_fraction_theoretical']:.2f} "
                  f"theoretical, {result['step_ms_mean']:.2f} ms/step, "
                  f"{result['launches_per_step']:.1f} launches/step")
        return 0
    result = run(per_chip=_val("--per-chip", PER_CHIP),
                 steps=_val("--steps", STEPS))
    if "--out" in argv:
        path = argv[argv.index("--out") + 1]
        with open(path, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    if "--json" in argv:
        print(json.dumps(result))
    else:
        print(f"multichip scaling ({result['platform']}, "
              f"{result['n_devices']} devices, weak scaling, "
              f"{result['per_chip_batch']}/chip):")
        for lane in result["curve"]:
            print(f"  {lane['devices']:>3} dev  "
                  f"{lane['img_s_per_chip']:>10.0f} img/s/chip  "
                  f"p50 {lane['step_ms_p50']:.2f} ms  "
                  f"std {lane['step_ms_std']:.2f} ms  "
                  f"eff {lane['efficiency']:.2f}  "
                  f"launches/step {lane['launches_per_step']:.1f}  "
                  f"{lane['param_bytes_per_device'] / 1024:.1f} "
                  f"KiB params/chip")
        print("model parallel (fixed global params, dp=1,fsdp=N):")
        for lane in result["model_parallel_curve"]:
            print(f"  fsdp={lane['fsdp']:<3} "
                  f"{lane['param_bytes_per_device'] / 1024:>8.1f} KiB "
                  f"params/chip ({lane['param_bytes_frac']:.2f}x)  "
                  f"{lane['opt_bytes_per_device'] / 1024:>8.1f} KiB "
                  f"opt/chip  "
                  f"launches/step {lane['launches_per_step']:.1f}  "
                  f"reshards {lane['reshards_after_warm']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
