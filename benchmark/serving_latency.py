"""Serving-path latency: the shape-bucketed compiled inference engine
(``mxnet_tpu/serving.py``) driven by a randomized variable-length request
stream.

Reports per-request p50/p99 latency, throughput, bucket hits/misses,
compiled-program count, and the retrace count after warm-up — the PR-4
acceptance bar is **0 steady-state retraces with the program count
bounded by the bucket grid** (counter-based, so the lane is meaningful on
any backend; the latency numbers additionally show the tunnel RTT win on
chip).  A second phase fires the same stream from concurrent threads to
exercise the micro-batcher (coalesced requests per dispatch).

``--serve-only --json`` emits just the lane dict (bench.py's ``infer``
lanes[] entry).  Like benchmark/eager_latency.py, the measured work runs
in a SUBPROCESS so jit caches and config are clean.

Usage: python benchmark/serving_latency.py [--json] [--serve-only]
                                           [--requests N] [--threads T]
"""
import json
import os
import subprocess
import sys

_WORKER = r"""
import json, os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))) if "__file__" in dir() else "/root/repo")
import numpy as onp
import mxnet_tpu as mx
from mxnet_tpu import gluon, serving
from mxnet_tpu.gluon import nn

N_REQ = int(os.environ.get("SERVE_REQUESTS", "64"))
THREADS = int(os.environ.get("SERVE_THREADS", "4"))
WIDTH = int(os.environ.get("SERVE_WIDTH", "64"))
MAXLEN = int(os.environ.get("SERVE_MAXLEN", "32"))

class Net(gluon.HybridBlock):
    def __init__(self):
        super().__init__()
        self.d1 = nn.Dense(WIDTH, in_units=WIDTH, activation="relu")
        self.d2 = nn.Dense(WIDTH, in_units=WIDTH, activation="relu")
        self.out = nn.Dense(8, in_units=WIDTH)
    def forward(self, x):
        return self.out(self.d2(self.d1(x)))

net = Net()
net.initialize(mx.init.Xavier())
rng = onp.random.RandomState(0)
lengths = rng.randint(1, MAXLEN + 1, size=N_REQ).tolist()
reqs = [mx.nd.array(rng.randn(n, WIDTH).astype(onp.float32))
        for n in lengths]

eng = serving.ServingEngine(net, max_delay_us=200)
# deploy-time AOT warmup (ProgramStore): compile the pow2 grid up to
# MAXLEN off the request path; compile_s is the whole tax paid here
from mxnet_tpu import program_store
t_warm = time.perf_counter()
warmup_programs = eng.warmup(
    mx.nd.array(onp.zeros((1, WIDTH), onp.float32)), max_rows=MAXLEN)
compile_s = time.perf_counter() - t_warm
# the first real request per bucket still pays its one-time verify
b = 1
while b <= MAXLEN:
    eng.infer(mx.nd.array(rng.randn(b, WIDTH).astype(onp.float32)))
    b <<= 1
warm_traces = serving.trace_count()
warm_progs = len(eng._programs)

# phase 1: sequential stream (per-request latency, retrace bar)
t0 = serving.trace_count(); d0 = serving.dispatch_count()
h0 = serving.bucket_stats()
t_start = time.perf_counter()
outs = [eng.infer(r) for r in reqs]
_ = float(outs[-1].asnumpy().ravel()[0])          # fence
dt = time.perf_counter() - t_start
seq = eng.stats()
retraces = serving.trace_count() - t0
h1 = serving.bucket_stats()

# phase 2: concurrent stream (micro-batcher coalescing)
import threading
eng2 = serving.ServingEngine(net, max_delay_us=3000)
for bb in (1, 2, 4, 8, 16, 32, 64):
    if bb <= serving.BucketPolicy().bucket(MAXLEN * THREADS):
        eng2.infer(mx.nd.array(rng.randn(bb, WIDTH).astype(onp.float32)))
errs = []
def fire(chunk):
    try:
        for r in chunk:
            eng2.infer(r)
    except BaseException as e:
        errs.append(repr(e))
threads = [threading.Thread(target=fire, args=(reqs[i::THREADS],))
           for i in range(THREADS)]
t2 = time.perf_counter()
for t in threads: t.start()
for t in threads: t.join()
dt2 = time.perf_counter() - t2
conc = eng2.stats()
assert not errs, errs

import jax
_disk = program_store.disk_stats()
print(json.dumps({
    "platform": jax.default_backend(),
    "requests": N_REQ,
    "buckets": serving.BucketPolicy().spec,
    "programs": seq["programs"],
    "warmup_programs": warmup_programs,
    "compile_s": round(compile_s, 3),
    "cache_hits": _disk["hits"],
    "cache_misses": _disk["misses"],
    "warm_traces": warm_traces,
    "retraces_after_warm": retraces,
    "bucket_hits": h1["hits"] - h0["hits"],
    "bucket_misses": h1["misses"] - h0["misses"],
    "dispatches": serving.dispatch_count() - d0,
    "p50_us": seq["p50_us"],
    "p99_us": seq["p99_us"],
    "throughput_rps": N_REQ / dt,
    "concurrent": {
        "threads": THREADS,
        "batches": conc["batches"],
        "requests": conc["requests"],
        "coalesced": conc["coalesced"],
        "requests_per_dispatch": conc["requests"] / max(conc["batches"], 1),
        "p99_us": conc["p99_us"],
        "throughput_rps": conc["requests"] / dt2,
    },
}))
eng.close(); eng2.close()
"""


def run_serving(requests: int = 64, threads: int = 4) -> dict:
    env = dict(os.environ)
    env["SERVE_REQUESTS"] = str(requests)
    env["SERVE_THREADS"] = str(threads)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
    r = subprocess.run([sys.executable, "-u", "-c", _WORKER],
                       capture_output=True, text=True, timeout=900, env=env,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))) or ".")
    if r.returncode != 0:
        raise RuntimeError(f"serving lane failed:\n{r.stderr[-2000:]}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def main() -> None:
    as_json = "--json" in sys.argv
    requests = 64
    if "--requests" in sys.argv:
        requests = int(sys.argv[sys.argv.index("--requests") + 1])
    threads = 4
    if "--threads" in sys.argv:
        threads = int(sys.argv[sys.argv.index("--threads") + 1])
    lane = run_serving(requests, threads)
    if as_json:
        print(json.dumps({"serving": lane}))
        return
    print(f"serving latency ({lane['platform']}, {lane['requests']} "
          f"variable-length requests, buckets={lane['buckets']})")
    print(f"programs {lane['programs']} (warm traces "
          f"{lane['warm_traces']}), retraces after warm "
          f"{lane['retraces_after_warm']}, bucket "
          f"{lane['bucket_hits']}h/{lane['bucket_misses']}m")
    print(f"sequential: p50 {lane['p50_us']:.0f} us, p99 "
          f"{lane['p99_us']:.0f} us, {lane['throughput_rps']:.1f} req/s")
    c = lane["concurrent"]
    print(f"concurrent ({c['threads']} threads): "
          f"{c['requests_per_dispatch']:.1f} requests/dispatch "
          f"({c['coalesced']} coalesced), p99 {c['p99_us']:.0f} us, "
          f"{c['throughput_rps']:.1f} req/s")


if __name__ == "__main__":
    if "--serve-only" in sys.argv:
        # bench.py's lanes[] entry point: the one serving lane
        lane = run_serving()
        print(json.dumps({"serving": lane}) if "--json" in sys.argv
              else lane)
    else:
        main()
