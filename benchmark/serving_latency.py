"""Serving-path latency: the shape-bucketed compiled inference engine
(``mxnet_tpu/serving.py``) driven by a randomized variable-length request
stream, plus the GENERATIVE lanes over ``serving_decode``.

Reports per-request p50/p99 latency, throughput, bucket hits/misses,
compiled-program count, and the retrace count after warm-up — the PR-4
acceptance bar is **0 steady-state retraces with the program count
bounded by the bucket grid** (counter-based, so the lane is meaningful on
any backend; the latency numbers additionally show the tunnel RTT win on
chip).  A second phase fires the same stream from concurrent threads to
exercise the micro-batcher (coalesced requests per dispatch).

``--serve-only --json`` emits just the lane dict (bench.py's ``infer``
lanes[] entry).  Like benchmark/eager_latency.py, the measured work runs
in a SUBPROCESS so jit caches and config are clean.

``--decode-only --json`` is bench.py's ``decode`` lane: the
continuous-batching A/B — the SAME request set generated
one-request-at-a-time (sequential submission, no row sharing) vs at
concurrency >= 8 through the iteration-level scheduler — whose
acceptance bar is **>= 2x tokens/s from continuous batching** with 0
retraces, plus a compact multi-tenant STORM: bursty Poisson arrivals
of mixed-length prompts against a fast model co-hosted with a
deliberately slow model on the SHARED KV page pool, reporting
per-model p50/p99, shed count, tokens/s, and the interference ratio
(fast model storm-p99 / solo-p99 — bounded misbehavior, not silent
collapse), and a ROUTER storm (ISSUE 14): two fast replicas behind a
``serving_router.ReplicaRouter`` with one replica killed mid-storm,
stamping the availability columns — dropped (must be 0) / hedged /
failed_over / breaker_transitions — next to the latency numbers, and
an ELASTIC storm (ISSUE 17): one replica plus a ``FleetSupervisor``
under the same bursty arrivals, stamping the replica-count timeline,
scale_ups/scale_downs/joins/drains, peak/final replica counts, and
fleet tokens/s. ``--storm`` prints the storm report standalone.

``--shared-prefix`` is the ISSUE-16 lane: M users x ONE system prompt
through the content-addressed prefix cache (``MXNET_PREFIX_CACHE``),
run warm (cache on) and cold (knob off) over the same seeds, stamping
``prefix_hit_rate`` (acceptance floor >= 0.9), prefill tokens/FLOPs
saved, tokens/s/chip for both passes, and token-exactness vs the cold
pass AND the eager oracle.  ``prefix_miss_blocks`` rides the lane dict
so tools/check_perf_delta.py gates hit-rate regressions round over
round.

``--speculative`` is the ISSUE-19 lane: the SAME greedy prompt set
through a high-agreement draft/target pair with ``MXNET_SPEC_DECODE=1``
vs the non-spec baseline, stamping tokens/s, measured acceptance,
tokens-per-round, and target-dispatches-per-token — the worker ENFORCES
the acceptance bars (>= 1.5x tokens/s at acceptance >= 0.7, token-exact
vs the eager oracle, low-agreement draft auto-disabled with tokens/s
never regressing past 5% of baseline).

Usage: python benchmark/serving_latency.py [--json] [--serve-only]
           [--decode-only] [--storm] [--shared-prefix] [--speculative]
           [--requests N] [--threads T]
"""
import json
import os
import subprocess
import sys

_WORKER = r"""
import json, os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))) if "__file__" in dir() else "/root/repo")
import numpy as onp
import mxnet_tpu as mx
from mxnet_tpu import gluon, serving
from mxnet_tpu.gluon import nn

N_REQ = int(os.environ.get("SERVE_REQUESTS", "64"))
THREADS = int(os.environ.get("SERVE_THREADS", "4"))
WIDTH = int(os.environ.get("SERVE_WIDTH", "64"))
MAXLEN = int(os.environ.get("SERVE_MAXLEN", "32"))

class Net(gluon.HybridBlock):
    def __init__(self):
        super().__init__()
        self.d1 = nn.Dense(WIDTH, in_units=WIDTH, activation="relu")
        self.d2 = nn.Dense(WIDTH, in_units=WIDTH, activation="relu")
        self.out = nn.Dense(8, in_units=WIDTH)
    def forward(self, x):
        return self.out(self.d2(self.d1(x)))

net = Net()
net.initialize(mx.init.Xavier())
rng = onp.random.RandomState(0)
lengths = rng.randint(1, MAXLEN + 1, size=N_REQ).tolist()
reqs = [mx.nd.array(rng.randn(n, WIDTH).astype(onp.float32))
        for n in lengths]

eng = serving.ServingEngine(net, max_delay_us=200)
# deploy-time AOT warmup (ProgramStore): compile the pow2 grid up to
# MAXLEN off the request path; compile_s is the whole tax paid here
from mxnet_tpu import program_store
t_warm = time.perf_counter()
warmup_programs = eng.warmup(
    mx.nd.array(onp.zeros((1, WIDTH), onp.float32)), max_rows=MAXLEN)
compile_s = time.perf_counter() - t_warm
# the first real request per bucket still pays its one-time verify
b = 1
while b <= MAXLEN:
    eng.infer(mx.nd.array(rng.randn(b, WIDTH).astype(onp.float32)))
    b <<= 1
warm_traces = serving.trace_count()
warm_progs = len(eng._programs)

# phase 1: sequential stream (per-request latency, retrace bar)
t0 = serving.trace_count(); d0 = serving.dispatch_count()
h0 = serving.bucket_stats()
t_start = time.perf_counter()
outs = [eng.infer(r) for r in reqs]
_ = float(outs[-1].asnumpy().ravel()[0])          # fence
dt = time.perf_counter() - t_start
seq = eng.stats()
retraces = serving.trace_count() - t0
h1 = serving.bucket_stats()

# phase 2: concurrent stream (micro-batcher coalescing)
import threading
eng2 = serving.ServingEngine(net, max_delay_us=3000)
for bb in (1, 2, 4, 8, 16, 32, 64):
    if bb <= serving.BucketPolicy().bucket(MAXLEN * THREADS):
        eng2.infer(mx.nd.array(rng.randn(bb, WIDTH).astype(onp.float32)))
errs = []
def fire(chunk):
    try:
        for r in chunk:
            eng2.infer(r)
    except BaseException as e:
        errs.append(repr(e))
threads = [threading.Thread(target=fire, args=(reqs[i::THREADS],))
           for i in range(THREADS)]
t2 = time.perf_counter()
for t in threads: t.start()
for t in threads: t.join()
dt2 = time.perf_counter() - t2
conc = eng2.stats()
assert not errs, errs

import jax
from mxnet_tpu import telemetry
telemetry.flush()   # flight-recorder shard for the lane's fleet merge
_disk = program_store.disk_stats()
print(json.dumps({
    "platform": jax.default_backend(),
    # full namespaced counter snapshot (process-fresh == delta from 0);
    # the hand-picked keys below stay as aliases for BENCH_* continuity
    "telemetry": {k: v for k, v in telemetry.snapshot().items() if v},
    "requests": N_REQ,
    "buckets": serving.BucketPolicy().spec,
    "programs": seq["programs"],
    "warmup_programs": warmup_programs,
    "compile_s": round(compile_s, 3),
    "cache_hits": _disk["hits"],
    "cache_misses": _disk["misses"],
    "warm_traces": warm_traces,
    "retraces_after_warm": retraces,
    "bucket_hits": h1["hits"] - h0["hits"],
    "bucket_misses": h1["misses"] - h0["misses"],
    "dispatches": serving.dispatch_count() - d0,
    "p50_us": seq["p50_us"],
    "p99_us": seq["p99_us"],
    "throughput_rps": N_REQ / dt,
    "concurrent": {
        "threads": THREADS,
        "batches": conc["batches"],
        "requests": conc["requests"],
        "coalesced": conc["coalesced"],
        "requests_per_dispatch": conc["requests"] / max(conc["batches"], 1),
        "p99_us": conc["p99_us"],
        "throughput_rps": conc["requests"] / dt2,
    },
}))
eng.close(); eng2.close()
"""


_DECODE_WORKER = r"""
import json, os, sys, threading, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))) if "__file__" in dir() else "/root/repo")
import numpy as onp
from mxnet_tpu import program_store, serving_decode as sd

CONC = int(os.environ.get("DECODE_CONCURRENCY", "8"))
REQS = int(os.environ.get("DECODE_REQUESTS", "16"))
NEW = int(os.environ.get("DECODE_NEW_TOKENS", "8"))
STORM = os.environ.get("DECODE_STORM", "1") == "1"

def fast_model():
    return sd.TinyCausalLM(vocab=128, d_model=64, n_layers=2, n_heads=4,
                           max_seq=64)

def slow_model():
    # the deliberately slow co-tenant: ~2.5x the per-step FLOPs of the
    # fast model — slow per TOKEN, while its own admission queue
    # (max_queue below) bounds how much of the host it can occupy.
    # Interference is bounded by the WORST single slow dispatch (the
    # gate is non-preemptive), so the tenant is deep, not wide.
    return sd.TinyCausalLM(vocab=128, d_model=72, n_layers=4, n_heads=4,
                           max_seq=64)

rng = onp.random.RandomState(0)
def mk_prompts(n, lo=2, hi=17):
    return [rng.randint(0, 128, size=rng.randint(lo, hi)).tolist()
            for _ in range(n)]

def drive(eng, prompts, conc, poisson_rate=None, new=NEW):
    '''Submit prompts from conc client threads (optionally with bursty
    Poisson inter-arrival sleeps); returns (wall_s, tokens, sheds).'''
    errs, sheds, tokens = [], [0], [0]
    lock = threading.Lock()
    def fire(chunk):
        for p in chunk:
            if poisson_rate:
                time.sleep(rng.exponential(1.0 / poisson_rate))
            try:
                out = eng.generate(p, max_new_tokens=new)
                with lock:
                    tokens[0] += len(out)
            except sd.ShedError:
                with lock:
                    sheds[0] += 1
            except BaseException as e:
                errs.append(e)
    threads = [threading.Thread(target=fire, args=(prompts[i::conc],))
               for i in range(conc)]
    t0 = time.perf_counter()
    for t in threads: t.start()
    for t in threads: t.join()
    if errs:
        raise errs[0]
    return time.perf_counter() - t0, tokens[0], sheds[0]

# ---- continuous-batching A/B ------------------------------------------
model = fast_model(); params = model.init_params(0)
pool = sd.PagePool(pages=256, page=8)
eng = sd.GenerativeEngine(model, params=params, pool=pool,
                          max_rows=max(8, CONC), name="fast")
t_warm = time.perf_counter()
warmup_programs = eng.warmup(max_len=16)
compile_s = time.perf_counter() - t_warm
prompts = mk_prompts(REQS)
eng.generate(prompts[0], max_new_tokens=2)       # first-dispatch warm
t0, d0 = sd.trace_count(), sd.dispatch_count()
seq_s, seq_tok, _ = drive(eng, prompts, conc=1)  # one request at a time
conc_s, conc_tok, _ = drive(eng, prompts, conc=CONC)
st = eng.stats()
retraces = sd.trace_count() - t0
seq_tps, conc_tps = seq_tok / seq_s, conc_tok / conc_s

out = {
    "platform": __import__("jax").default_backend(),
    "requests": REQS, "concurrency": CONC, "new_tokens": NEW,
    "programs": st["programs"], "warmup_programs": warmup_programs,
    "compile_s": round(compile_s, 3),
    "retraces_after_warm": retraces,
    "dispatches": sd.dispatch_count() - d0,
    "rows_per_decode": round(st.get("rows_per_decode", 0.0), 2),
    "sequential_tokens_s": round(seq_tps, 1),
    "continuous_tokens_s": round(conc_tps, 1),
    "batching_speedup": round(conc_tps / max(seq_tps, 1e-9), 2),
    "p50_us": round(st["p50_us"], 1), "p99_us": round(st["p99_us"], 1),
    "pool": {k: st["pool"][k] for k in
             ("pages", "page", "in_use", "high_water")},
}
eng.close()

# ---- multi-tenant storm ------------------------------------------------
if STORM:
    fparams, sparams = params, slow_model().init_params(1)
    def storm_phase(with_slow):
        pool = sd.PagePool(pages=256, page=8)
        # the fast tenant carries an SLO -> it outranks the slow tenant
        # at the shared dispatch gate (most-urgent-first ordering)
        fe = sd.GenerativeEngine(fast_model(), params=fparams, pool=pool,
                                 max_rows=8, name="fast",
                                 slo_us=500_000)
        fe.warmup(max_len=16)
        agents = []
        if with_slow:
            se = sd.GenerativeEngine(slow_model(), params=sparams,
                                     pool=pool, max_rows=2, max_queue=2,
                                     name="slow")
            se.warmup(max_len=16)        # covers the 4..12-token prompts
            # the slow tenant gets hammered past its tiny queue so the
            # storm also shows load SHEDDING, not just interference —
            # shed requests are refused at ADMISSION (no compute), so
            # arrival pressure exceeds its 2-row/2-queue capacity
            # without the host saturating (which would measure CPU
            # contention, not co-tenancy)
            agents.append((se, mk_prompts(14, 4, 13), 7, 50.0))
        # >= 101 fast samples so p99 is a real percentile, not the
        # single unluckiest burst
        agents.append((fe, mk_prompts(104), 8, 40.0))
        results = {}
        def run(eng, prompts, conc, rate):
            results[eng.name] = drive(eng, prompts, conc,
                                      poisson_rate=rate)
        ths = [threading.Thread(target=run, args=a) for a in agents]
        for t in ths: t.start()
        for t in ths: t.join()
        stats = {}
        for eng, _p, _c, _r in agents:
            s = eng.stats()
            wall, tok, shed = results[eng.name]
            stats[eng.name] = {
                "p50_us": round(s["p50_us"], 1),
                "p99_us": round(s["p99_us"], 1),
                "tokens_s": round(tok / wall, 1),
                "shed": s["shed"], "preempts": s["preempts"],
                "slo_violations": s["slo_violations"],
                "delivered": s["delivered"],
            }
            eng.close()
        return stats
    solo = storm_phase(with_slow=False)["fast"]
    storm = storm_phase(with_slow=True)
    out["storm"] = {
        "fast_solo_p99_us": solo["p99_us"],
        "fast": storm["fast"], "slow": storm["slow"],
        "interference_p99_ratio": round(
            storm["fast"]["p99_us"] / max(solo["p99_us"], 1e-9), 2),
        "shed_total": storm["fast"]["shed"] + storm["slow"]["shed"],
    }

    # ---- router storm: the availability columns -----------------------
    # 2 replicas behind a ReplicaRouter, bursty arrivals, one replica
    # KILLED mid-storm: the columns the fault-tolerant serving plane is
    # judged on — dropped (must be 0), hedged, failed_over, breaker
    # transitions — ride the bench artifact so availability regressions
    # are visible round over round like every perf number.
    from mxnet_tpu.serving_router import ReplicaRouter
    rpools = [sd.PagePool(pages=256, page=8) for _ in range(2)]
    rengines = [sd.GenerativeEngine(fast_model(), params=fparams,
                                    pool=rpools[i], max_rows=8,
                                    name=f"rr{i}") for i in range(2)]
    for e in rengines:
        e.warmup(max_len=16)
    router = ReplicaRouter(rengines, name="bench", breaker_errs=2,
                           breaker_cooldown_s=0.5, hedge_pctl=95)
    rprompts = mk_prompts(48)
    delivered, shed, rerrs = [0], [0], []
    rlock = threading.Lock()
    def rfire(chunk):
        for p in chunk:
            time.sleep(rng.exponential(1.0 / 40.0))
            try:
                router.generate(p, max_new_tokens=NEW,
                                deadline_us=20_000_000)
                with rlock:
                    delivered[0] += 1
            except sd.ShedError:
                with rlock:
                    shed[0] += 1
            except BaseException as e:
                rerrs.append(repr(e))
    rthreads = [threading.Thread(target=rfire, args=(rprompts[i::8],))
                for i in range(8)]
    t0 = time.perf_counter()
    for t in rthreads: t.start()
    time.sleep(0.3)                       # storm rolling: kill replica 0
    def rboom(*a, **k):
        raise RuntimeError("bench replica kill")
    rengines[0].generate = rboom
    for t in rthreads: t.join()
    rwall = time.perf_counter() - t0
    rst = router.stats()
    out["router_storm"] = {
        "requests": len(rprompts),
        "delivered": delivered[0],
        "dropped": len(rprompts) - delivered[0] - shed[0],
        "shed": shed[0],
        "errors": rerrs,
        "hedged": rst["hedges"],
        "failed_over": rst["failovers"],
        "breaker_transitions": (rst["breaker_opens"]
                                + rst["breaker_half_opens"]
                                + rst["breaker_closes"]),
        "p50_us": round(rst["p50_us"], 1),
        "p99_us": round(rst["p99_us"], 1),
        "tokens_s": round(delivered[0] * NEW / rwall, 1),
        "wall_s": round(rwall, 2),
    }
    for e in rengines:
        e.close()

    # ---- elastic storm: the ISSUE-17 autoscaler columns ---------------
    # 1 replica + a FleetSupervisor under the same bursty arrivals: the
    # artifact stamps the replica-count TIMELINE, the scale event
    # counts, and fleet tokens/s — autoscaler regressions (flapping,
    # never scaling, slow joins, failure to shrink back) show up round
    # over round like every latency number.
    from mxnet_tpu.serving_router import FleetSupervisor
    def espawn():
        epool = sd.PagePool(pages=256, page=8)
        ee = sd.GenerativeEngine(fast_model(), params=fparams,
                                 pool=epool, max_rows=8,
                                 name="elastic")
        ee.warmup(max_len=16)
        return ee
    erouter = ReplicaRouter([espawn()], name="elastic",
                            breaker_errs=2, breaker_cooldown_s=0.5,
                            hedge_pctl=95)
    def eretire(eng_, index):
        eng_.close()
    esup = FleetSupervisor(erouter, espawn, retire=eretire,
                           enabled=True, min_replicas=1,
                           max_replicas=3, cooldown_s=0.4,
                           interval_s=0.05, up_queue=1.0,
                           down_queue=0.1,
                           warmup_kwargs={"max_len": 16})
    esup.start()
    # long enough a burst that the first join COMPLETES mid-storm (an
    # in-process spawn pays a warmup, not a process boot)
    eprompts = mk_prompts(288)
    edelivered, eshed, eerrs = [0], [0], []
    elock = threading.Lock()
    def efire(chunk):
        for p in chunk:
            time.sleep(rng.exponential(1.0 / 60.0))
            try:
                erouter.generate(p, max_new_tokens=NEW,
                                 deadline_us=30_000_000)
                with elock:
                    edelivered[0] += 1
            except sd.ShedError:
                with elock:
                    eshed[0] += 1
            except BaseException as e:
                eerrs.append(repr(e))
    ethreads = [threading.Thread(target=efire,
                                 args=(eprompts[i::12],))
                for i in range(12)]
    timeline = []
    t0 = time.perf_counter()
    for t in ethreads: t.start()
    while any(t.is_alive() for t in ethreads):
        timeline.append([round(time.perf_counter() - t0, 2),
                         erouter.serving_replicas()])
        time.sleep(0.05)
    for t in ethreads: t.join()
    ewall = time.perf_counter() - t0
    # let the burst subside so the supervisor shrinks back to the
    # floor; the minimum wait catches a join that completes just after
    # the last request (a spawn in flight when the storm ended)
    tdown_min = time.perf_counter() + 3.0
    tdown_max = time.perf_counter() + 20.0
    while time.perf_counter() < tdown_max and (
            time.perf_counter() < tdown_min
            or erouter.serving_replicas() > 1):
        timeline.append([round(time.perf_counter() - t0, 2),
                         erouter.serving_replicas()])
        time.sleep(0.05)
    esup.stop()
    efleet = erouter.fleet_stats()
    out["elastic_storm"] = {
        "requests": len(eprompts),
        "delivered": edelivered[0],
        "dropped": len(eprompts) - edelivered[0] - eshed[0],
        "shed": eshed[0],
        "errors": eerrs,
        "scale_ups": efleet["scale_ups"],
        "scale_downs": efleet["scale_downs"],
        "joins": efleet["joins"],
        "drains": efleet["drains"],
        "scale_errors": efleet["scale_errors"],
        "peak_replicas": max((n for _, n in timeline), default=1),
        "final_replicas": erouter.serving_replicas(),
        "replica_timeline": timeline[:400],
        "fleet_tokens_s": round(edelivered[0] * NEW / ewall, 1),
        "wall_s": round(ewall, 2),
    }
    for r in list(erouter._replicas):
        if hasattr(r.engine, "close"):
            r.engine.close()

_disk = program_store.disk_stats()
out["cache_hits"] = _disk["hits"]
out["cache_misses"] = _disk["misses"]
from mxnet_tpu import telemetry
telemetry.flush()   # flight-recorder shard for the lane's fleet merge
# full namespaced counter snapshot (process-fresh == delta from 0);
# the hand-picked keys above stay as aliases for BENCH_* continuity
out["telemetry"] = {k: v for k, v in telemetry.snapshot().items() if v}
print(json.dumps(out))
"""


_PREFIX_WORKER = r"""
import json, os, sys, threading, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))) if "__file__" in dir() else "/root/repo")
import numpy as onp
import jax
from mxnet_tpu import serving_decode as sd, telemetry

USERS = int(os.environ.get("PREFIX_USERS", "16"))
NEW = int(os.environ.get("PREFIX_NEW_TOKENS", "8"))

def fast_model():
    return sd.TinyCausalLM(vocab=128, d_model=64, n_layers=2, n_heads=4,
                           max_seq=64)

model = fast_model(); params = model.init_params(0)
rng = onp.random.RandomState(0)
# the one shared system prompt: 32 tokens = 4 full page-8 blocks, so
# USERS identical prompts prefill once and the rest full-hit.  Hit rate
# over the storm = (USERS-1)*4 / (USERS*4) = 0.9375 for USERS=16 — the
# >= 0.9 acceptance floor with margin, and deterministic.
SYS = rng.randint(0, 128, size=32).tolist()

def storm(knob):
    '''One pass of the USERS-identical-prompt storm with the prefix
    cache forced on/off; returns (outputs, wall_s, prefix counter
    deltas, prefill dispatch count).'''
    os.environ["MXNET_PREFIX_CACHE"] = knob
    pool = sd.PagePool(pages=256, page=8)
    eng = sd.GenerativeEngine(fast_model(), params=params, pool=pool,
                              max_rows=max(8, USERS), name="px" + knob)
    eng.warmup(max_len=16)
    eng.generate(rng.randint(0, 128, size=5).tolist(), max_new_tokens=2)
    base = telemetry.snapshot()
    outs = {}
    errs = []
    lock = threading.Lock()
    t0 = time.perf_counter()
    # primer: the one physical prefill the shared prompt should cost
    outs[0] = eng.generate(list(SYS), max_new_tokens=NEW)
    def fire(uid):
        try:
            out = eng.generate(list(SYS), max_new_tokens=NEW)
            with lock:
                outs[uid] = out
        except BaseException as e:
            errs.append(repr(e))
    ths = [threading.Thread(target=fire, args=(u,))
           for u in range(1, USERS)]
    for t in ths: t.start()
    for t in ths: t.join()
    wall = time.perf_counter() - t0
    if errs:
        raise RuntimeError("; ".join(errs))
    delta = telemetry.delta(base)
    prefills = sum(int(v) for k, v in delta.items()
                   if k.startswith("decode.engine")
                   and k.endswith(".prefills"))
    eng.close()
    if pool.in_use():
        raise RuntimeError(f"leaked {pool.in_use()} pages (knob={knob})")
    bad = pool.audit()
    if bad:
        raise RuntimeError(f"pool audit failed (knob={knob}): {bad}")
    px = {k.split(".", 1)[1]: int(v) for k, v in delta.items()
          if k.startswith("prefix.")}
    return [outs[u] for u in range(USERS)], wall, px, delta, prefills

warm_outs, warm_wall, px, warm_delta, warm_prefills = storm("1")
cold_outs, cold_wall, px_off, _cold_delta, cold_prefills = storm("0")
if any(v for v in px_off.values()):
    raise RuntimeError(f"prefix counters nonzero with the knob off: {px_off}")
oracle = list(sd.eager_generate(model, params, list(SYS),
                                max_new_tokens=NEW))
token_exact = all(o == oracle for o in warm_outs) and \
    all(o == oracle for o in cold_outs)

hit = px.get("hit_blocks", 0)
miss = px.get("miss_blocks", 0)
hit_rate = hit / max(hit + miss, 1)
page = 8
# prefill work avoided: every hit block skips `page` prompt tokens of
# prefill compute.  FLOPs estimated analytically from the model dims
# (projections + MLP; attention's quadratic term excluded, so the
# stamp is a floor).
d = model.d_model
flops_per_tok = model.n_layers * (8 * d * d + 4 * d * d)
tokens_saved = hit * page
chips = max(jax.device_count(), 1)
lane = {
    "metric": "prefix_shared_storm",
    "platform": jax.default_backend(),
    "users": USERS, "prompt_tokens": len(SYS), "new_tokens": NEW,
    "prefix_hit_rate": round(hit_rate, 4),
    "prefix_hit_blocks": hit, "prefix_miss_blocks": miss,
    "prefix_cow_forks": px.get("cow_forks", 0),
    "prefix_evictions": px.get("evictions", 0),
    "prefill_tokens_saved": tokens_saved,
    "prefill_flops_saved": tokens_saved * flops_per_tok,
    "prefills_warm": warm_prefills, "prefills_cold": cold_prefills,
    "warm_wall_s": round(warm_wall, 3), "cold_wall_s": round(cold_wall, 3),
    "warm_tokens_s_per_chip": round(USERS * NEW / warm_wall / chips, 1),
    "cold_tokens_s_per_chip": round(USERS * NEW / cold_wall / chips, 1),
    "token_exact": token_exact,
}
telemetry.flush()   # flight-recorder shard for the lane's fleet merge
lane["telemetry"] = {k: v for k, v in warm_delta.items() if v}
print(json.dumps(lane))
"""


_SPEC_WORKER = r"""
import json, os, sys, threading, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))) if "__file__" in dir() else "/root/repo")
import numpy as onp
import jax
from mxnet_tpu import serving_decode as sd, telemetry

REQS = int(os.environ.get("SPEC_REQUESTS", "12"))
NEW = int(os.environ.get("SPEC_NEW_TOKENS", "24"))
K = int(os.environ.get("SPEC_K", "4"))
ENFORCE = os.environ.get("SPEC_ENFORCE", "1") == "1"

# the high-agreement pair: a deep target whose extra layers are
# identity, so draft logits == target logits (acceptance 1.0 by
# construction) while the target still pays 8x the draft's per-token
# compute — the workload speculation exists for
target, tp, draft, dp = sd.high_agreement_pair(
    vocab=128, d_model=64, target_layers=8, draft_layers=1,
    n_heads=4, max_seq=96, seed=0)

rng = onp.random.RandomState(0)
prompts = [rng.randint(0, 128, size=rng.randint(4, 13)).tolist()
           for _ in range(REQS)]

def run(spec_on, draft_model=None, draft_params=None, label="x"):
    '''One pass of the SAME greedy prompt set; returns tokens/s and the
    spec counters.  The knob is uncached, so the env flip scopes to
    the engine built under it.'''
    os.environ["MXNET_SPEC_DECODE"] = "1" if spec_on else "0"
    pool = sd.PagePool(pages=256, page=8)
    kw = (dict(draft=draft_model, draft_params=draft_params, spec_k=K)
          if draft_model is not None else {})
    # max_rows=2: decode-bound rows, the workload the k-for-1 verify
    # win targets (wide batches amortize dispatch on their own)
    eng = sd.GenerativeEngine(target, params=tp, pool=pool, max_rows=2,
                              name="spec_" + label, **kw)
    eng.warmup(max_len=16)
    eng.generate(prompts[0], max_new_tokens=2)   # first-dispatch warm
    outs, errs = {}, []
    lock = threading.Lock()
    def fire(i):
        try:
            out = eng.generate(prompts[i], max_new_tokens=NEW)
            with lock:
                outs[i] = out
        except BaseException as e:
            errs.append(repr(e))
    ths = [threading.Thread(target=fire, args=(i,)) for i in range(REQS)]
    t0 = time.perf_counter()
    for t in ths: t.start()
    for t in ths: t.join()
    wall = time.perf_counter() - t0
    if errs:
        raise RuntimeError("; ".join(errs))
    st = eng.stats()
    eng.close()
    if pool.in_use():
        raise RuntimeError(f"leaked {pool.in_use()} pages ({label})")
    bad = pool.audit()
    if bad:
        raise RuntimeError(f"pool audit failed ({label}): {bad}")
    toks = sum(len(o) for o in outs.values())
    return {
        "outs": [outs[i] for i in range(REQS)],
        "wall_s": wall, "tokens": toks, "tokens_s": toks / wall,
        "rounds": st["spec_rounds"], "proposed": st["spec_proposed"],
        "accepted": st["spec_accepted"],
        "fallbacks": st["spec_fallbacks"],
        "disabled": st["spec_disabled"],
        "decode_steps": st["decode_steps"],
    }

base = run(False, label="base")          # the non-spec baseline
# LOW-agreement leg first (so the final spec.* gauge snapshot reflects
# the healthy high-agreement pass): an independently-initialized draft
# whose proposals rarely match — the cost table must auto-disable and
# tokens/s must stay within 5% of baseline (never a regression)
low_draft = sd.TinyCausalLM(vocab=128, d_model=64, n_layers=1,
                            n_heads=4, max_seq=96)
low = run(True, low_draft, low_draft.init_params(99), label="low")
on = run(True, draft, dp, label="on")    # high-agreement speculation

oracle = [list(sd.eager_generate(target, tp, p, max_new_tokens=NEW))
          for p in prompts]
token_exact = (base["outs"] == oracle and on["outs"] == oracle
               and low["outs"] == oracle)
acceptance = on["accepted"] / max(on["proposed"], 1)
speedup = on["tokens_s"] / max(base["tokens_s"], 1e-9)
low_ratio = low["tokens_s"] / max(base["tokens_s"], 1e-9)

if ENFORCE:
    # the ISSUE-19 acceptance bar, enforced where it is measured
    if not token_exact:
        raise RuntimeError("speculative/baseline outputs diverge from "
                           "the eager oracle under greedy")
    if acceptance < 0.7:
        raise RuntimeError(f"acceptance {acceptance:.2f} < 0.7 on the "
                           "high-agreement draft")
    if speedup < 1.5:
        raise RuntimeError(f"speculative speedup {speedup:.2f}x < 1.5x "
                           f"({on['tokens_s']:.0f} vs "
                           f"{base['tokens_s']:.0f} tok/s)")
    if not low["disabled"]:
        raise RuntimeError("low-agreement draft never auto-disabled")
    if low_ratio < 0.95:
        raise RuntimeError(f"low-agreement leg ran at {low_ratio:.2f}x "
                           "baseline (must stay within 5%: disable "
                           "means degrade, never regress)")

lane = {
    "metric": "decode_speculative_tokens_per_s",
    "value": round(on["tokens_s"], 1),
    "platform": jax.default_backend(),
    "requests": REQS, "new_tokens": NEW, "spec_k": K,
    "baseline_tokens_s": round(base["tokens_s"], 1),
    "spec_tokens_s": round(on["tokens_s"], 1),
    "speedup": round(speedup, 2),
    "acceptance": round(acceptance, 4),
    "rounds": on["rounds"], "proposed": on["proposed"],
    "accepted": on["accepted"], "fallback_rounds": on["fallbacks"],
    "tokens_per_round": round(on["tokens"] / max(on["rounds"], 1), 2),
    "target_dispatches_per_token": round(
        (on["decode_steps"] + on["rounds"]) / max(on["tokens"], 1), 3),
    "low_agreement": {
        "tokens_s": round(low["tokens_s"], 1),
        "ratio_vs_baseline": round(low_ratio, 3),
        "autodisabled": low["disabled"],
        "rounds_before_disable": low["rounds"],
    },
    "token_exact": token_exact,
}
telemetry.flush()   # flight-recorder shard for the lane's fleet merge
lane["telemetry"] = {k: v for k, v in telemetry.snapshot().items() if v}
print(json.dumps(lane))
"""


def run_speculative(requests: int = 12, new_tokens: int = 24,
                    k: int = 4, enforce: bool = True) -> dict:
    env = dict(os.environ)
    env["SPEC_REQUESTS"] = str(requests)
    env["SPEC_NEW_TOKENS"] = str(new_tokens)
    env["SPEC_K"] = str(k)
    env["SPEC_ENFORCE"] = "1" if enforce else "0"
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
    r = subprocess.run([sys.executable, "-u", "-c", _SPEC_WORKER],
                       capture_output=True, text=True, timeout=900,
                       env=env,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))) or ".")
    if r.returncode != 0:
        raise RuntimeError(f"speculative lane failed:\n{r.stderr[-2000:]}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def run_shared_prefix(users: int = 16) -> dict:
    env = dict(os.environ)
    env["PREFIX_USERS"] = str(users)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
    r = subprocess.run([sys.executable, "-u", "-c", _PREFIX_WORKER],
                       capture_output=True, text=True, timeout=900,
                       env=env,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))) or ".")
    if r.returncode != 0:
        raise RuntimeError(f"shared-prefix lane failed:\n{r.stderr[-2000:]}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def run_decode(requests: int = 16, concurrency: int = 8,
               storm: bool = True) -> dict:
    env = dict(os.environ)
    env["DECODE_REQUESTS"] = str(requests)
    env["DECODE_CONCURRENCY"] = str(concurrency)
    env["DECODE_STORM"] = "1" if storm else "0"
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
    r = subprocess.run([sys.executable, "-u", "-c", _DECODE_WORKER],
                       capture_output=True, text=True, timeout=900,
                       env=env,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))) or ".")
    if r.returncode != 0:
        raise RuntimeError(f"decode lane failed:\n{r.stderr[-2000:]}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def run_serving(requests: int = 64, threads: int = 4) -> dict:
    env = dict(os.environ)
    env["SERVE_REQUESTS"] = str(requests)
    env["SERVE_THREADS"] = str(threads)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
    r = subprocess.run([sys.executable, "-u", "-c", _WORKER],
                       capture_output=True, text=True, timeout=900, env=env,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))) or ".")
    if r.returncode != 0:
        raise RuntimeError(f"serving lane failed:\n{r.stderr[-2000:]}")
    return json.loads(r.stdout.strip().splitlines()[-1])


def main() -> None:
    as_json = "--json" in sys.argv
    requests = 64
    if "--requests" in sys.argv:
        requests = int(sys.argv[sys.argv.index("--requests") + 1])
    threads = 4
    if "--threads" in sys.argv:
        threads = int(sys.argv[sys.argv.index("--threads") + 1])
    lane = run_serving(requests, threads)
    if as_json:
        print(json.dumps({"serving": lane}))
        return
    print(f"serving latency ({lane['platform']}, {lane['requests']} "
          f"variable-length requests, buckets={lane['buckets']})")
    print(f"programs {lane['programs']} (warm traces "
          f"{lane['warm_traces']}), retraces after warm "
          f"{lane['retraces_after_warm']}, bucket "
          f"{lane['bucket_hits']}h/{lane['bucket_misses']}m")
    print(f"sequential: p50 {lane['p50_us']:.0f} us, p99 "
          f"{lane['p99_us']:.0f} us, {lane['throughput_rps']:.1f} req/s")
    c = lane["concurrent"]
    print(f"concurrent ({c['threads']} threads): "
          f"{c['requests_per_dispatch']:.1f} requests/dispatch "
          f"({c['coalesced']} coalesced), p99 {c['p99_us']:.0f} us, "
          f"{c['throughput_rps']:.1f} req/s")


def main_decode(storm_only: bool = False) -> None:
    lane = run_decode(storm=True)
    print(f"decode lane ({lane['platform']}, {lane['requests']} requests "
          f"x {lane['new_tokens']} tokens, concurrency "
          f"{lane['concurrency']})")
    print(f"programs {lane['programs']} (warmup "
          f"{lane['warmup_programs']}), retraces after warm "
          f"{lane['retraces_after_warm']}, "
          f"{lane['rows_per_decode']} rows/decode-step")
    print(f"one-at-a-time {lane['sequential_tokens_s']} tok/s -> "
          f"continuous {lane['continuous_tokens_s']} tok/s "
          f"({lane['batching_speedup']}x)")
    s = lane.get("storm")
    if s:
        print(f"storm: fast p99 {s['fast']['p99_us']:.0f} us "
              f"(solo {s['fast_solo_p99_us']:.0f} us, "
              f"{s['interference_p99_ratio']}x), "
              f"fast {s['fast']['tokens_s']} tok/s / slow "
              f"{s['slow']['tokens_s']} tok/s, "
              f"{s['shed_total']} shed, "
              f"{s['slow']['preempts'] + s['fast']['preempts']} "
              "preempts")
    r = lane.get("router_storm")
    if r:
        print(f"router storm (1-of-2 replicas killed mid-storm): "
              f"{r['delivered']}/{r['requests']} delivered, "
              f"{r['dropped']} dropped, {r['shed']} shed, "
              f"{r['failed_over']} failed over, {r['hedged']} hedged, "
              f"{r['breaker_transitions']} breaker transitions, "
              f"p99 {r['p99_us']:.0f} us, {r['tokens_s']} tok/s")
    e = lane.get("elastic_storm")
    if e:
        print(f"elastic storm (autoscaler 1->{e['peak_replicas']}->"
              f"{e['final_replicas']} replicas): "
              f"{e['delivered']}/{e['requests']} delivered, "
              f"{e['dropped']} dropped, {e['shed']} shed, "
              f"{e['scale_ups']} up / {e['scale_downs']} down "
              f"({e['scale_errors']} errors, {e['joins']} joins / "
              f"{e['drains']} drains), fleet {e['fleet_tokens_s']} "
              f"tok/s over {e['wall_s']}s, "
              f"{len(e['replica_timeline'])} timeline samples")


def main_spec() -> None:
    lane = run_speculative()
    if "--json" in sys.argv:
        print(json.dumps({"speculative": lane}))
        return
    print(f"speculative decode ({lane['platform']}, {lane['requests']} "
          f"requests x {lane['new_tokens']} tokens, k={lane['spec_k']})")
    print(f"baseline {lane['baseline_tokens_s']} tok/s -> speculative "
          f"{lane['spec_tokens_s']} tok/s ({lane['speedup']}x), "
          f"acceptance {lane['acceptance']:.3f} "
          f"({lane['accepted']}/{lane['proposed']} over "
          f"{lane['rounds']} rounds, "
          f"{lane['tokens_per_round']} tokens/round, "
          f"{lane['target_dispatches_per_token']} target "
          "dispatches/token)")
    lo = lane["low_agreement"]
    print(f"low-agreement draft: auto-disabled after "
          f"{lo['rounds_before_disable']} rounds, "
          f"{lo['tokens_s']} tok/s "
          f"({lo['ratio_vs_baseline']:.2f}x baseline); token-exact vs "
          f"eager oracle: {lane['token_exact']}")


def main_prefix() -> None:
    lane = run_shared_prefix()
    if "--json" in sys.argv:
        print(json.dumps({"prefix": lane}))
        return
    print(f"shared-prefix storm ({lane['platform']}, {lane['users']} users "
          f"x one {lane['prompt_tokens']}-token system prompt)")
    print(f"prefix hit rate {lane['prefix_hit_rate']:.3f} "
          f"({lane['prefix_hit_blocks']}h/{lane['prefix_miss_blocks']}m "
          f"blocks), {lane['prefix_cow_forks']} COW forks, "
          f"{lane['prefix_evictions']} evictions")
    print(f"prefills: warm {lane['prefills_warm']} vs cold "
          f"{lane['prefills_cold']}; {lane['prefill_tokens_saved']} prompt "
          f"tokens ({lane['prefill_flops_saved'] / 1e6:.1f} MFLOPs) of "
          "prefill skipped")
    print(f"throughput: warm {lane['warm_tokens_s_per_chip']} vs cold "
          f"{lane['cold_tokens_s_per_chip']} tok/s/chip; token-exact "
          f"vs cold + eager oracle: {lane['token_exact']}")


if __name__ == "__main__":
    if "--serve-only" in sys.argv:
        # bench.py's lanes[] entry point: the one serving lane
        lane = run_serving()
        print(json.dumps({"serving": lane}) if "--json" in sys.argv
              else lane)
    elif "--decode-only" in sys.argv:
        # bench.py's decode lane entry point
        lane = run_decode()
        print(json.dumps({"decode": lane}) if "--json" in sys.argv
              else lane)
    elif "--shared-prefix" in sys.argv:
        # ISSUE-16 lane: M users x one system prompt through the
        # content-addressed prefix cache, warm vs cold vs eager oracle
        main_prefix()
    elif "--speculative" in sys.argv:
        # ISSUE-19 lane: spec on (high-agreement draft) vs the non-spec
        # baseline on the same prompt set, plus the low-agreement
        # auto-disable leg — acceptance bars enforced in the worker
        main_spec()
    elif "--storm" in sys.argv:
        main_decode(storm_only=True)
    else:
        main()
