#!/usr/bin/env python
"""Per-operator micro-benchmark runner.

Reference analog: ``benchmark/opperf/opperf.py`` + op discovery in
``benchmark/opperf/utils/op_registry_utils.py`` — time every registered
operator's forward (and backward) for regression hunting.

Usage:
  python benchmark/opperf/opperf.py                 # representative set
  python benchmark/opperf/opperf.py --ops relu,dot  # specific ops
  python benchmark/opperf/opperf.py --all           # every auto-runnable op
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax  # noqa: E402
import numpy as onp  # noqa: E402

# ops that need structured attrs: name -> (input shapes, attrs)
_SPECIAL = {
    "FullyConnected": ([(64, 256), (512, 256), (512,)],
                       {"num_hidden": 512}),
    "Convolution": ([(8, 32, 28, 28), (64, 32, 3, 3), (64,)],
                    {"kernel": (3, 3), "num_filter": 64}),
    "Pooling": ([(8, 32, 28, 28)], {"kernel": (2, 2), "pool_type": "max",
                                    "stride": (2, 2)}),
    "softmax": ([(128, 1000)], {}),
    "log_softmax": ([(128, 1000)], {}),
    "dot": ([(512, 512), (512, 512)], {}),
    "batch_dot": ([(32, 128, 128), (32, 128, 128)], {}),
    "sum": ([(256, 1024)], {"axis": 1}),
    "mean": ([(256, 1024)], {"axis": 1}),
    "take": ([(1000, 128), (64,)], {}),
    "embedding": ([(64,), (1000, 128)], {"input_dim": 1000,
                                         "output_dim": 128}),
    "LayerNorm": ([(64, 768), (768,), (768,)], {}),
    "transpose": ([(256, 256)], {}),
    "reshape": ([(256, 256)], {"shape": (65536,)}),
}

_DEFAULT_SET = list(_SPECIAL) + [
    "relu", "sigmoid", "tanh", "exp", "log", "sqrt", "square", "abs",
    "broadcast_add", "broadcast_mul", "broadcast_div", "clip",
]


def _inputs_for(name, schema):
    import mxnet_tpu as mx

    if name in _SPECIAL:
        shapes, attrs = _SPECIAL[name]
        rng = onp.random.RandomState(0)
        arrays = []
        for i, s in enumerate(shapes):
            if name in ("take", "embedding") and i == (1 if name == "take"
                                                       else 0):
                arrays.append(mx.nd.array(
                    rng.randint(0, 100, s).astype(onp.int32)))
            else:
                arrays.append(mx.nd.array(rng.rand(*s).astype(onp.float32)
                                          + 0.1))
        return arrays, attrs
    rng = onp.random.RandomState(0)
    n = schema.num_inputs if schema.num_inputs > 0 else 1
    arrays = [mx.nd.array(rng.rand(256, 256).astype(onp.float32) + 0.1)
              for _ in range(n)]
    return arrays, {}


def bench_op(name, warmup=3, runs=20, with_backward=True):
    import mxnet_tpu as mx
    from mxnet_tpu.ops.registry import find_op

    schema = find_op(name)
    if schema is None:
        return {"op": name, "error": "not registered"}
    try:
        arrays, attrs = _inputs_for(name, schema)
        invoke = mx.nd.invoke

        def fwd():
            out = invoke(schema, arrays, dict(attrs))
            (out[0] if isinstance(out, list) else out).wait_to_read()
            return out

        for _ in range(warmup):
            fwd()
        t0 = time.perf_counter()
        for _ in range(runs):
            fwd()
        fwd_ms = (time.perf_counter() - t0) / runs * 1e3

        bwd_ms = None
        if with_backward and schema.differentiable:
            for a in arrays:
                if a.dtype.kind == "f":
                    a.attach_grad()

            def step():
                with mx.autograd.record():
                    out = invoke(schema, arrays, dict(attrs))
                    head = (out[0] if isinstance(out, list) else out).sum()
                head.backward()
                head.wait_to_read()

            try:
                for _ in range(warmup):
                    step()
                t0 = time.perf_counter()
                for _ in range(runs):
                    step()
                bwd_ms = (time.perf_counter() - t0) / runs * 1e3
            except Exception:
                bwd_ms = None
        return {"op": name, "avg_forward_ms": round(fwd_ms, 4),
                "avg_fwd_bwd_ms": round(bwd_ms, 4) if bwd_ms else None}
    except Exception as e:  # keep the sweep going
        return {"op": name, "error": str(e)[:200]}


def run_benchmark(ops=None, warmup=3, runs=20):
    results = [bench_op(op, warmup, runs) for op in ops or _DEFAULT_SET]
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default=None,
                    help="comma-separated op names")
    ap.add_argument("--all", action="store_true",
                    help="sweep every registered op with generic inputs")
    ap.add_argument("--runs", type=int, default=20)
    ap.add_argument("--output", default=None)
    ap.add_argument("--eager-latency", action="store_true",
                    help="run the eager-dispatch A/B lane (per-op jit "
                         "cache vs plain dispatch, benchmark/"
                         "eager_latency.py) instead of the op sweep")
    args = ap.parse_args()

    if args.eager_latency:
        import subprocess

        script = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "eager_latency.py")
        cmd = [sys.executable, script, "--ops", str(args.runs)]
        if args.output:
            out = subprocess.run(cmd + ["--json"], capture_output=True,
                                 text=True)
            if out.returncode == 0:
                with open(args.output, "w") as f:
                    f.write(out.stdout)
            sys.stdout.write(out.stdout)
            sys.stderr.write(out.stderr)
            raise SystemExit(out.returncode)
        raise SystemExit(subprocess.call(cmd))

    if args.ops:
        ops = args.ops.split(",")
    elif args.all:
        from mxnet_tpu.ops.registry import list_ops

        ops = list_ops()
    else:
        ops = _DEFAULT_SET
    results = run_benchmark(ops, runs=args.runs)
    text = json.dumps(results, indent=1)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    print(text)


if __name__ == "__main__":
    main()
