#!/bin/bash
# Background TPU-window watcher (round 5).
#
# Probes the tunnel every PROBE_EVERY seconds with a tiny bounded matmul
# subprocess (a wedged tunnel costs one timeout, never a hang).  On a
# healthy window it runs the queued on-chip work in priority order
# (benchmark/chip_session.md), full driver-style bench FIRST so even an
# early re-wedge leaves the most valuable artifact.  Every run goes
# through `timeout` so no item can wedge the watcher itself.
#
# State files (benchmark/.watch/): one marker per completed item.
# Touch benchmark/.watch/rerun_bench to request a bench re-run after a
# perf-relevant code change lands (refreshes .jax_cache for the driver).
set -u
cd /root/repo
mkdir -p benchmark/.watch
LOG=benchmark/tpu_watch.log
export JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache
PROBE_EVERY=${PROBE_EVERY:-240}

log() { echo "[watch $(date +%H:%M:%S)] $*" >> "$LOG"; }

probe() {
    timeout 75 python - <<'EOF' >> "$LOG" 2>&1
import jax, jax.numpy as jnp
x = jnp.ones((256, 256))
v = float((x @ x)[0, 0])
assert jax.default_backend() == "tpu", jax.default_backend()
print("probe OK:", jax.default_backend(), v)
EOF
}

run_item() {  # run_item <marker> <budget_s> <cmd...>
    local marker=$1 budget=$2; shift 2
    [ -e "benchmark/.watch/$marker" ] && return 0
    log "running $marker: $*"
    if timeout "$budget" "$@" >> "$LOG" 2>&1; then
        touch "benchmark/.watch/$marker"
        log "$marker DONE"
    else
        log "$marker FAILED/TIMED OUT (rc=$?)"
        return 1
    fi
}

log "watcher started (probe every ${PROBE_EVERY}s)"
while true; do
    if probe; then
        log "tunnel healthy"
        # 1. full driver-style bench — the round's defining artifact
        if [ ! -e benchmark/.watch/bench_full ] || [ -e benchmark/.watch/rerun_bench ]; then
            rm -f benchmark/.watch/rerun_bench benchmark/.watch/bench_full
            log "running bench_full"
            if timeout 2400 python bench.py > benchmark/.watch/bench_full.out 2>> "$LOG"; then
                tail -1 benchmark/.watch/bench_full.out > BENCH_builder_r05.json
                touch benchmark/.watch/bench_full
                log "bench_full DONE: $(tail -c 300 BENCH_builder_r05.json)"
            else
                log "bench_full FAILED/TIMED OUT (rc=$?)"
            fi
        fi
        probe || { log "tunnel lost after bench"; sleep "$PROBE_EVERY"; continue; }
        # 2026-08-01 session 2: items 2-5 of the original queue (micro-
        # bench, ablations, profile, eager latency, remat bs256) were all
        # captured on chip (benchmark/chip_session.md, docs/PERF.md) —
        # what remains is re-validating the FINAL big-index code and one
        # BERT batch-sweep experiment.
        # 2. large-tensor on-chip test (>2^31 elements in HBM), final code
        run_item large_tensor_final 1800 env MXNET_TEST_ALLOW_TPU=1 python -m pytest tests/test_large_tensor.py -x -q -m tpu --no-header
        # 3. BERT batch sweep: does bs64 lift the 45.6% MFU?
        run_item bert_bs64 1200 env BENCH_MODEL=bert BENCH_BATCH=64 python bench.py
    else
        log "tunnel down"
    fi
    sleep "$PROBE_EVERY"
done
