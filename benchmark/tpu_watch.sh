#!/bin/bash
# Background TPU-window watcher (round 5).
#
# Probes the tunnel every PROBE_EVERY seconds with a tiny bounded matmul
# subprocess (a wedged tunnel costs one timeout, never a hang).  On a
# healthy window it runs the queued on-chip work in priority order
# (benchmark/chip_session.md), full driver-style bench FIRST so even an
# early re-wedge leaves the most valuable artifact.  Every run goes
# through `timeout` so no item can wedge the watcher itself.
#
# State files (benchmark/.watch/): one marker per completed item.
# Touch benchmark/.watch/rerun_bench to request a bench re-run after a
# perf-relevant code change lands (refreshes .jax_cache for the driver).
set -u
cd /root/repo
mkdir -p benchmark/.watch
LOG=benchmark/tpu_watch.log
export JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache
PROBE_EVERY=${PROBE_EVERY:-240}

log() { echo "[watch $(date +%H:%M:%S)] $*" >> "$LOG"; }

probe() {
    timeout 75 python - <<'EOF' >> "$LOG" 2>&1
import jax, jax.numpy as jnp
x = jnp.ones((256, 256))
v = float((x @ x)[0, 0])
assert jax.default_backend() == "tpu", jax.default_backend()
print("probe OK:", jax.default_backend(), v)
EOF
}

run_item() {  # run_item <marker> <budget_s> <cmd...>
    local marker=$1 budget=$2; shift 2
    [ -e "benchmark/.watch/$marker" ] && return 0
    log "running $marker: $*"
    if timeout "$budget" "$@" >> "$LOG" 2>&1; then
        touch "benchmark/.watch/$marker"
        log "$marker DONE"
    else
        log "$marker FAILED/TIMED OUT (rc=$?)"
        return 1
    fi
}

log "watcher started (probe every ${PROBE_EVERY}s)"
while true; do
    if probe; then
        log "tunnel healthy"
        # 1. full driver-style bench — the round's defining artifact
        if [ ! -e benchmark/.watch/bench_full ] || [ -e benchmark/.watch/rerun_bench ]; then
            rm -f benchmark/.watch/rerun_bench benchmark/.watch/bench_full
            log "running bench_full"
            if timeout 2400 python bench.py > benchmark/.watch/bench_full.out 2>> "$LOG"; then
                tail -1 benchmark/.watch/bench_full.out > BENCH_builder_r05.json
                touch benchmark/.watch/bench_full
                log "bench_full DONE: $(tail -c 300 BENCH_builder_r05.json)"
            else
                log "bench_full FAILED/TIMED OUT (rc=$?)"
            fi
        fi
        probe || { log "tunnel lost after bench"; sleep "$PROBE_EVERY"; continue; }
        # 2. microbench (s8-vs-bf16, epilogue, BN cost)
        run_item microbench 900 python benchmark/microbench_tpu.py
        # 3. bf16 ablation rows
        run_item ablation_nchw 900 env BENCH_MODEL=resnet50_v1_bf16 BENCH_LAYOUT=NCHW BENCH_S2D=0 python bench.py
        run_item ablation_nhwc 900 env BENCH_MODEL=resnet50_v1_bf16 BENCH_LAYOUT=NHWC BENCH_S2D=0 python bench.py
        # 4. train-step profile
        run_item profile 600 python benchmark/profile_step.py --steps 5 --top 30
        # 4b. eager dispatch latency A/B (per-op jit cache vs plain);
        # outer budget > sum of the script's two 900s inner subprocesses
        run_item eager_latency 2000 python benchmark/eager_latency.py
        # 5. remat headroom at bs256
        run_item remat_bs256 1200 env BENCH_MODEL=resnet50_v1_bf16 BENCH_BATCH=256 MXNET_BACKWARD_DO_MIRROR=1 python bench.py
        # 6. large-tensor on-chip test (>2^31 elements in HBM)
        run_item large_tensor 900 env MXNET_TEST_ALLOW_TPU=1 python -m pytest tests/test_large_tensor.py -x -q -m tpu --no-header
    else
        log "tunnel down"
    fi
    sleep "$PROBE_EVERY"
done
