#!/usr/bin/env python
"""Distributed job launcher.

Reference analog: ``tools/launch.py:72`` (dmlc-tracker: spawns scheduler +
servers + workers over local/ssh/mpi with DMLC_* env).  TPU-native jobs are
multi-controller JAX: N identical worker processes, process 0 doubling as
the coordination point — no scheduler/server processes needed (collectives
replace the parameter server).  Supported launchers:

  local  N worker processes on this machine (how the reference tests
         multi-node without a cluster, tests/nightly/dist_sync_kvstore.py)
  ssh    one worker per host from --host-file

Each worker gets MXNET_TPU_COORDINATOR / MXNET_TPU_NUM_PROCS /
MXNET_TPU_PROC_ID, consumed by ``mxnet_tpu.kvstore.kvstore_server
.init_distributed``.
"""
from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
import threading

_PRINT_LOCK = threading.Lock()


def _relay(stream, sink):
    """Copy a worker's output line-atomically onto our own stream.

    Workers share the launcher's stdout; concurrent writes from separate
    processes interleave mid-line on a pipe (observed: ``RANKRANK 1\\n 0\\n``),
    which corrupts any consumer parsing lines.  One reader thread per worker
    + a print lock keeps every line intact."""
    for line in iter(stream.readline, b""):
        with _PRINT_LOCK:
            sink.buffer.write(line)
            sink.flush()
    stream.close()


def _wait_all(procs, relay_threads):
    # wait for workers FIRST: a worker may leave a background child holding
    # its stdout pipe open, in which case the relay thread never sees EOF —
    # bounded joins after exit drain what's left without hanging the launcher
    rcs = [p.wait() for p in procs]
    for t in relay_threads:
        t.join(timeout=5.0)
    bad = [(i, rc) for i, rc in enumerate(rcs) if rc]
    if bad:
        for i, rc in bad:
            print(f"launch.py: worker {i} exited with rc={rc}",
                  file=sys.stderr)
        sys.exit(bad[0][1])
    sys.exit(0)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference parity; TPU jobs need no "
                         "servers (0 spawned unless explicitly requested)")
    ap.add_argument("--launcher", choices=["local", "ssh"], default="local")
    ap.add_argument("-H", "--host-file", default=None)
    ap.add_argument("--port", type=int, default=29500)
    ap.add_argument("--env", action="append", default=[],
                    help="extra VAR=VAL for every worker")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")

    n = args.num_workers
    coordinator = f"127.0.0.1:{args.port}"
    extra_env = dict(e.split("=", 1) for e in args.env)

    if args.launcher == "local":
        procs, threads = [], []
        for rank in range(n):
            env = dict(os.environ)
            env.update(extra_env)
            env.update({
                "MXNET_TPU_COORDINATOR": coordinator,
                "MXNET_TPU_NUM_PROCS": str(n),
                "MXNET_TPU_PROC_ID": str(rank),
                "DMLC_ROLE": "worker",
                # reference-compat aliases
                "DMLC_NUM_WORKER": str(n),
                "DMLC_WORKER_ID": str(rank),
            })
            p = subprocess.Popen(args.command, env=env,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.PIPE)
            procs.append(p)
            for stream, sink in ((p.stdout, sys.stdout),
                                 (p.stderr, sys.stderr)):
                t = threading.Thread(target=_relay, args=(stream, sink),
                                     daemon=True)
                t.start()
                threads.append(t)
        _wait_all(procs, threads)

    # ssh launcher
    with open(args.host_file) as f:
        hosts = [h.strip() for h in f if h.strip()]
    if len(hosts) < n:
        sys.exit(f"need {n} hosts, have {len(hosts)}")
    coordinator = f"{hosts[0]}:{args.port}"
    procs, threads = [], []
    for rank, host in enumerate(hosts[:n]):
        envs = " ".join(
            f"{k}={shlex.quote(v)}" for k, v in {
                **extra_env,
                "MXNET_TPU_COORDINATOR": coordinator,
                "MXNET_TPU_NUM_PROCS": str(n),
                "MXNET_TPU_PROC_ID": str(rank),
                "DMLC_ROLE": "worker",
            }.items())
        cmd = " ".join(shlex.quote(c) for c in args.command)
        p = subprocess.Popen(
            ["ssh", "-o", "StrictHostKeyChecking=no", host,
             f"cd {shlex.quote(os.getcwd())} && {envs} {cmd}"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        procs.append(p)
        for stream, sink in ((p.stdout, sys.stdout), (p.stderr, sys.stderr)):
            t = threading.Thread(target=_relay, args=(stream, sink),
                                 daemon=True)
            t.start()
            threads.append(t)
    _wait_all(procs, threads)


if __name__ == "__main__":
    main()
