#!/usr/bin/env python
"""Distributed job launcher.

Reference analog: ``tools/launch.py:72`` (dmlc-tracker: spawns scheduler +
servers + workers over local/ssh/mpi with DMLC_* env).  TPU-native jobs are
multi-controller JAX: N identical worker processes, process 0 doubling as
the coordination point — no scheduler/server processes needed (collectives
replace the parameter server).  Supported launchers:

  local  N worker processes on this machine (how the reference tests
         multi-node without a cluster, tests/nightly/dist_sync_kvstore.py)
  ssh    one worker per host from --host-file
  mpi    one worker per MPI rank via ``mpirun``; ranks map their
         OMPI_COMM_WORLD_RANK / PMI_RANK onto the same env contract
         (reference tools/launch.py mpi submission)
  sge    a Sun Grid Engine array job via ``qsub -t 1-N``; rank =
         SGE_TASK_ID - 1 (reference dmlc-tracker sge)
  yarn   one worker per YARN container via the ``yarn`` CLI's
         distributed-shell; requires HADOOP_HOME and a reachable RM
         (reference dmlc-tracker yarn; on TPU fleets prefer GKE — this
         mode exists for parity with Hadoop clusters)

Each worker gets MXNET_TPU_COORDINATOR / MXNET_TPU_NUM_PROCS /
MXNET_TPU_PROC_ID, consumed by ``mxnet_tpu.kvstore.kvstore_server
.init_distributed``.
"""
from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
import threading

_PRINT_LOCK = threading.Lock()


def _relay(stream, sink):
    """Copy a worker's output line-atomically onto our own stream.

    Workers share the launcher's stdout; concurrent writes from separate
    processes interleave mid-line on a pipe (observed: ``RANKRANK 1\\n 0\\n``),
    which corrupts any consumer parsing lines.  One reader thread per worker
    + a print lock keeps every line intact."""
    for line in iter(stream.readline, b""):
        with _PRINT_LOCK:
            sink.buffer.write(line)
            sink.flush()
    stream.close()


def _wait_all(procs, relay_threads):
    # wait for workers FIRST: a worker may leave a background child holding
    # its stdout pipe open, in which case the relay thread never sees EOF —
    # bounded joins after exit drain what's left without hanging the launcher
    rcs = [p.wait() for p in procs]
    for t in relay_threads:
        t.join(timeout=5.0)
    bad = [(i, rc) for i, rc in enumerate(rcs) if rc]
    if bad:
        for i, rc in bad:
            print(f"launch.py: worker {i} exited with rc={rc}",
                  file=sys.stderr)
        sys.exit(bad[0][1])
    sys.exit(0)


def _mpi_shim(coordinator: str, command):
    """Exec'd once per MPI rank (by ``mpirun``): translate the MPI
    launcher's rank/size env onto the MXNET_TPU_* contract, then exec the
    user command.  Open MPI exports OMPI_COMM_WORLD_*; MPICH/Slurm-PMI
    export PMI_*."""
    env = os.environ
    rank = env.get("OMPI_COMM_WORLD_RANK", env.get("PMI_RANK",
                   env.get("MV2_COMM_WORLD_RANK")))
    size = env.get("OMPI_COMM_WORLD_SIZE", env.get("PMI_SIZE",
                   env.get("MV2_COMM_WORLD_SIZE")))
    if rank is None or size is None:
        sys.exit("launch.py --mpi-shim: no MPI rank env found "
                 "(OMPI_COMM_WORLD_RANK / PMI_RANK) — run under mpirun")
    os.environ.update({
        "MXNET_TPU_COORDINATOR": coordinator,
        "MXNET_TPU_NUM_PROCS": size,
        "MXNET_TPU_PROC_ID": rank,
        "DMLC_ROLE": "worker",
        "DMLC_NUM_WORKER": size,
        "DMLC_WORKER_ID": rank,
    })
    os.execvp(command[0], command)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference parity; TPU jobs need no "
                         "servers (0 spawned unless explicitly requested)")
    ap.add_argument("--launcher",
                    choices=["local", "ssh", "mpi", "sge", "yarn"],
                    default="local")
    ap.add_argument("-H", "--host-file", default=None)
    ap.add_argument("--port", type=int, default=29500)
    ap.add_argument("--env", action="append", default=[],
                    help="extra VAR=VAL for every worker")
    ap.add_argument("--mpi-shim", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--coordinator", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--sge-queue", default=None,
                    help="SGE queue to submit to (sge launcher)")
    ap.add_argument("--coordinator-host", default=None,
                    help="host rank 0 binds on, as reachable from the "
                         "cluster (sge/yarn; default: this machine's "
                         "FQDN)")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if args.mpi_shim:
        return _mpi_shim(args.coordinator or "127.0.0.1:29500",
                         args.command)

    n = args.num_workers
    coordinator = f"127.0.0.1:{args.port}"
    extra_env = dict(e.split("=", 1) for e in args.env)

    if args.launcher == "local":
        procs, threads = [], []
        for rank in range(n):
            env = dict(os.environ)
            env.update(extra_env)
            env.update({
                "MXNET_TPU_COORDINATOR": coordinator,
                "MXNET_TPU_NUM_PROCS": str(n),
                "MXNET_TPU_PROC_ID": str(rank),
                "DMLC_ROLE": "worker",
                # reference-compat aliases
                "DMLC_NUM_WORKER": str(n),
                "DMLC_WORKER_ID": str(rank),
            })
            p = subprocess.Popen(args.command, env=env,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.PIPE)
            procs.append(p)
            for stream, sink in ((p.stdout, sys.stdout),
                                 (p.stderr, sys.stderr)):
                t = threading.Thread(target=_relay, args=(stream, sink),
                                     daemon=True)
                t.start()
                threads.append(t)
        _wait_all(procs, threads)

    if args.launcher == "mpi":
        import shutil

        mpirun = shutil.which("mpirun") or shutil.which("mpiexec")
        if not mpirun:
            sys.exit("launch.py: --launcher mpi needs mpirun/mpiexec on "
                     "PATH")
        if args.host_file:
            with open(args.host_file) as f:
                first = next((h.strip() for h in f if h.strip()), None)
            coordinator = f"{first}:{args.port}" if first else coordinator
        cmd = [mpirun, "-np", str(n)]
        if args.host_file:
            cmd += ["--hostfile", args.host_file]
        try:
            ver = subprocess.run([mpirun, "--version"],
                                 capture_output=True, text=True,
                                 timeout=10).stdout
        except Exception:
            ver = ""
        for k, v in extra_env.items():
            if "Open MPI" in ver or "OpenRTE" in ver:
                cmd += ["-x", f"{k}={v}"]        # Open MPI spelling
            else:
                cmd += ["-genv", k, v]           # Hydra (MPICH/Intel MPI)
        cmd += [sys.executable, os.path.abspath(__file__), "-n", str(n),
                "--mpi-shim", "--coordinator", coordinator, "--"]
        cmd += args.command
        p = subprocess.Popen(cmd)
        sys.exit(p.wait())

    if args.launcher in ("sge", "yarn"):
        # workers land on other nodes: 127.0.0.1 can never rendezvous —
        # rank 0 must bind an address the cluster can reach
        import socket

        host = args.coordinator_host or socket.getfqdn()
        coordinator = f"{host}:{args.port}"

    if args.launcher == "sge":
        import shutil
        import tempfile

        if not shutil.which("qsub"):
            sys.exit("launch.py: --launcher sge needs qsub on PATH")
        envs = "\n".join(
            f"export {k}={shlex.quote(v)}" for k, v in {
                **extra_env,
                "MXNET_TPU_COORDINATOR": coordinator,
                "MXNET_TPU_NUM_PROCS": str(n),
                "DMLC_ROLE": "worker",
            }.items())
        cmd = " ".join(shlex.quote(c) for c in args.command)
        script = (f"#!/bin/bash\n#$ -cwd\n#$ -V\n{envs}\n"
                  "export MXNET_TPU_PROC_ID=$((SGE_TASK_ID - 1))\n"
                  "export DMLC_WORKER_ID=$MXNET_TPU_PROC_ID\n"
                  f"exec {cmd}\n")
        with tempfile.NamedTemporaryFile("w", suffix=".sh",
                                         delete=False) as f:
            f.write(script)
            path = f.name
        qsub = ["qsub", "-sync", "y", "-t", f"1-{n}"]
        if args.sge_queue:
            qsub += ["-q", args.sge_queue]
        sys.exit(subprocess.call(qsub + [path]))

    if args.launcher == "yarn":
        import shutil

        if not shutil.which("yarn"):
            sys.exit(
                "launch.py: --launcher yarn needs the Hadoop 'yarn' CLI "
                "(HADOOP_HOME) — on TPU fleets prefer GKE/xpk, or use "
                "--launcher ssh/mpi")
        cmd = " ".join(shlex.quote(c) for c in args.command)
        envs = ",".join(
            f"{k}={v}" for k, v in {
                **extra_env,
                "MXNET_TPU_COORDINATOR": coordinator,
                "MXNET_TPU_NUM_PROCS": str(n),
                "DMLC_ROLE": "worker",
            }.items())
        # distributed-shell: one container per worker; the container id
        # env CONTAINER_ID's last field - 1 is the rank
        # container _000001 is the distributed-shell AM; workers are
        # _000002.. => rank = id - 2.  10# forces base-10 (zero-padded
        # suffixes like 000008 would otherwise parse as bad octal).
        shell = ("export MXNET_TPU_PROC_ID=$((10#${CONTAINER_ID##*_} - 2));"
                 " export DMLC_WORKER_ID=$MXNET_TPU_PROC_ID; " + cmd)
        jar = os.environ.get(
            "YARN_DSHELL_JAR",
            os.path.join(os.environ.get("HADOOP_HOME", ""),
                         "share/hadoop/yarn",
                         "hadoop-yarn-applications-distributedshell.jar"))
        sys.exit(subprocess.call(
            ["yarn", "jar", jar,
             "-jar", jar, "-num_containers", str(n),
             "-shell_env", envs, "-shell_command", shell]))

    # ssh launcher
    with open(args.host_file) as f:
        hosts = [h.strip() for h in f if h.strip()]
    if len(hosts) < n:
        sys.exit(f"need {n} hosts, have {len(hosts)}")
    coordinator = f"{hosts[0]}:{args.port}"
    procs, threads = [], []
    for rank, host in enumerate(hosts[:n]):
        envs = " ".join(
            f"{k}={shlex.quote(v)}" for k, v in {
                **extra_env,
                "MXNET_TPU_COORDINATOR": coordinator,
                "MXNET_TPU_NUM_PROCS": str(n),
                "MXNET_TPU_PROC_ID": str(rank),
                "DMLC_ROLE": "worker",
            }.items())
        cmd = " ".join(shlex.quote(c) for c in args.command)
        p = subprocess.Popen(
            ["ssh", "-o", "StrictHostKeyChecking=no", host,
             f"cd {shlex.quote(os.getcwd())} && {envs} {cmd}"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        procs.append(p)
        for stream, sink in ((p.stdout, sys.stdout), (p.stderr, sys.stderr)):
            t = threading.Thread(target=_relay, args=(stream, sink),
                                 daemon=True)
            t.start()
            threads.append(t)
    _wait_all(procs, threads)


if __name__ == "__main__":
    main()
