#!/usr/bin/env python
"""Communication / memory bandwidth measurement.

Reference analog: ``tools/bandwidth/measure.py`` (kvstore comm bandwidth
per GPU).  TPU-native version measures the three lanes that matter here:

- host -> device staging (device_put), the input-pipeline lane;
- device -> host readback (device_get), the eval/checkpoint lane;
- on-device copy bandwidth (HBM), via a jitted identity-plus;
- all-reduce bandwidth over the mesh (ICI on hardware, shared-memory on
  the virtual CPU mesh) — the kvstore='tpu' gradient lane, using the
  standard 2(n-1)/n ring-bytes accounting.

    python tools/bandwidth.py --mb 64 --iters 10
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/bandwidth.py --mesh dp=8
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fence(x):
    """Host read — the only reliable completion fence over the TPU tunnel
    (block_until_ready exerts no backpressure until the queue drains)."""
    import numpy as onp

    return onp.asarray(x).ravel()[0]


def measure(mb=64, iters=10, mesh_spec=""):
    import jax
    import jax.numpy as jnp
    import numpy as onp

    n = mb * (1 << 20) // 4
    host = onp.random.RandomState(0).rand(n).astype(onp.float32)
    results = {}

    # host -> device
    dev = jax.device_put(host)
    _fence(dev)
    t0 = time.perf_counter()
    for _ in range(iters):
        dev = jax.device_put(host)
    _fence(dev)
    dt = time.perf_counter() - t0
    results["h2d_GBps"] = mb * iters / 1024 / dt

    # device -> host: read a FRESH device buffer each iteration — jax
    # caches the host copy of an unchanged array, which would measure a
    # memcpy (or nothing) instead of the transfer.  The distinct buffers
    # are produced (and completed) BEFORE the timed region so readback is
    # the only thing on the clock — bumping inside the loop would mix a
    # kernel dispatch+execute into the figure.
    bump = jax.jit(lambda x, k: x + k)
    # chunked so the pool of distinct live buffers stays bounded (~2 GiB)
    # regardless of --mb/--iters; per-chunk: produce + fence OUTSIDE the
    # clock, then time only the readbacks and sum across chunks
    chunk = max(1, min(iters, (2 << 10) // max(mb, 1)))
    dt = 0.0
    done = 0
    while done < iters:
        k = min(chunk, iters - done)
        bufs = [bump(dev, float(done + i)) for i in range(k)]
        # drain the dispatch queue with ONE host read of a sentinel (over
        # the TPU tunnel block_until_ready exerts no backpressure until
        # the queue has drained once), then block on each buffer WITHOUT
        # reading it — _fence(b) would populate jax's cached host copy
        # and turn the timed readback into a no-op
        _fence(bump(dev, -1.0))
        for b in bufs:
            b.block_until_ready()
        t0 = time.perf_counter()
        for b in bufs:
            out = onp.asarray(b)
        dt += time.perf_counter() - t0
        del bufs
        done += k
    results["d2h_GBps"] = mb * iters / 1024 / dt

    # on-device (read+write one buffer each way)
    f = jax.jit(lambda x: x + 1.0)
    _fence(f(dev))
    t0 = time.perf_counter()
    y = dev
    for _ in range(iters):
        y = f(y)
    _fence(y)
    dt = time.perf_counter() - t0
    results["hbm_GBps"] = 2 * mb * iters / 1024 / dt

    # all-reduce over the device mesh: a REAL psum via shard_map, so every
    # timed iteration moves bytes across devices (a plain jitted reduce
    # would produce a replicated output and communicate only once)
    if mesh_spec:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        ndev = 1
        for part in mesh_spec.split(","):
            _, v = part.split("=")
            ndev *= int(v)
        devices = jax.devices()[:ndev]
        if len(devices) < ndev:
            raise SystemExit(f"--mesh wants {ndev} devices, "
                             f"have {len(devices)}")
        flat = Mesh(devices, ("all",))
        # kvstore-gradient semantics: EVERY device holds a full mb-sized
        # gradient; the all-reduce moves 2(n-1)/n * mb per device.  Shape
        # (ndev, n) sharded on the leading axis gives each device one
        # full-payload row.
        sharding = NamedSharding(flat, P("all", None))
        # one row per device, one row on the host — device_put of a
        # broadcast view would materialize ndev full copies host-side
        row = host[None, :]
        sharded = jax.make_array_from_callback(
            (ndev, n), sharding, lambda idx: row)
        ar = jax.jit(shard_map(
            lambda x: jax.lax.psum(x, "all"), mesh=flat,
            in_specs=P("all", None), out_specs=P(None, None)))
        _fence(ar(sharded))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = ar(sharded)               # fresh psum each iteration
        _fence(out)
        dt = time.perf_counter() - t0
        ring_bytes = 2 * (ndev - 1) / ndev * mb * iters
        results["allreduce_GBps"] = ring_bytes / 1024 / dt
        results["mesh"] = mesh_spec

    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=64,
                    help="payload size in MiB")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--mesh", default="",
                    help="axis spec for the all-reduce lane, e.g. dp=8")
    args = ap.parse_args()
    import json

    import jax

    res = measure(args.mb, args.iters, args.mesh)
    res["platform"] = jax.default_backend()
    res["payload_mb"] = args.mb
    # 4 decimals: tiny payloads on a loaded host must not round to 0.0
    print(json.dumps({k: (round(v, 4) if isinstance(v, float) else v)
                      for k, v in res.items()}))


if __name__ == "__main__":
    main()
