"""Repo tooling: CLI scripts (run directly) and the ``tools.lint``
static-analysis package (``python -m tools.lint``)."""
