"""Parse a training log into a markdown table (reference
tools/parse_log.py).

Understands the log shapes this framework's examples and estimator emit:
``Epoch[3] ... train-accuracy=0.94 ... time cost=12.3`` as well as the
speedometer's ``Speed: 123.45 samples/sec``.

    python tools/parse_log.py train.log --metric-names accuracy loss
"""
import argparse
import re
import sys


def parse(lines, metric_names):
    epochs = {}
    for line in lines:
        m_epoch = re.search(r"Epoch\s*\[?(\d+)\]?", line)
        if not m_epoch:
            continue
        e = int(m_epoch.group(1))
        row = epochs.setdefault(e, {})
        for name in metric_names:
            m = re.search(rf"(?:train|validation)?-?{name}[=:]\s*([0-9.eE+-]+)",
                          line, re.IGNORECASE)
            if m:
                key = f"val-{name}" if re.search(
                    rf"validation-{name}", line, re.IGNORECASE) else name
                row[key] = float(m.group(1))
        m = re.search(r"[Ss]peed[:=]\s*([0-9.]+)\s*samples/sec", line)
        if m:
            row.setdefault("speed", []).append(float(m.group(1)))
        m = re.search(r"[Tt]ime cost[=:]\s*([0-9.]+)", line)
        if m:
            row["time"] = float(m.group(1))
    return epochs


def to_markdown(epochs):
    cols = sorted({k for row in epochs.values() for k in row})
    out = ["| epoch | " + " | ".join(cols) + " |",
           "| --- | " + " | ".join("---" for _ in cols) + " |"]
    for e in sorted(epochs):
        cells = []
        for c in cols:
            v = epochs[e].get(c, "")
            if isinstance(v, list):
                v = sum(v) / len(v)
            cells.append(f"{v:.6g}" if v != "" else "")
        out.append(f"| {e} | " + " | ".join(cells) + " |")
    return "\n".join(out)


def main():
    p = argparse.ArgumentParser(description="parse a training log")
    p.add_argument("logfile", nargs=1)
    p.add_argument("--format", choices=["markdown", "none"],
                   default="markdown")
    p.add_argument("--metric-names", nargs="+", default=["accuracy"])
    args = p.parse_args()
    with open(args.logfile[0]) as f:
        epochs = parse(f, args.metric_names)
    if not epochs:
        print("no epoch lines found", file=sys.stderr)
        return
    if args.format == "markdown":
        print(to_markdown(epochs))


if __name__ == "__main__":
    main()
