"""Diagnose the environment for bug reports (reference tools/diagnose.py).

Prints platform, Python, dependency versions, framework feature flags,
native-library status, and device availability.  The device probe runs in
a SUBPROCESS with a timeout: a wedged TPU tunnel must never hang the
diagnosis itself (that asymmetry is the most common thing being
diagnosed).

    python tools/diagnose.py [--probe-timeout 60]
"""
import argparse
import os
import platform
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def check_python():
    print("----------Python Info----------")
    print("Version      :", platform.python_version())
    print("Compiler     :", platform.python_compiler())
    print("Build        :", platform.python_build())


def check_os():
    print("----------System Info----------")
    print("Platform     :", platform.platform())
    print("system       :", platform.system())
    print("node         :", platform.node())
    print("release      :", platform.release())
    print("machine      :", platform.machine())


def check_deps():
    print("----------Dependency Versions----------")
    for mod in ("numpy", "jax", "jaxlib", "flax", "optax"):
        try:
            m = __import__(mod)
            print(f"{mod:<13}: {getattr(m, '__version__', 'unknown')}")
        except ImportError:
            print(f"{mod:<13}: not installed")


def check_framework():
    print("----------Framework----------")
    import mxnet_tpu as mx

    print("mxnet_tpu    :", mx.__version__)
    print("location     :", os.path.dirname(mx.__file__))
    try:
        paths = mx.libinfo.find_lib_path()
        print("native libs  :", ", ".join(os.path.basename(p)
                                          for p in paths))
    except RuntimeError as e:
        print("native libs  : none (", e, ")")
    from mxnet_tpu import runtime

    feats = [f.name for f in runtime.feature_list() if f.enabled]
    print("features     :", ", ".join(feats) if feats else "(none)")
    envs = {k: v for k, v in os.environ.items() if k.startswith("MXNET_")}
    print("MXNET_* env  :", envs or "(none)")


def check_devices(timeout: float):
    print("----------Devices----------")
    code = ("import jax;"
            "print('backend:', jax.default_backend());"
            "print('devices:', jax.devices())")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=timeout)
        out = (r.stdout + r.stderr).strip()
        print(out if out else f"probe exited rc={r.returncode}")
    except subprocess.TimeoutExpired:
        print(f"device probe TIMED OUT after {timeout:.0f}s — the "
              f"accelerator tunnel looks wedged. CPU-only work still "
              f"runs with JAX_PLATFORMS=cpu and the axon autoload "
              f"disabled (unset PALLAS_AXON_POOL_IPS).")


def main():
    p = argparse.ArgumentParser(description="diagnose the environment")
    p.add_argument("--probe-timeout", type=float, default=60.0)
    args = p.parse_args()
    check_os()
    check_python()
    check_deps()
    check_framework()
    check_devices(args.probe_timeout)
    print("diagnose: done")


if __name__ == "__main__":
    main()
