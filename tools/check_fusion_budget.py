#!/usr/bin/env python
"""CI gate: the conv/BN/ReLU fusion story must hold on the CPU backend.

The MFU campaign's chip claims (docs/PERF.md "MFU campaign round 2")
need a proxy the suite can verify without a TPU.  Four lanes, each a
CPU-checkable invariant of the round-9 work; FAIL (exit 1) on any
regression:

- ``fusion``: a hybridized train-mode conv+BN+ReLU chain compiles into
  at most ``FUSION_BUDGET`` XLA fusions (guards the unfused baseline
  against de-fusion regressions), and under ``MXNET_FUSED_EPILOGUE=2``
  the model-zoo BottleneckV1 (a) really routes its three 1x1 sites
  through ``_fused_conv1x1_bn_act``, (b) carries the Pallas kernel in
  its traced program (the ``pallas_call`` jaxpr marker — the
  CPU-verifiable analog of the TPU custom-call), (c) compiles to FEWER
  fusions than the unfused baseline (the whole epilogue chain collapsed
  into the kernel), and (d) matches the unfused output numerically.

- ``pad``: the MXU-alignment padding pass (``MXNET_PAD_CHANNELS=2``) on
  a misaligned-channel model keeps the compiled train step at exactly
  1 dispatch and 0 retraces per steady-state step (the pad/slice live
  INSIDE the program, keyed by unpadded shapes) and the loss trajectory
  is BIT-EXACT vs the pass disabled — padded taps contribute 0.0 and
  sliced-off channels are independent dots.

- ``int8``: the retired Pallas int8 conv route refuses loudly —
  ``MXNET_INT8_PALLAS=1`` raises pointing at the 0.345x measurement
  (BENCH_builder_r05) — and the default path still counts every conv a
  Pallas route would have claimed (``pallas_skipped_count``).

Invoked by the test suite (tests/test_fused_epilogue.py) exactly like
tools/check_dispatch_budget.py, and runnable standalone:
``JAX_PLATFORMS=cpu python tools/check_fusion_budget.py``.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the unfused conv+BN+ReLU budget: the chain measured 6 fusions on this
# jax/XLA CPU build; 8 leaves slack for compiler drift without letting a
# de-fusion regression (separate stats passes, unfused normalize) hide
FUSION_BUDGET = 8
# BottleneckV1 1x1 sites the fused path must claim: conv1, downsample,
# conv3 (the 3x3 stays on XLA by design)
FUSED_SITES = 3
PAD_STEPS = 4


def _set(name: str, value):
    from mxnet_tpu import config

    if value is None:
        os.environ.pop(name, None)
    else:
        os.environ[name] = str(value)
    config.refresh(name)


def _lower_cached(net, x):
    """Lower the hybridized block's cached program and return
    (jaxpr_text, optimized_hlo_text, cost_analysis_dict)."""
    import jax

    from mxnet_tpu import random as _random

    rec = list(net._cached.values())[-1]
    jitted, names, params, _ctx_idx, _out_struct, _mut = rec
    parr = [params[n]._data[0]._data for n in names]
    key = _random.next_key()
    jaxpr = str(jax.make_jaxpr(lambda p, i, k: jitted(p, i, k))(
        parr, [x._data], key))
    lo = jitted.lower(parr, [x._data], key)
    comp = lo.compile()
    ca = comp.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0] if ca else {}
    return jaxpr, comp.as_text(), (ca or {})


def _count_fusions(hlo_text: str) -> int:
    return hlo_text.count(" fusion(")


def _measure_chain() -> dict:
    """The simple conv+BN+ReLU chain, unfused default: fusion budget."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import nn

    _set("MXNET_FUSED_EPILOGUE", None)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(32, kernel_size=1, use_bias=True, layout="NHWC"))
    net.add(nn.BatchNorm(axis=3))
    net.add(nn.Activation("relu"))
    net.initialize(mx.init.Xavier())
    x = mx.nd.array(onp.random.RandomState(0)
                    .randn(2, 8, 8, 16).astype(onp.float32))
    net(x)
    net.hybridize()
    with autograd.record():
        net(x)
    _sh, hlo, ca = _lower_cached(net, x)
    return {"mode": "chain", "fusions": _count_fusions(hlo),
            "bytes": ca.get("bytes accessed"), "flops": ca.get("flops")}


def _build_bottleneck(x, seed=0):
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.vision.resnet import BottleneckV1

    b = BottleneckV1(64, stride=1, downsample=True, in_channels=32,
                     layout="NHWC")
    b.initialize(mx.init.Xavier())
    b(x)
    rng = onp.random.RandomState(seed)
    for _name, p in sorted(b.collect_params().items()):
        if "running" not in _name:
            p._data[0]._set_data(
                mx.nd.array(rng.randn(*p.shape).astype("float32")
                            * 0.1)._data)
    return b


def _measure_fused() -> dict:
    """BottleneckV1 fused-epilogue vs unfused: sites claimed, pallas
    marker, fusion-count drop, bytes-accessed columns, output parity."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.ops.registry import get_op

    x = mx.nd.array(onp.random.RandomState(3)
                    .randn(2, 8, 8, 32).astype(onp.float32))
    rows = {}
    schema = get_op("_fused_conv1x1_bn_act")
    calls = {"n": 0}
    orig = schema.fn

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    schema.fn = counting
    try:
        for mode in (None, 2):
            _set("MXNET_FUSED_EPILOGUE", mode)
            net = _build_bottleneck(x)
            net.hybridize()
            calls["n"] = 0
            with autograd.record():
                out = net(x)
            jaxpr, hlo, ca = _lower_cached(net, x)
            rows["fused" if mode else "unfused"] = {
                "sites": calls["n"],
                "pallas_marker": ("pallas_call" in jaxpr
                                  or "tpu_custom_call" in hlo),
                "fusions": _count_fusions(hlo),
                "bytes": ca.get("bytes accessed"),
                "flops": ca.get("flops"),
                "out": out.asnumpy(),
            }
    finally:
        schema.fn = orig
        _set("MXNET_FUSED_EPILOGUE", None)
    f, u = rows["fused"], rows["unfused"]
    return {
        "mode": "fused-epilogue",
        "fused_sites": f["sites"],
        "unfused_sites": u["sites"],
        "pallas_marker": f["pallas_marker"],
        "fused_fusions": f["fusions"],
        "unfused_fusions": u["fusions"],
        "fused_bytes": f["bytes"],
        "unfused_bytes": u["bytes"],
        "max_out_diff": float(onp.abs(f["out"] - u["out"]).max()),
        "out_close": bool(onp.allclose(f["out"], u["out"],
                                       rtol=2e-4, atol=2e-4)),
    }


def _pad_run(mode) -> dict:
    """One fresh misaligned-channel model trained PAD_STEPS steps through
    the compiled TrainStep; returns per-step losses + counters."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import cached_step, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.ops import nn as ops_nn

    _set("MXNET_PAD_CHANNELS", mode)

    class Net(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            # cin=3 and cout=10 both miss the 8-lane quantum
            self.conv = nn.Conv2D(10, kernel_size=3, padding=1,
                                  use_bias=True, layout="NHWC",
                                  in_channels=3)
            self.bn = nn.BatchNorm(axis=3)
            self.pool = nn.GlobalAvgPool2D(layout="NHWC")
            self.out = nn.Dense(4, in_units=10)

        def forward(self, x):
            h = self.bn(self.conv(x)).relu()
            return self.out(self.pool(h).reshape((x.shape[0], -1)))

    net = Net()
    net.initialize(mx.init.Xavier())
    rng = onp.random.RandomState(7)
    data = mx.nd.array(rng.randn(4, 8, 8, 3).astype(onp.float32))
    label = mx.nd.array(rng.randn(4, 4).astype(onp.float32))
    net(data)                        # complete deferred init eagerly
    for _name, p in sorted(net.collect_params().items()):
        if "running" not in _name:
            p._data[0]._set_data(
                mx.nd.array(rng.randn(*p.shape).astype("float32")
                            * 0.1)._data)
        else:                        # the probe forward moved them
            p._data[0]._set_data(
                mx.nd.zeros(p.shape)._data if "mean" in _name
                else mx.nd.ones(p.shape)._data)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    loss_fn = lambda n, x, y: ((n(x) - y) ** 2).mean()
    step = trainer.compile_step(net, loss_fn)
    p0 = ops_nn.pad_channels_count()
    losses = [float(step(data, label, batch_size=4).asnumpy())]  # warm
    t0, d0 = cached_step.trace_count(), cached_step.dispatch_count()
    for _ in range(PAD_STEPS):
        losses.append(float(step(data, label, batch_size=4).asnumpy()))
    out = {
        "losses": losses,
        "compiled": step.last_step_compiled,
        "retraces_after_warm": cached_step.trace_count() - t0,
        "dispatches_per_step":
            (cached_step.dispatch_count() - d0) / PAD_STEPS,
        "pads": ops_nn.pad_channels_count() - p0,
    }
    _set("MXNET_PAD_CHANNELS", None)
    return out


def _measure_pad() -> dict:
    on = _pad_run(2)
    off = _pad_run(0)
    return {
        "mode": "pad-channels",
        "compiled": on["compiled"] and off["compiled"],
        "retraces_after_warm": on["retraces_after_warm"],
        "dispatches_per_step": on["dispatches_per_step"],
        "padded_convs": on["pads"],
        "unpadded_pass_pads": off["pads"],
        "bit_exact": on["losses"] == off["losses"],
        "losses_on": on["losses"],
        "losses_off": off["losses"],
    }


def _measure_int8() -> dict:
    """The retired knob refuses loudly; the default path counts skips."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.contrib import quantization as q
    from mxnet_tpu.ndarray.ndarray import invoke

    rng = onp.random.RandomState(5)
    qd = mx.nd.array(rng.randint(-127, 128, (2, 8, 8, 32)), dtype="int8")
    qw = mx.nd.array(rng.randint(-127, 128, (64, 1, 1, 32)), dtype="int8")
    attrs = dict(kernel=(1, 1), stride=(1, 1), num_filter=64,
                 layout="NHWC", no_bias=True, data_scale=0.02,
                 w_scale=0.015)
    _set("MXNET_INT8_PALLAS", None)
    s0 = q.pallas_skipped_count()
    invoke("quantized_conv", [qd, qw], attrs)
    skips = q.pallas_skipped_count() - s0
    refused, points_at_measurement = False, False
    _set("MXNET_INT8_PALLAS", 1)
    try:
        invoke("quantized_conv", [qd, qw], attrs)
    except MXNetError as e:
        refused = True
        points_at_measurement = "0.345x" in str(e) \
            and "BENCH_builder_r05" in str(e)
    finally:
        _set("MXNET_INT8_PALLAS", None)
    return {"mode": "int8", "skips_counted": skips, "knob_refused": refused,
            "refusal_names_measurement": points_at_measurement}


def main() -> int:
    chain = _measure_chain()
    fused = _measure_fused()
    pad = _measure_pad()
    int8 = _measure_int8()
    print(f"{'chain':<16} {chain['fusions']} fusions "
          f"(budget {FUSION_BUDGET}), {chain['bytes']:.0f} bytes accessed")
    print(f"{'fused-epilogue':<16} {fused['fused_sites']}/{FUSED_SITES} "
          f"sites, pallas_marker={fused['pallas_marker']}, fusions "
          f"{fused['unfused_fusions']} -> {fused['fused_fusions']}, "
          f"bytes {fused['unfused_bytes']:.0f} -> {fused['fused_bytes']:.0f}"
          f" (CPU-interpret figure), max |d out| {fused['max_out_diff']:.2e}")
    print(f"{'pad-channels':<16} {pad['padded_convs']} padded convs, "
          f"{pad['dispatches_per_step']:.1f} dispatch/step, "
          f"{pad['retraces_after_warm']} retraces, "
          f"bit_exact={pad['bit_exact']}")
    print(f"{'int8':<16} knob_refused={int8['knob_refused']} "
          f"(names measurement: {int8['refusal_names_measurement']}), "
          f"{int8['skips_counted']} skip(s) counted")
    failures = []
    if chain["fusions"] > FUSION_BUDGET:
        failures.append(
            f"conv+BN+ReLU compiles to {chain['fusions']} fusions, "
            f"budget {FUSION_BUDGET}")
    if fused["fused_sites"] != FUSED_SITES:
        failures.append(
            f"fused epilogue claimed {fused['fused_sites']} bottleneck "
            f"1x1 sites, expected {FUSED_SITES}")
    if fused["unfused_sites"] != 0:
        failures.append("fused op ran with the knob off")
    if not fused["pallas_marker"]:
        failures.append(
            "fused trace carries no pallas custom-call marker")
    if fused["fused_fusions"] >= fused["unfused_fusions"]:
        failures.append(
            f"fused path has {fused['fused_fusions']} fusions, not fewer "
            f"than the unfused baseline's {fused['unfused_fusions']} — "
            "the epilogue chain did not collapse into the kernel")
    if not fused["out_close"]:
        failures.append(
            f"fused bottleneck output diverged "
            f"(max diff {fused['max_out_diff']:.2e})")
    if not pad["compiled"]:
        failures.append("pad lane fell back to the eager tape")
    if pad["padded_convs"] < 1:
        failures.append("padding pass never fired on a misaligned conv")
    if pad["unpadded_pass_pads"] != 0:
        failures.append("padding pass fired with the knob off")
    if pad["retraces_after_warm"] > 0:
        failures.append(
            f"padding pass added {pad['retraces_after_warm']} retraces")
    if pad["dispatches_per_step"] > 1:
        failures.append(
            f"padding pass added dispatches "
            f"({pad['dispatches_per_step']:.1f}/step, budget 1)")
    if not pad["bit_exact"]:
        failures.append(
            f"padded train step is not bit-exact: {pad['losses_on']} vs "
            f"{pad['losses_off']}")
    if not int8["knob_refused"]:
        failures.append("MXNET_INT8_PALLAS=1 did not refuse")
    if not int8["refusal_names_measurement"]:
        failures.append(
            "int8 refusal does not point at the 0.345x measurement")
    if int8["skips_counted"] < 1:
        failures.append("eligible int8 conv did not count a Pallas skip")
    if failures:
        print("check_fusion_budget: FAILED —", "; ".join(failures),
              file=sys.stderr)
        return 1
    print(f"check_fusion_budget: fusion budget holds "
          f"({chain['fusions']} <= {FUSION_BUDGET} fusions unfused; "
          f"fused epilogue {fused['unfused_fusions']} -> "
          f"{fused['fused_fusions']} fusions over {FUSED_SITES} sites); "
          f"padding pass bit-exact at {pad['dispatches_per_step']:.0f} "
          f"dispatch/step, 0 retraces; int8 knob refuses with the "
          f"measurement")
    return 0


if __name__ == "__main__":
    sys.exit(main())
