#!/usr/bin/env python
"""Fold a directory of per-process telemetry flight-recorder shards
into ONE combined snapshot and ONE chrome trace (ISSUE 15).

A drill forks children, bench workers fork subprocesses, and a
multi-controller job runs one process per host — each writes an atomic
``telemetry-r<rank>-p<pid>.jsonl`` shard under ``MXNET_TELEMETRY_DIR``
(flushed by ``engine.waitall()`` and the preemption drain).  This tool
is the thin CLI over ``mxnet_tpu.telemetry.merge`` /
``merge_chrome_trace``:

- cumulative/time counters SUM across processes;
- gauges stay per-process (summing queue depth across ranks is a lie);
- the chrome trace gets one lane per process, with requests that
  crossed processes linked into one flow by ``trace_id``.

``python -m mxnet_tpu.telemetry merge <dir>`` is the same fold with the
report-table front end; this entry point writes artifacts for CI.

Usage::

    python tools/telemetry_merge.py <dir> [--out merged.json]
                                          [--chrome trace.json] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dir", help="MXNET_TELEMETRY_DIR shard directory")
    ap.add_argument("--out", default=None,
                    help="write the merged snapshot JSON here")
    ap.add_argument("--chrome", default=None,
                    help="write the merged per-process chrome trace here")
    ap.add_argument("--json", action="store_true", dest="emit_json",
                    help="print the full merge to stdout as JSON")
    a = ap.parse_args(argv)

    from mxnet_tpu import telemetry

    merged = telemetry.merge(a.dir)
    if not merged["shards"]:
        print(f"telemetry_merge: no telemetry-*.jsonl shards under "
              f"{a.dir}", file=sys.stderr)
        return 1
    if a.out:
        with open(a.out, "w") as f:
            json.dump(merged, f, default=str)
    if a.chrome:
        with open(a.chrome, "w") as f:
            json.dump(telemetry.merge_chrome_trace(a.dir, merged), f)
    if a.emit_json:
        print(json.dumps(merged, default=str))
    else:
        print(f"telemetry_merge: {len(merged['shards'])} shard(s), "
              f"{len(merged['counters'])} summed counters, "
              f"{len(merged['events'])} events, "
              f"{len(merged['spans'])} spans"
              + (f", {merged['skipped_lines']} torn line(s) skipped"
                 if merged["skipped_lines"] else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
