#!/usr/bin/env python
"""CI gate: the compiled train step AND the serving path must stay
inside their dispatch budgets.

Runs a tiny MLP under both step modes and FAILS (exit 1) if the compiled
mode exceeds the documented budget — guarding against silent de-fusion
regressions (an eager op sneaking back into the hot loop, a per-step
re-trace, a group program splitting off the whole-step program):

- compiled mode: exactly ``1`` compiled launch per step
  (``cached_step.dispatch_count``), ``0`` eager op dispatches
  (``ndarray.invoke_count``), ``0`` separate fused group-program launches
  (``fused.dispatch_count`` — the update must ride INSIDE the step
  program), and ``0`` re-traces across constant-shape steps;
- eager mode (comparison lane, printed, not gated): the tape path's
  dispatches/step.

The INFERENCE gate (PR 4, docs/PERF.md "Serving") drives a
``serving.ServingEngine`` over a randomized variable-length request
stream after warming every bucket: exactly ``1`` compiled launch per
dispatched batch, ``0`` re-traces, and the compiled-program count
bounded by the bucket grid.

The DECODE gate (PR 8, docs/PERF.md "Continuous batching + paged
KV-cache") drives a ``serving_decode.GenerativeEngine`` through a
concurrent join/retire storm: live programs == prefill buckets + 1
decode, ``0`` re-traces after warm-up, exactly ``1`` dispatch per
decode iteration (plus one per prefill, nothing else), and ``0``
leaked KV pages after ``engine.waitall()``.

Invoked by the test suite (tests/test_cached_step.py /
tests/test_serving.py) exactly like tools/check_fault_sites.py, and
runnable standalone:
``JAX_PLATFORMS=cpu python tools/check_dispatch_budget.py``.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# standalone runs need the virtual multi-device CPU world BEFORE jax
# initializes (the suite's conftest already provides it in-process)
if "jax" not in sys.modules and "--xla_force_host_platform_device_count" \
        not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

# the budget the docs promise (docs/PERF.md "Compiled whole-train-step" +
# "Pipelined train loop"): a steady-state non-AMP compiled step performs
# ZERO blocking host syncs; with AMP at most ONE read per step, and it
# must be the DEFERRED read (step N-1's flag, never a stall on step N)
BUDGET = {"compiled_launches_per_step": 1, "eager_invokes_per_step": 0,
          "group_launches_per_step": 0, "retraces_after_warm": 0,
          "host_syncs_per_step": 0}
AMP_BUDGET = {"host_syncs_per_step": 1, "deferred_reads_per_step": 1}
# the serving budget (docs/PERF.md "Serving: shape buckets + dynamic
# batching"): steady state over a variable-length stream
INFER_BUDGET = {"launches_per_batch": 1, "retraces_after_warm": 0,
                "programs_over_buckets": 0}
# the DECODE budget (docs/PERF.md "Continuous batching + paged
# KV-cache"): across a join/retire storm the generative engine holds
# exactly prefill-buckets + 1 decode program, re-traces nothing after
# warm-up, performs exactly ONE dispatch per decode iteration (and one
# per prefill), and leaks zero KV pages once drained
DECODE_BUDGET = {"retraces_after_warm": 0, "programs_over_grid": 0,
                 "extra_dispatches": 0, "leaked_pages": 0}
# the PROGRAM-STORE budget (docs/PERF.md "ProgramStore"): steady state
# keeps the live-program count at the declared grid (train: 1 signature
# -> 1 program; serving: <= buckets, covered by programs_over_buckets),
# performs ZERO evictions, and — with MXNET_PROGRAM_CACHE_DIR set — a
# WARM SECOND PROCESS replaying the same train+serving workload
# performs ZERO fresh XLA compiles (all disk/memory hits, bit-exact
# outputs)
STORE_BUDGET = {"evictions_after_warm": 0, "live_train_programs_over": 0,
                "second_process_compiles": 0}
# the SENTINEL budget (docs/ROBUSTNESS.md "Training-integrity
# sentinel"): with a Sentinel attached at cadence E the step STAYS one
# compiled launch with zero retraces — the digest rides an in-program
# lax.cond selected by a traced flag — and the only added host syncs
# are the deferred digest reads (exactly one per cadence window, never
# one per step)
SENTINEL_BUDGET = {"compiled_launches_per_step": 1,
                   "eager_invokes_per_step": 0,
                   "retraces_after_warm": 0,
                   "replica_divergence": 0}
# the ROUTER budget (docs/ROBUSTNESS.md "Partial serving failure"):
# zero-overhead-off — a ReplicaRouter wrapping ONE healthy replica with
# hedging off and the breaker closed adds NOTHING to the engine's
# per-request costs: dispatch count, retrace count, and host syncs for
# an identical request stream must equal the bare engine's, and the
# token streams must be identical
ROUTER_BUDGET = {"extra_dispatches": 0, "extra_retraces": 0,
                 "extra_host_syncs": 0}
# the SPEC budget (ISSUE 19, docs/PERF.md "Speculative decoding +
# sampled decode"): with MXNET_SPEC_DECODE=1 and a high-agreement
# draft, a mixed greedy/sampled join/retire storm holds the BOUNDED
# program set (target grid + draft prefill buckets + 1 draft round + 1
# verify per k — all warmup-compiled), re-traces NOTHING, pays
# strictly LESS than one target-model dispatch per committed token
# (the k-for-1 win), and leaks zero pages across both geometries;
# with MXNET_SPEC_DECODE=0 a draft-attached engine's greedy stream is
# byte-identical in dispatch budget (and tokens) to a draft-free one
SPEC_BUDGET = {"retraces_after_warm": 0, "programs_over_grid": 0,
               "leaked_pages": 0, "greedy_off_extra_dispatches": 0,
               "greedy_off_extra_retraces": 0}
# the MESH budget (docs/PERF.md "Pod-scale SPMD train step"): under
# kvstore='tpu' the data-parallel step stays ONE compiled launch — the
# SPMD partitioner fans out over the mesh, never the host (no per-chip
# dispatch fan-out) — with ZERO steady-state host-side cross-device
# copies (params/state placed once; prefetched/sharded batches pass
# through; spmd.reshard_count stays flat) and every batch truly sharded
# (spmd.replicated_batch_count flat: an indivisible batch would silently
# run replicated = un-scaled)
MESH_BUDGET = {"compiled_launches_per_step": 1, "eager_invokes_per_step": 0,
               "group_launches_per_step": 0, "retraces_after_warm": 0,
               "host_syncs_per_step": 0, "reshards_after_warm": 0,
               "replicated_batches": 0}
# the FSDP budget (docs/PERF.md "Sharded training"): with
# MXNET_SPMD_MESH='dp=2,fsdp=2' params AND optimizer state shard over
# the fsdp axis, yet the step STAYS one compiled launch with zero
# retraces and zero steady-state reshards — the partitioner schedules
# the all-gather/reduce-scatter INSIDE the one donated program, never
# the host.  Accumulation sub-lane: compile_step(accum_steps=N) pays
# exactly N+1 dispatches per window (N microbatch grad programs + ONE
# fused update), zero retraces once both programs are warm —
# accum_extra_dispatches is measured-per-window minus (N+1)
FSDP_BUDGET = {"compiled_launches_per_step": 1, "eager_invokes_per_step": 0,
               "group_launches_per_step": 0, "retraces_after_warm": 0,
               "host_syncs_per_step": 0, "reshards_after_warm": 0,
               "replicated_batches": 0, "accum_extra_dispatches": 0,
               "accum_retraces_after_warm": 0}
# the PP budget (ISSUE 20, docs/PERF.md "Every-axis mesh"): with
# MXNET_SPMD_MESH='pp=2,dp=2,fsdp=2' a PipelineBlock-backed step stays
# ONE compiled launch — the GPipe microbatch schedule is scan-INTERNAL,
# never a per-stage or per-microbatch host dispatch — with 0 retraces,
# 0 steady-state reshards (the packed stage buffer is placed P('pp')
# once), batches sharded over dp only, and PR-18 accumulation still at
# exactly N+1 dispatches per window on the pp mesh
PP_BUDGET = {"compiled_launches_per_step": 1, "eager_invokes_per_step": 0,
             "group_launches_per_step": 0, "retraces_after_warm": 0,
             "reshards_after_warm": 0, "replicated_batches": 0,
             "accum_extra_dispatches": 0, "accum_retraces_after_warm": 0}
# the MOE budget (ISSUE 20, docs/PERF.md "Every-axis mesh"): with
# MXNET_SPMD_MESH='ep=4,dp=2' an MoEBlock step — dispatch/combine,
# expert einsums, the load-balance aux head folded into the loss, and
# the fused update over ep-sharded expert weights — stays ONE compiled
# launch with 0 retraces and 0 steady-state reshards
MOE_BUDGET = {"compiled_launches_per_step": 1, "eager_invokes_per_step": 0,
              "group_launches_per_step": 0, "retraces_after_warm": 0,
              "reshards_after_warm": 0, "replicated_batches": 0}
STEPS = 5
INFER_REQUESTS = 24
INFER_MAXLEN = 16


def _build(seed: int = 0, rows: int = 6, kvstore: str = "device"):
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    class Net(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.d1 = nn.Dense(16, in_units=8, activation="relu")
            self.d2 = nn.Dense(4, in_units=16)

        def forward(self, x):
            return self.d2(self.d1(x))

    net = Net()
    net.initialize(mx.init.Xavier())
    rng = onp.random.RandomState(seed)
    for _name, p in sorted(net.collect_params().items()):
        p.data()._set_data(mx.nd.array(rng.randn(*p.shape) * 0.1)._data)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9},
                            kvstore=kvstore)
    data = mx.nd.array(rng.randn(rows, 8))
    label = mx.nd.array(rng.randn(rows, 4))
    loss_fn = lambda n, x, y: ((n(x) - y) ** 2).mean()
    return net, trainer, loss_fn, data, label


def _measure(compiled: bool, with_amp: bool = False) -> dict:
    import mxnet_tpu as mx
    from mxnet_tpu import amp, cached_step
    from mxnet_tpu.ndarray import ndarray as _ndmod
    from mxnet_tpu.optimizer import fused

    net, trainer, loss_fn, data, label = _build()
    if with_amp:
        trainer._amp_loss_scaler = amp.LossScaler(init_scale=8.0)
    if compiled:
        step = trainer.compile_step(net, loss_fn)

        def one_step():
            return step(data, label, batch_size=6)
    else:
        def one_step():
            with mx.autograd.record():
                loss = loss_fn(net, data, label)
            loss.backward()
            trainer.step(6)
            return loss

    loss = one_step()                    # warm: trace + state create
    float(loss.asnumpy().ravel()[0])     # drain
    inv0, d0, f0, t0 = (_ndmod.invoke_count(), cached_step.dispatch_count(),
                        fused.dispatch_count(), cached_step.trace_count())
    h0, dr0 = _ndmod.host_sync_count(), cached_step.deferred_read_count()
    for _ in range(STEPS):
        loss = one_step()
    h1, dr1 = _ndmod.host_sync_count(), cached_step.deferred_read_count()
    float(loss.asnumpy().ravel()[0])     # fence (after the sync window)
    out = {
        "mode": ("compiled" if compiled else "eager")
                + ("+amp" if with_amp else ""),
        "used_compiled": compiled and step.last_step_compiled,
        "eager_invokes_per_step":
            (_ndmod.invoke_count() - inv0) / STEPS,
        "compiled_launches_per_step":
            (cached_step.dispatch_count() - d0) / STEPS,
        "group_launches_per_step": (fused.dispatch_count() - f0) / STEPS,
        "retraces_after_warm": cached_step.trace_count() - t0,
        "host_syncs_per_step": (h1 - h0) / STEPS,
        "deferred_reads_per_step": (dr1 - dr0) / STEPS,
    }
    out["dispatches_per_step"] = (out["eager_invokes_per_step"]
                                  + out["compiled_launches_per_step"]
                                  + out["group_launches_per_step"])
    # program-store lane input: one constant-shape signature must hold
    # exactly ONE live program in this step's keyspace
    out["live_programs"] = len(step._programs) if compiled else 0
    return out


def _measure_sentinel() -> dict:
    """Training-integrity sentinel lane: a Sentinel at cadence 2 rides
    the compiled step for 6 steps — still 1 launch/step, 0 retraces,
    digest reads == cadence windows (each a deferred read, counted as a
    host sync), fingerprints bit-stable across two identical windows,
    and the in-program fold equals a host recomputation of the same
    state."""
    import mxnet_tpu as mx  # noqa: F401
    from mxnet_tpu import cached_step, sentinel, telemetry
    from mxnet_tpu.ndarray import ndarray as _ndmod

    net, trainer, loss_fn, data, label = _build(seed=7)
    step = trainer.compile_step(net, loss_fn)
    snt = sentinel.Sentinel(step=step, every=2)
    loss = step(data, label, batch_size=6)          # warm (call 1)
    float(loss.asnumpy().ravel()[0])
    d0, t0 = cached_step.dispatch_count(), cached_step.trace_count()
    i0, h0 = _ndmod.invoke_count(), _ndmod.host_sync_count()
    base = telemetry.snapshot()
    STEPS_S = 5                       # calls 2..6: last call is a
    for _ in range(STEPS_S):          # sentinel step, so the flushed
        loss = step(data, label, batch_size=6)    # fold matches the
    assert step.last_step_compiled, step.last_fallback_reason  # live state
    snt.flush()
    snap = telemetry.snapshot()
    reads = snap["sentinel.digests"] - base["sentinel.digests"]
    # host recomputation of the fold over exactly what the program
    # digests: post-update trainable params + optimizer state
    upd = trainer._updaters[0]
    leaves = [p.data()._data for p in trainer._params
              if p.grad_req != "null"]
    import jax

    states = [upd.states[trainer._param2idx[id(p)]]
              for p in trainer._params if p.grad_req != "null"]
    state_leaves = [getattr(l, "_data", l)
                    for l in jax.tree_util.tree_leaves(states)]
    host_fold = sentinel.tree_digest(leaves + state_leaves)
    out = {
        "mode": "sentinel",
        "compiled_launches_per_step":
            (cached_step.dispatch_count() - d0) / STEPS_S,
        "eager_invokes_per_step":
            (_ndmod.invoke_count() - i0) / STEPS_S,
        "retraces_after_warm": cached_step.trace_count() - t0,
        "digest_reads": reads,
        "host_syncs": _ndmod.host_sync_count() - h0,
        "replica_divergence": snap["sentinel.replica_divergence"]
        - base["sentinel.replica_divergence"],
        "fold": snt.last_fold,
        "host_fold": host_fold,
        "fold_matches_host": snt.last_fold == host_fold,
    }
    return out


def _measure_mesh() -> dict:
    """kvstore='tpu' under the 8-device mesh: the data-parallel step must
    stay ONE compiled launch (the partitioner fans out, not the host),
    re-trace 0, and perform zero steady-state host-side cross-device
    copies or silently-replicated batches."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import cached_step
    from mxnet_tpu.ndarray import ndarray as _ndmod
    from mxnet_tpu.optimizer import fused
    from mxnet_tpu.parallel import spmd

    n_dev = len(jax.devices())
    if n_dev < 2:
        return {"mode": "mesh", "skipped": f"only {n_dev} device(s)"}
    # 2 rows per device: divisible batch, truly sharded
    net, trainer, loss_fn, data, label = _build(
        seed=2, rows=2 * n_dev, kvstore="tpu")
    step = trainer.compile_step(net, loss_fn)

    loss = step(data, label, batch_size=2 * n_dev)      # warm
    float(loss.asnumpy().ravel()[0])
    inv0, d0, f0, t0 = (_ndmod.invoke_count(), cached_step.dispatch_count(),
                        fused.dispatch_count(), cached_step.trace_count())
    h0 = _ndmod.host_sync_count()
    r0, b0 = spmd.reshard_count(), spmd.replicated_batch_count()
    for _ in range(STEPS):
        loss = step(data, label, batch_size=2 * n_dev)
    h1 = _ndmod.host_sync_count()
    r1, b1 = spmd.reshard_count(), spmd.replicated_batch_count()
    float(loss.asnumpy().ravel()[0])
    weight = net.collect_params()["d1.weight"].data()._data
    out = {
        "mode": "mesh",
        "skipped": None,
        "used_compiled": step.last_step_compiled,
        "mesh_active": step.mesh is not None,
        "mesh_devices": len(weight.sharding.device_set),
        "n_devices": n_dev,
        "eager_invokes_per_step": (_ndmod.invoke_count() - inv0) / STEPS,
        "compiled_launches_per_step":
            (cached_step.dispatch_count() - d0) / STEPS,
        "group_launches_per_step": (fused.dispatch_count() - f0) / STEPS,
        "retraces_after_warm": cached_step.trace_count() - t0,
        "host_syncs_per_step": (h1 - h0) / STEPS,
        "reshards_after_warm": r1 - r0,
        "replicated_batches": b1 - b0,
    }
    return out


def _measure_fsdp() -> dict:
    """dp×fsdp lane: params + optimizer state sharded over the fsdp
    axis, batch over dp only — still ONE launch/step, zero retraces,
    zero steady-state reshards, and param bytes per device at 1/fsdp of
    the replicated footprint.  Then the accumulation sub-lane on the
    same mesh: accum_steps=2 must pay exactly 3 dispatches per window
    (2 grad + 1 fused update), zero retraces after the first window."""
    import jax

    import mxnet_tpu as mx  # noqa: F401
    from mxnet_tpu import cached_step
    from mxnet_tpu.ndarray import ndarray as _ndmod
    from mxnet_tpu.optimizer import fused
    from mxnet_tpu.parallel import spmd

    n_dev = len(jax.devices())
    if n_dev < 4:
        return {"mode": "fsdp", "skipped": f"only {n_dev} device(s)"}
    prev_mesh = os.environ.get("MXNET_SPMD_MESH")
    prev_min = os.environ.get("MXNET_FSDP_MIN_SIZE")
    os.environ["MXNET_SPMD_MESH"] = "dp=2,fsdp=2"
    os.environ["MXNET_FSDP_MIN_SIZE"] = "1"     # the gate MLP is tiny
    try:
        net, trainer, loss_fn, data, label = _build(
            seed=3, rows=8, kvstore="tpu")
        step = trainer.compile_step(net, loss_fn)
        loss = step(data, label, batch_size=8)          # warm
        float(loss.asnumpy().ravel()[0])
        weight = net.collect_params()["d1.weight"].data()._data
        shard = weight.sharding.shard_shape(weight.shape)
        total = sum(p.data()._data.nbytes
                    for _n, p in sorted(net.collect_params().items()))
        per_dev = spmd.param_bytes_per_device()
        inv0, d0, f0, t0 = (_ndmod.invoke_count(),
                            cached_step.dispatch_count(),
                            fused.dispatch_count(),
                            cached_step.trace_count())
        h0 = _ndmod.host_sync_count()
        r0, b0 = spmd.reshard_count(), spmd.replicated_batch_count()
        for _ in range(STEPS):
            loss = step(data, label, batch_size=8)
        h1 = _ndmod.host_sync_count()
        r1, b1 = spmd.reshard_count(), spmd.replicated_batch_count()
        float(loss.asnumpy().ravel()[0])
        out = {
            "mode": "fsdp",
            "skipped": None,
            "used_compiled": step.last_step_compiled,
            "mesh_active": step.mesh is not None,
            "param_sharded": tuple(shard) != tuple(weight.shape),
            "param_bytes_per_device": per_dev,
            "param_bytes_frac": per_dev / total if total else 1.0,
            "eager_invokes_per_step":
                (_ndmod.invoke_count() - inv0) / STEPS,
            "compiled_launches_per_step":
                (cached_step.dispatch_count() - d0) / STEPS,
            "group_launches_per_step":
                (fused.dispatch_count() - f0) / STEPS,
            "retraces_after_warm": cached_step.trace_count() - t0,
            "host_syncs_per_step": (h1 - h0) / STEPS,
            "reshards_after_warm": r1 - r0,
            "replicated_batches": b1 - b0,
        }
        # accumulation sub-lane: same dp×fsdp mesh, accum_steps=2 —
        # exactly N+1 = 3 dispatches per window, zero retraces after
        # the first full window (grad + update programs both warm)
        net2, tr2, loss2, d2, l2 = _build(seed=4, rows=8, kvstore="tpu")
        astep = tr2.compile_step(net2, loss2, accum_steps=2)
        for _ in range(2):                              # warm one window
            loss = astep(d2, l2, batch_size=8)
        float(loss.asnumpy().ravel()[0])
        ad0, at0 = cached_step.dispatch_count(), cached_step.trace_count()
        windows = 3
        for _ in range(2 * windows):
            loss = astep(d2, l2, batch_size=8)
        float(loss.asnumpy().ravel()[0])
        per_window = (cached_step.dispatch_count() - ad0) / windows
        out["accum_used_compiled"] = astep.last_step_compiled
        out["accum_dispatches_per_window"] = per_window
        out["accum_extra_dispatches"] = per_window - 3.0
        out["accum_retraces_after_warm"] = cached_step.trace_count() - at0
        return out
    finally:
        if prev_mesh is None:
            os.environ.pop("MXNET_SPMD_MESH", None)
        else:
            os.environ["MXNET_SPMD_MESH"] = prev_mesh
        if prev_min is None:
            os.environ.pop("MXNET_FSDP_MIN_SIZE", None)
        else:
            os.environ["MXNET_FSDP_MIN_SIZE"] = prev_min


def _measure_pp() -> dict:
    """pp×dp×fsdp lane: a 2-stage PipelineBlock under
    MXNET_SPMD_MESH='pp=2,dp=2,fsdp=2' — the scan-internal GPipe
    schedule keeps the step at ONE donated launch with zero retraces
    and zero steady-state reshards, the packed stage buffer sharded
    one-stage-per-pp-group.  Accum sub-lane: accum_steps=2 on the same
    mesh pays exactly 3 dispatches per window."""
    import jax
    import jax.numpy as jnp
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import cached_step, gluon
    from mxnet_tpu.ndarray import ndarray as _ndmod
    from mxnet_tpu.optimizer import fused
    from mxnet_tpu.parallel import pipeline as pipe_mod, spmd

    n_dev = len(jax.devices())
    if n_dev < 8:
        return {"mode": "pp", "skipped": f"only {n_dev} device(s)"}
    prev_mesh = os.environ.get("MXNET_SPMD_MESH")
    prev_min = os.environ.get("MXNET_FSDP_MIN_SIZE")
    os.environ["MXNET_SPMD_MESH"] = "pp=2,dp=2,fsdp=2"
    os.environ["MXNET_FSDP_MIN_SIZE"] = "1"
    try:
        def build(seed):
            mesh = spmd.resolve_mesh()
            rng = onp.random.RandomState(seed)
            ws = [jnp.asarray((rng.randn(8, 8) * 0.3)
                              .astype(onp.float32)) for _ in range(2)]

            def stage(params, x):
                return jnp.tanh(x @ params["w"])

            pipe = pipe_mod.HeteroPipeline(
                [stage, stage], [{"w": w} for w in ws], mesh,
                num_microbatches=2,
                example_x=jnp.zeros((4, 8), jnp.float32))
            blk = pipe_mod.PipelineBlock(pipe)
            trainer = gluon.Trainer(blk.collect_params(), "sgd",
                                    {"learning_rate": 0.05,
                                     "momentum": 0.9}, kvstore="tpu")
            loss_fn = lambda n, x: ((n(x)) ** 2).sum()
            data = mx.nd.array(rng.randn(4, 8).astype(onp.float32))
            return blk, trainer, loss_fn, data

        blk, trainer, loss_fn, data = build(seed=11)
        step = trainer.compile_step(blk, loss_fn)
        loss = step(data, batch_size=4)                 # warm
        float(loss.asnumpy().ravel()[0])
        packed = blk.pp_stages.data()._data
        shard = packed.sharding.shard_shape(packed.shape)
        inv0, d0, f0, t0 = (_ndmod.invoke_count(),
                            cached_step.dispatch_count(),
                            fused.dispatch_count(),
                            cached_step.trace_count())
        r0, b0 = spmd.reshard_count(), spmd.replicated_batch_count()
        for _ in range(STEPS):
            loss = step(data, batch_size=4)
        r1, b1 = spmd.reshard_count(), spmd.replicated_batch_count()
        float(loss.asnumpy().ravel()[0])
        out = {
            "mode": "pp",
            "skipped": None,
            "used_compiled": step.last_step_compiled,
            "mesh_active": step.mesh is not None,
            "stage_sharded": packed.sharding.spec
            and packed.sharding.spec[0] == "pp" and shard[0] == 1,
            "bubble_fraction": pipe_mod.bubble_fraction(2, 2),
            "eager_invokes_per_step":
                (_ndmod.invoke_count() - inv0) / STEPS,
            "compiled_launches_per_step":
                (cached_step.dispatch_count() - d0) / STEPS,
            "group_launches_per_step":
                (fused.dispatch_count() - f0) / STEPS,
            "retraces_after_warm": cached_step.trace_count() - t0,
            "reshards_after_warm": r1 - r0,
            "replicated_batches": b1 - b0,
        }
        # accum sub-lane: N+1 dispatches per window on the pp mesh
        blk2, tr2, loss2, d2 = build(seed=12)
        astep = tr2.compile_step(blk2, loss2, accum_steps=2)
        for _ in range(2):                              # warm one window
            loss = astep(d2, batch_size=4)
        float(loss.asnumpy().ravel()[0])
        ad0, at0 = cached_step.dispatch_count(), cached_step.trace_count()
        windows = 3
        for _ in range(2 * windows):
            loss = astep(d2, batch_size=4)
        float(loss.asnumpy().ravel()[0])
        per_window = (cached_step.dispatch_count() - ad0) / windows
        out["accum_used_compiled"] = astep.last_step_compiled
        out["accum_dispatches_per_window"] = per_window
        out["accum_extra_dispatches"] = per_window - 3.0
        out["accum_retraces_after_warm"] = cached_step.trace_count() - at0
        return out
    finally:
        if prev_mesh is None:
            os.environ.pop("MXNET_SPMD_MESH", None)
        else:
            os.environ["MXNET_SPMD_MESH"] = prev_mesh
        if prev_min is None:
            os.environ.pop("MXNET_FSDP_MIN_SIZE", None)
        else:
            os.environ["MXNET_FSDP_MIN_SIZE"] = prev_min


def _measure_moe() -> dict:
    """ep×dp lane: an MoEBlock (4 experts, top-2 routing) under
    MXNET_SPMD_MESH='ep=4,dp=2' — gating, dispatch/combine, the
    ep-sharded expert einsums, the folded aux head, and the fused
    update all inside ONE donated launch per step."""
    import jax
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import cached_step, gluon
    from mxnet_tpu.ndarray import ndarray as _ndmod
    from mxnet_tpu.optimizer import fused
    from mxnet_tpu.parallel import moe as moe_mod, spmd

    n_dev = len(jax.devices())
    if n_dev < 8:
        return {"mode": "moe", "skipped": f"only {n_dev} device(s)"}
    prev_mesh = os.environ.get("MXNET_SPMD_MESH")
    prev_min = os.environ.get("MXNET_FSDP_MIN_SIZE")
    os.environ["MXNET_SPMD_MESH"] = "ep=4,dp=2"
    os.environ["MXNET_FSDP_MIN_SIZE"] = "1"
    try:
        net = moe_mod.MoEBlock(units=8, hidden=16, num_experts=4, k=2)
        net.initialize(mx.init.Xavier())
        rng = onp.random.RandomState(13)
        for _name, p in sorted(net.collect_params().items()):
            p.data()._set_data(
                mx.nd.array(rng.randn(*p.shape).astype(onp.float32)
                            * 0.2)._data)
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.01, "momentum": 0.9},
                                kvstore="tpu")
        loss_fn = lambda n, x: ((n(x)) ** 2).sum()
        data = mx.nd.array(rng.randn(4, 6, 8).astype(onp.float32))
        step = trainer.compile_step(net, loss_fn)
        loss = step(data, batch_size=4)                 # warm
        float(loss.asnumpy().ravel()[0])
        ew = net.collect_params()["expert.ffn_1.weight"].data()._data
        inv0, d0, f0, t0 = (_ndmod.invoke_count(),
                            cached_step.dispatch_count(),
                            fused.dispatch_count(),
                            cached_step.trace_count())
        r0, b0 = spmd.reshard_count(), spmd.replicated_batch_count()
        for _ in range(STEPS):
            loss = step(data, batch_size=4)
        r1, b1 = spmd.reshard_count(), spmd.replicated_batch_count()
        float(loss.asnumpy().ravel()[0])
        return {
            "mode": "moe",
            "skipped": None,
            "used_compiled": step.last_step_compiled,
            "mesh_active": step.mesh is not None,
            "expert_sharded": ew.sharding.spec
            and ew.sharding.spec[0] == "ep"
            and ew.sharding.shard_shape(ew.shape)[0] == 1,
            "eager_invokes_per_step":
                (_ndmod.invoke_count() - inv0) / STEPS,
            "compiled_launches_per_step":
                (cached_step.dispatch_count() - d0) / STEPS,
            "group_launches_per_step":
                (fused.dispatch_count() - f0) / STEPS,
            "retraces_after_warm": cached_step.trace_count() - t0,
            "reshards_after_warm": r1 - r0,
            "replicated_batches": b1 - b0,
        }
    finally:
        if prev_mesh is None:
            os.environ.pop("MXNET_SPMD_MESH", None)
        else:
            os.environ["MXNET_SPMD_MESH"] = prev_mesh
        if prev_min is None:
            os.environ.pop("MXNET_FSDP_MIN_SIZE", None)
        else:
            os.environ["MXNET_FSDP_MIN_SIZE"] = prev_min


def _measure_infer() -> dict:
    """Variable-length request stream through the serving engine: warm
    every bucket the stream can hit, then count launches/retraces over a
    randomized stream (the steady-state contract)."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import serving

    net, _trainer, _loss_fn, _d, _l = _build(seed=1)
    policy = serving.BucketPolicy()
    eng = serving.ServingEngine(net, max_delay_us=200, policy=policy)
    buckets = set()
    n = 1
    while n <= INFER_MAXLEN:
        b = policy.bucket(n)
        if b is not None and b not in buckets:
            buckets.add(b)
            eng.infer(mx.nd.array(onp.zeros((b, 8), onp.float32)))
        n += 1
    rng = onp.random.RandomState(7)
    t0, d0 = serving.trace_count(), serving.dispatch_count()
    lengths = rng.randint(1, INFER_MAXLEN + 1, size=INFER_REQUESTS)
    for ln in lengths:
        out = eng.infer(mx.nd.array(rng.randn(int(ln), 8)))
        assert out.shape[0] == int(ln)
    batches = eng.stats()["batches"] - len(buckets)
    out = {
        "mode": "serving",
        "bucket_refused": eng.bucket_refused,
        "requests": INFER_REQUESTS,
        "launches_per_batch":
            (serving.dispatch_count() - d0) / max(batches, 1),
        "retraces_after_warm": serving.trace_count() - t0,
        "programs_over_buckets": max(0, len(eng._programs) - len(buckets)),
        "programs": len(eng._programs),
        "buckets": len(buckets),
    }
    eng.close()
    return out


def _measure_decode() -> dict:
    """Join/retire storm through the continuous batcher: concurrent
    variable-length requests with staggered lengths and budgets so
    sequences join mid-stream and retire early, then count programs,
    retraces, dispatches-per-iteration, and leaked pages."""
    import threading

    import numpy as onp

    from mxnet_tpu import engine as _engine
    from mxnet_tpu import serving_decode as sd

    model = sd.TinyCausalLM(vocab=37, d_model=16, n_layers=2, n_heads=2,
                            max_seq=32)
    params = model.init_params(3)
    pool = sd.PagePool(pages=48, page=4)
    eng = sd.GenerativeEngine(model, params=params, pool=pool,
                              max_rows=4, name="budget")
    grid = eng.warmup(max_len=16)        # pow2 buckets 1..16 + decode
    t0, d0 = sd.trace_count(), sd.dispatch_count()
    rng = onp.random.RandomState(11)
    prompts = [rng.randint(0, 37, size=rng.randint(1, 13)).tolist()
               for _ in range(8)]
    budgets = [3, 9, 5, 2, 7, 4, 8, 6]   # early retires + long tails
    errs = []

    def fire(i):
        try:
            out = eng.generate(prompts[i], max_new_tokens=budgets[i])
            assert len(out) == budgets[i]
        except BaseException as e:        # pragma: no cover
            errs.append(repr(e))

    threads = [threading.Thread(target=fire, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    _engine.waitall()                    # drains the engine's queue
    st = eng.stats()
    out = {
        "mode": "decode",
        "errors": errs,
        "warmup_programs": grid,
        "programs": st["programs"],
        "programs_over_grid": max(0, st["programs"] - grid),
        "retraces_after_warm": sd.trace_count() - t0,
        # 1 dispatch per decode iteration + 1 per prefill, nothing else
        "dispatches": sd.dispatch_count() - d0,
        "decode_steps": st["decode_steps"],
        "prefills": st["prefills"],
        "extra_dispatches": (sd.dispatch_count() - d0)
        - st["decode_steps"] - st["prefills"],
        "rows_per_decode": round(st.get("rows_per_decode", 0.0), 2),
        "leaked_pages": pool.in_use(),
        "shed": st["shed"],
    }
    eng.close()
    return out


def _measure_router() -> dict:
    """Zero-overhead-off lane: the SAME sequential request stream
    through a bare GenerativeEngine and through a ReplicaRouter
    wrapping one replica (hedging off, breaker closed) — the router
    must add zero dispatches, zero retraces, zero host syncs, and the
    token streams must match bit-for-bit."""
    from mxnet_tpu import serving_decode as sd
    from mxnet_tpu.ndarray import ndarray as _ndmod
    from mxnet_tpu.serving_router import ReplicaRouter

    model = sd.TinyCausalLM(vocab=31, d_model=16, n_layers=1, n_heads=2,
                            max_seq=32)
    params = model.init_params(5)
    prompts = [[1 + (i * 3 + j) % 29 for j in range(3 + i % 3)]
               for i in range(6)]

    def run(route: bool) -> dict:
        from mxnet_tpu import telemetry as _tel

        pool = sd.PagePool(pages=32, page=4)
        eng = sd.GenerativeEngine(model, params=params, pool=pool,
                                  max_rows=2, name="lane")
        eng.warmup(max_len=8)
        front = (ReplicaRouter([eng], hedge_pctl=0) if route else eng)
        t0, d0 = sd.trace_count(), sd.dispatch_count()
        h0 = _ndmod.host_sync_count()
        evs = _tel.events()
        e0 = evs[-1]["seq"] if evs else 0
        sp0 = {id(s) for s in _tel.spans()}
        outs = [front.generate(p, max_new_tokens=5) for p in prompts]
        new_evs = [e for e in _tel.events() if e["seq"] > e0]
        new_sps = [s for s in _tel.spans() if id(s) not in sp0]
        row = {"outs": outs,
               "dispatches": sd.dispatch_count() - d0,
               "retraces": sd.trace_count() - t0,
               "host_syncs": _ndmod.host_sync_count() - h0,
               "trace_fields": sum(1 for e in new_evs
                                   if "trace_id" in e)
               + sum(1 for s in new_sps if "trace_id" in s),
               "leaked_pages": pool.in_use()}
        eng.close()
        return row

    bare = run(False)
    routed = run(True)
    # ISSUE-15 disabled-mode contract: with MXNET_TELEMETRY_TRACE=0 the
    # routed lane is BYTE-IDENTICAL to PR 14 — same token streams, same
    # dispatch/retrace/host-sync counts, and zero trace fields on any
    # event or span (the knob is uncached, so the env flip takes
    # effect immediately)
    prev = os.environ.get("MXNET_TELEMETRY_TRACE")
    os.environ["MXNET_TELEMETRY_TRACE"] = "0"
    try:
        routed_off = run(True)
    finally:
        if prev is None:
            os.environ.pop("MXNET_TELEMETRY_TRACE", None)
        else:
            os.environ["MXNET_TELEMETRY_TRACE"] = prev
    return {
        "mode": "router",
        "requests": len(prompts),
        "bare_dispatches": bare["dispatches"],
        "routed_dispatches": routed["dispatches"],
        "extra_dispatches": routed["dispatches"] - bare["dispatches"],
        "extra_retraces": routed["retraces"] - bare["retraces"],
        "extra_host_syncs": routed["host_syncs"] - bare["host_syncs"],
        "outputs_equal": bare["outs"] == routed["outs"],
        "leaked_pages": (bare["leaked_pages"] + routed["leaked_pages"]
                         + routed_off["leaked_pages"]),
        "traced_off_outputs_equal": routed_off["outs"] == bare["outs"],
        "traced_off_extra_dispatches":
            routed_off["dispatches"] - bare["dispatches"],
        "traced_off_extra_retraces":
            routed_off["retraces"] - bare["retraces"],
        "traced_off_extra_host_syncs":
            routed_off["host_syncs"] - bare["host_syncs"],
        "traced_off_trace_fields": routed_off["trace_fields"],
    }


def _measure_spec() -> dict:
    """Speculative-decoding lane: a high-agreement draft under
    MXNET_SPEC_DECODE=1 drives a mixed greedy/sampled join/retire
    storm — bounded programs (== the warmup grid across BOTH
    ProgramStore namespaces), 0 retraces, < 1 target dispatch per
    committed token, greedy rows token-exact vs the eager oracle, 0
    leaked pages.  Then the off leg: the SAME greedy stream through a
    draft-attached engine with MXNET_SPEC_DECODE=0 must match a
    draft-free engine's dispatch/retrace budget and tokens exactly."""
    import threading

    import numpy as onp

    from mxnet_tpu import engine as _engine
    from mxnet_tpu import serving_decode as sd

    target, tp, draft, dp = sd.high_agreement_pair(
        vocab=41, d_model=16, target_layers=2, draft_layers=1,
        n_heads=2, max_seq=64, seed=5)
    rng = onp.random.RandomState(23)
    prompts = [rng.randint(0, 41, size=rng.randint(1, 10)).tolist()
               for _ in range(8)]
    budgets = [6, 9, 4, 8, 5, 7, 10, 6]
    # even rows greedy (token-exactness leg), odd rows sampled (the
    # heterogeneous-config leg: same programs, zero retraces)
    samps = [None if i % 2 == 0
             else sd.SamplingSpec(temperature=0.9, top_k=7, top_p=0.95,
                                  seed=100 + i)
             for i in range(8)]
    prev = os.environ.get("MXNET_SPEC_DECODE")
    os.environ["MXNET_SPEC_DECODE"] = "1"
    try:
        pool = sd.PagePool(pages=96, page=4)
        eng = sd.GenerativeEngine(target, params=tp, pool=pool,
                                  max_rows=4, name="spec_lane",
                                  draft=draft, draft_params=dp,
                                  spec_k=4)
        grid = eng.warmup(max_len=16)
        t0 = sd.trace_count() + sd.spec_trace_count()
        d0 = sd.dispatch_count() + sd.spec_dispatch_count()
        outs: list = [None] * 8
        errs: list = []

        def fire(i):
            try:
                outs[i] = eng.generate(prompts[i],
                                       max_new_tokens=budgets[i],
                                       sampling=samps[i])
            except BaseException as e:    # pragma: no cover
                errs.append(repr(e))

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        _engine.waitall()
        st = eng.stats()
        greedy_exact = all(
            outs[i] == sd.eager_generate(target, tp, prompts[i],
                                         budgets[i])
            for i in range(0, 8, 2) if outs[i] is not None)
        tokens = sum(len(o) for o in outs if o is not None)
        # target-equivalent dispatches: each plain decode step AND each
        # verify round costs one target-model launch; the draft's
        # launches ride the cheap geometry and are priced by the cost
        # table, not this ratio
        target_dispatches = st["decode_steps"] + st["spec_rounds"]
        row = {
            "mode": "spec",
            "errors": errs,
            "warmup_programs": grid,
            "programs": st["programs"] + st["spec_programs"],
            "programs_over_grid":
                max(0, st["programs"] + st["spec_programs"] - grid),
            "retraces_after_warm":
                (sd.trace_count() + sd.spec_trace_count()) - t0,
            "dispatches":
                (sd.dispatch_count() + sd.spec_dispatch_count()) - d0,
            "spec_rounds": st["spec_rounds"],
            "spec_proposed": st["spec_proposed"],
            "spec_accepted": st["spec_accepted"],
            "acceptance": (st["spec_accepted"]
                           / max(st["spec_proposed"], 1)),
            "spec_disabled": st["spec_disabled"],
            "tokens": tokens,
            "target_dispatches_per_token":
                target_dispatches / max(tokens, 1),
            "greedy_token_exact": greedy_exact,
            "leaked_pages": pool.in_use(),
        }
        eng.close()
    finally:
        if prev is None:
            os.environ.pop("MXNET_SPEC_DECODE", None)
        else:
            os.environ["MXNET_SPEC_DECODE"] = prev
    # the OFF leg: greedy path byte-identical dispatch budget with the
    # knob off, draft attached or not (MXNET_SPEC_DECODE=0 is ambient
    # here — the knob is uncached)

    def run_off(with_draft: bool) -> dict:
        pool2 = sd.PagePool(pages=64, page=4)
        kw = (dict(draft=draft, draft_params=dp, spec_k=4)
              if with_draft else {})
        e2 = sd.GenerativeEngine(target, params=tp, pool=pool2,
                                 max_rows=2, name="spec_off", **kw)
        e2.warmup(max_len=16)
        t1 = sd.trace_count() + sd.spec_trace_count()
        d1 = sd.dispatch_count() + sd.spec_dispatch_count()
        toks = [e2.generate(p, max_new_tokens=5) for p in prompts[:4]]
        got = {
            "outs": toks,
            "dispatches":
                (sd.dispatch_count() + sd.spec_dispatch_count()) - d1,
            "retraces": (sd.trace_count() + sd.spec_trace_count()) - t1,
            "leaked_pages": pool2.in_use(),
        }
        e2.close()
        return got

    bare = run_off(False)
    offd = run_off(True)
    row["greedy_off_extra_dispatches"] = (offd["dispatches"]
                                          - bare["dispatches"])
    row["greedy_off_extra_retraces"] = offd["retraces"] - bare["retraces"]
    row["greedy_off_outputs_equal"] = offd["outs"] == bare["outs"]
    row["leaked_pages"] += bare["leaked_pages"] + offd["leaked_pages"]
    return row


def _store_worker() -> None:
    """``--store-worker`` mode: run the tiny train-step + serving-bucket
    workload in THIS process and print its program-store verdict as one
    JSON line.  The parent runs it twice against one
    MXNET_PROGRAM_CACHE_DIR; the second run must report 0 fresh XLA
    compiles and a bit-exact output digest."""
    import json
    import time

    t0 = time.perf_counter()
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import program_store, serving

    net, trainer, loss_fn, data, label = _build()
    step = trainer.compile_step(net, loss_fn)
    losses = []
    first_result_s = None
    for _ in range(3):
        loss = step(data, label, batch_size=6)
        losses.append(float(loss.asnumpy().ravel()[0]))
        if first_result_s is None:
            first_result_s = time.perf_counter() - t0
    assert step.last_step_compiled, step.last_fallback_reason
    net2, _tr, _lf, _d, _l = _build(seed=1)
    eng = serving.ServingEngine(net2, max_delay_us=0)
    out = eng.infer(mx.nd.array(onp.ones((3, 8), onp.float32)))
    digest = ([l.hex() for l in losses]
              + [float(v).hex() for v in
                 onp.asarray(out.asnumpy(), onp.float64).ravel().tolist()])
    eng.close()
    ds = program_store.disk_stats()
    print(json.dumps({
        "fresh_compiles": ds["misses"], "disk_hits": ds["hits"],
        "persistent_enabled": ds["enabled"],
        "first_result_s": round(first_result_s, 3),
        "digest": digest}), flush=True)


def _measure_store_cold_start() -> dict:
    """Warm second-process lane: two subprocesses replay the same
    workload against one persistent program cache — process B must
    compile nothing and reproduce process A's outputs bit-exactly."""
    import json
    import subprocess
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="mxnet_program_store_gate_")
    env = dict(os.environ)
    env["MXNET_PROGRAM_CACHE_DIR"] = cache_dir
    # the knob under test must own the cache dir (never piggyback on an
    # externally configured jax cache)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    runs = []
    for i in ("A", "B"):
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--store-worker"],
            env=env, capture_output=True, text=True, timeout=300)
        if r.returncode != 0:
            return {"mode": "store", "error":
                    f"store worker {i} rc={r.returncode}: "
                    + r.stderr.strip()[-500:]}
        runs.append(json.loads(r.stdout.strip().splitlines()[-1]))
    a, b = runs
    return {
        "mode": "store",
        "error": None,
        "cache_dir": cache_dir,
        "persistent_enabled": a["persistent_enabled"],
        "first_process_compiles": a["fresh_compiles"],
        "second_process_compiles": b["fresh_compiles"],
        "second_process_disk_hits": b["disk_hits"],
        "first_result_s": (a["first_result_s"], b["first_result_s"]),
        "outputs_bit_exact": a["digest"] == b["digest"],
    }


def main() -> int:
    from mxnet_tpu import program_store as _ps

    ev0 = sum(_ps.stats(n)["evictions"]
              for n in ("train_step", "serving", "hybrid_forward"))
    compiled = _measure(True)
    eager = _measure(False)
    amp_row = _measure(True, with_amp=True)
    print(f"{'mode':<13} {'dispatches':>11} {'compiled':>9} "
          f"{'eager-ops':>10} {'group':>6} {'retrace':>8} {'syncs':>6}")
    for row in (compiled, amp_row, eager):
        print(f"{row['mode']:<13} {row['dispatches_per_step']:>11.1f} "
              f"{row['compiled_launches_per_step']:>9.1f} "
              f"{row['eager_invokes_per_step']:>10.1f} "
              f"{row['group_launches_per_step']:>6.1f} "
              f"{row['retraces_after_warm']:>8d} "
              f"{row['host_syncs_per_step']:>6.1f}")
    infer = _measure_infer()
    print(f"{'serving':<10} requests {infer['requests']} -> "
          f"{infer['launches_per_batch']:.1f} launches/batch, "
          f"{infer['retraces_after_warm']} retraces, "
          f"{infer['programs']} programs over {infer['buckets']} buckets")
    decode = _measure_decode()
    print(f"{'decode':<10} storm -> {decode['programs']} programs "
          f"(grid {decode['warmup_programs']}), "
          f"{decode['retraces_after_warm']} retraces, "
          f"{decode['dispatches']} dispatches = "
          f"{decode['decode_steps']} decode + "
          f"{decode['prefills']} prefill "
          f"({decode['rows_per_decode']} rows/step), "
          f"{decode['leaked_pages']} leaked pages")
    spec = _measure_spec()
    print(f"{'spec':<10} mixed storm -> {spec['programs']} programs "
          f"(grid {spec['warmup_programs']}), "
          f"{spec['retraces_after_warm']} retraces, "
          f"{spec['spec_rounds']} rounds "
          f"{spec['spec_accepted']}/{spec['spec_proposed']} accepted "
          f"({spec['acceptance']:.2f}), "
          f"{spec['target_dispatches_per_token']:.2f} target "
          f"dispatches/token over {spec['tokens']} tokens; off leg "
          f"{spec['greedy_off_extra_dispatches']} extra dispatches")
    router = _measure_router()
    print(f"{'router':<10} 1 replica, hedge off -> "
          f"{router['routed_dispatches']} dispatches "
          f"(bare {router['bare_dispatches']}), "
          f"{router['extra_retraces']} extra retraces, "
          f"{router['extra_host_syncs']} extra host syncs, outputs "
          f"{'==' if router['outputs_equal'] else '!='} bare")
    snt = _measure_sentinel()
    print(f"{'sentinel':<10} cadence 2 -> "
          f"{snt['compiled_launches_per_step']:.1f} launch/step, "
          f"{snt['retraces_after_warm']} retraces, "
          f"{snt['digest_reads']} digest reads "
          f"({snt['host_syncs']} syncs), fold "
          f"{'==' if snt['fold_matches_host'] else '!='} host recompute")
    mesh = _measure_mesh()
    if mesh["skipped"]:
        print(f"mesh       SKIPPED ({mesh['skipped']})")
    else:
        print(f"{'mesh':<10} {mesh['mesh_devices']} devices -> "
              f"{mesh['compiled_launches_per_step']:.1f} launch/step, "
              f"{mesh['retraces_after_warm']} retraces, "
              f"{mesh['reshards_after_warm']} reshards, "
              f"{mesh['replicated_batches']} replicated batches")
    fsdp = _measure_fsdp()
    if fsdp["skipped"]:
        print(f"fsdp       SKIPPED ({fsdp['skipped']})")
    else:
        print(f"{'fsdp':<10} dp=2,fsdp=2 -> "
              f"{fsdp['compiled_launches_per_step']:.1f} launch/step, "
              f"{fsdp['retraces_after_warm']} retraces, "
              f"{fsdp['reshards_after_warm']} reshards, "
              f"{fsdp['param_bytes_frac']:.2f}x param bytes/device; "
              f"accum 2 -> {fsdp['accum_dispatches_per_window']:.1f} "
              f"dispatches/window, "
              f"{fsdp['accum_retraces_after_warm']} retraces")
    pp = _measure_pp()
    if pp["skipped"]:
        print(f"pp         SKIPPED ({pp['skipped']})")
    else:
        print(f"{'pp':<10} pp=2,dp=2,fsdp=2 -> "
              f"{pp['compiled_launches_per_step']:.1f} launch/step, "
              f"{pp['retraces_after_warm']} retraces, "
              f"{pp['reshards_after_warm']} reshards, theoretical "
              f"bubble {pp['bubble_fraction']:.2f}; accum 2 -> "
              f"{pp['accum_dispatches_per_window']:.1f} "
              f"dispatches/window, "
              f"{pp['accum_retraces_after_warm']} retraces")
    moe = _measure_moe()
    if moe["skipped"]:
        print(f"moe        SKIPPED ({moe['skipped']})")
    else:
        print(f"{'moe':<10} ep=4,dp=2 -> "
              f"{moe['compiled_launches_per_step']:.1f} launch/step, "
              f"{moe['retraces_after_warm']} retraces, "
              f"{moe['reshards_after_warm']} reshards, experts "
              f"{'sharded' if moe['expert_sharded'] else 'REPLICATED'}")
    # program-store lane: all the steady-state runs above went through
    # the store — they must not have evicted anything
    ev_after_warm = sum(
        _ps.stats(n)["evictions"]
        for n in ("train_step", "serving", "hybrid_forward")) - ev0
    store = _measure_store_cold_start()
    if store["error"]:
        print(f"store      FAILED ({store['error']})")
    else:
        print(f"{'store':<10} warm 2nd process: "
              f"{store['second_process_compiles']} fresh compiles, "
              f"{store['second_process_disk_hits']} disk hits "
              f"(1st process compiled {store['first_process_compiles']}), "
              f"first result {store['first_result_s'][0]:.2f}s -> "
              f"{store['first_result_s'][1]:.2f}s, "
              f"{ev_after_warm} evictions in-process")
    failures = []
    if not compiled["used_compiled"]:
        failures.append("compiled mode fell back to the eager tape")
    for key, budget in BUDGET.items():
        if compiled[key] > budget:
            failures.append(
                f"{key} = {compiled[key]} exceeds budget {budget}")
    if not amp_row["used_compiled"]:
        failures.append("compiled AMP mode fell back to the eager tape")
    for key, budget in AMP_BUDGET.items():
        if amp_row[key] > budget:
            failures.append(
                f"AMP {key} = {amp_row[key]} exceeds budget {budget}")
    if amp_row["host_syncs_per_step"] > amp_row["deferred_reads_per_step"]:
        failures.append(
            "AMP step performs a blocking host sync beyond the deferred "
            f"flag read ({amp_row['host_syncs_per_step']} syncs vs "
            f"{amp_row['deferred_reads_per_step']} deferred reads)")
    if infer["bucket_refused"] is not None:
        failures.append(
            f"serving refused bucketing: {infer['bucket_refused']}")
    for key, budget in INFER_BUDGET.items():
        if infer[key] > budget:
            failures.append(
                f"serving {key} = {infer[key]} exceeds budget {budget}")
    if decode["errors"]:
        failures.append(f"decode storm errors: {decode['errors']}")
    if decode["shed"]:
        failures.append(
            f"decode storm shed {decode['shed']} request(s) — the gate "
            "pool is sized to absorb the whole storm")
    for key, budget in DECODE_BUDGET.items():
        if decode[key] > budget:
            failures.append(
                f"decode {key} = {decode[key]} exceeds budget {budget}")
    if spec["errors"]:
        failures.append(f"spec storm errors: {spec['errors']}")
    for key, budget in SPEC_BUDGET.items():
        if spec[key] > budget:
            failures.append(
                f"spec {key} = {spec[key]} exceeds budget {budget}")
    if spec["spec_rounds"] == 0 or spec["spec_disabled"]:
        failures.append(
            "spec lane never engaged speculation (0 rounds or "
            "auto-disabled) on the high-agreement fixture")
    if spec["acceptance"] < 0.7:
        failures.append(
            f"spec acceptance {spec['acceptance']:.2f} < 0.7 on the "
            "high-agreement draft (rejection sampling broken?)")
    if spec["target_dispatches_per_token"] >= 1.0:
        failures.append(
            f"spec pays {spec['target_dispatches_per_token']:.2f} "
            "target dispatches per committed token (must be < 1: the "
            "k-for-1 verify win is gone)")
    if not spec["greedy_token_exact"]:
        failures.append(
            "spec greedy rows diverge from the eager oracle "
            "(token-exactness invariant broken under speculation)")
    if not spec["greedy_off_outputs_equal"]:
        failures.append(
            "MXNET_SPEC_DECODE=0 draft-attached token streams differ "
            "from the draft-free engine's")
    for key, budget in ROUTER_BUDGET.items():
        if router[key] > budget:
            failures.append(
                f"router {key} = {router[key]} exceeds budget {budget} "
                "(zero-overhead-off broken)")
    if not router["outputs_equal"]:
        failures.append(
            "router-wrapped token streams differ from the bare engine's")
    if router["leaked_pages"]:
        failures.append(
            f"router lane leaked {router['leaked_pages']} KV pages")
    # ISSUE-15: tracing disabled must be byte-identical to PR 14
    if not router["traced_off_outputs_equal"]:
        failures.append(
            "router token streams under MXNET_TELEMETRY_TRACE=0 differ "
            "from the bare engine's")
    for key in ("traced_off_extra_dispatches", "traced_off_extra_retraces",
                "traced_off_extra_host_syncs", "traced_off_trace_fields"):
        if router[key] != 0:
            failures.append(
                f"router {key} = {router[key]} with tracing disabled "
                "(must be 0: zero overhead when off)")
    for key, budget in SENTINEL_BUDGET.items():
        if snt[key] > budget:
            failures.append(
                f"sentinel {key} = {snt[key]} exceeds budget {budget}")
    if snt["digest_reads"] != 3:
        failures.append(
            f"sentinel read {snt['digest_reads']} digests over 5 steps "
            "at cadence 2 (expected 3: one per cadence window)")
    if snt["host_syncs"] > snt["digest_reads"]:
        failures.append(
            "sentinel step performs host syncs beyond the deferred "
            f"digest reads ({snt['host_syncs']} syncs vs "
            f"{snt['digest_reads']} reads)")
    if not snt["fold_matches_host"]:
        failures.append(
            f"in-program digest {snt['fold']} != host recomputation "
            f"{snt['host_fold']} — the fingerprint does not attest the "
            "state it claims to")
    if not mesh["skipped"]:
        if not mesh["used_compiled"]:
            failures.append("mesh mode fell back to the eager tape")
        if not mesh["mesh_active"]:
            failures.append(
                "kvstore='tpu' did not resolve an SPMD mesh")
        if mesh["mesh_devices"] != mesh["n_devices"]:
            failures.append(
                f"params replicated over {mesh['mesh_devices']} devices, "
                f"expected {mesh['n_devices']}")
        for key, budget in MESH_BUDGET.items():
            if mesh[key] > budget:
                failures.append(
                    f"mesh {key} = {mesh[key]} exceeds budget {budget}")
    if not fsdp["skipped"]:
        if not fsdp["used_compiled"]:
            failures.append("fsdp mode fell back to the eager tape")
        if not fsdp["accum_used_compiled"]:
            failures.append(
                "fsdp accumulation mode fell back to the eager tape")
        if not fsdp["mesh_active"]:
            failures.append(
                "fsdp lane: kvstore='tpu' did not resolve a dp=2,fsdp=2 "
                "mesh")
        if not fsdp["param_sharded"]:
            failures.append(
                "fsdp lane: d1.weight is fully replicated — the fsdp "
                "axis did not shard the parameters")
        if fsdp["param_bytes_frac"] > 0.75:
            failures.append(
                f"fsdp lane: param bytes per device is "
                f"{fsdp['param_bytes_frac']:.2f}x the global footprint "
                "(expected ~1/fsdp = 0.5x on a 2-way fsdp axis)")
        for key, budget in FSDP_BUDGET.items():
            if fsdp[key] > budget:
                failures.append(
                    f"fsdp {key} = {fsdp[key]} exceeds budget {budget}")
    if not pp["skipped"]:
        if not pp["used_compiled"]:
            failures.append("pp mode fell back to the eager tape")
        if not pp["accum_used_compiled"]:
            failures.append(
                "pp accumulation mode fell back to the eager tape")
        if not pp["mesh_active"]:
            failures.append(
                "pp lane: kvstore='tpu' did not resolve a "
                "pp=2,dp=2,fsdp=2 mesh")
        if not pp["stage_sharded"]:
            failures.append(
                "pp lane: packed stage buffer is not one-stage-per-pp-"
                "group (expected P('pp') with shard dim 0 == 1)")
        for key, budget in PP_BUDGET.items():
            if pp[key] > budget:
                failures.append(
                    f"pp {key} = {pp[key]} exceeds budget {budget}")
    if not moe["skipped"]:
        if not moe["used_compiled"]:
            failures.append("moe mode fell back to the eager tape")
        if not moe["mesh_active"]:
            failures.append(
                "moe lane: kvstore='tpu' did not resolve an ep=4,dp=2 "
                "mesh")
        if not moe["expert_sharded"]:
            failures.append(
                "moe lane: expert weights are replicated — the ep axis "
                "did not shard dim 0 (expected 1 expert per ep group)")
        for key, budget in MOE_BUDGET.items():
            if moe[key] > budget:
                failures.append(
                    f"moe {key} = {moe[key]} exceeds budget {budget}")
    if ev_after_warm > STORE_BUDGET["evictions_after_warm"]:
        failures.append(
            f"program store evicted {ev_after_warm} programs during "
            "steady-state runs (caps must cover the declared grid)")
    if compiled["live_programs"] - 1 > \
            STORE_BUDGET["live_train_programs_over"]:
        failures.append(
            f"train step holds {compiled['live_programs']} live programs "
            "for one constant-shape signature (expected 1)")
    if store["error"]:
        failures.append(f"program-store cold-start lane: {store['error']}")
    else:
        if not store["persistent_enabled"]:
            failures.append(
                "MXNET_PROGRAM_CACHE_DIR did not enable the persistent "
                "compilation cache in the worker")
        if store["second_process_compiles"] > \
                STORE_BUDGET["second_process_compiles"]:
            failures.append(
                f"warm second process performed "
                f"{store['second_process_compiles']} fresh XLA compiles "
                "(expected 0: every program must be a disk/memory hit)")
        if not store["outputs_bit_exact"]:
            failures.append(
                "warm second process outputs differ from the first "
                "process (disk-cached executables must be bit-exact)")
    if failures:
        print("check_dispatch_budget: FAILED —", "; ".join(failures),
              file=sys.stderr)
        return 1
    print(f"check_dispatch_budget: compiled step within budget "
          f"({compiled['dispatches_per_step']:.0f} dispatch/step, "
          f"{compiled['host_syncs_per_step']:.0f} host syncs over "
          f"{STEPS} steps; AMP pays {amp_row['host_syncs_per_step']:.0f} "
          f"sync = {amp_row['deferred_reads_per_step']:.0f} deferred "
          f"read; eager tape pays "
          f"{eager['dispatches_per_step']:.0f}); serving within budget "
          f"({infer['launches_per_batch']:.0f} launch/batch, "
          f"{infer['retraces_after_warm']} retraces, "
          f"{infer['programs']} programs <= {infer['buckets']} buckets)"
          f"; decode within budget ({decode['programs']} programs == "
          f"grid {decode['warmup_programs']}, "
          f"{decode['retraces_after_warm']} retraces, "
          f"{decode['extra_dispatches']} extra dispatches, "
          f"{decode['leaked_pages']} leaked pages)"
          f"; spec within budget ({spec['programs']} programs == grid, "
          f"{spec['target_dispatches_per_token']:.2f} target "
          f"dispatches/token at {spec['acceptance']:.2f} acceptance, "
          f"off leg {spec['greedy_off_extra_dispatches']} extra)"
          f"; router within budget ({router['extra_dispatches']} extra "
          f"dispatches over {router['requests']} routed requests)"
          f"; sentinel within budget "
          f"({snt['compiled_launches_per_step']:.0f} launch/step, "
          f"{snt['digest_reads']} digest reads, fold == host)"
          + ("" if mesh["skipped"] else
             f"; mesh within budget ({mesh['mesh_devices']}-device SPMD, "
             f"{mesh['compiled_launches_per_step']:.0f} launch/step, "
             f"{mesh['reshards_after_warm']} steady-state reshards)")
          + ("" if fsdp["skipped"] else
             f"; fsdp within budget "
             f"({fsdp['compiled_launches_per_step']:.0f} launch/step at "
             f"{fsdp['param_bytes_frac']:.2f}x param bytes/device, accum "
             f"{fsdp['accum_dispatches_per_window']:.0f} "
             f"dispatches/window)")
          + ("" if pp["skipped"] else
             f"; pp within budget "
             f"({pp['compiled_launches_per_step']:.0f} launch/step "
             f"scan-internal schedule, accum "
             f"{pp['accum_dispatches_per_window']:.0f} "
             f"dispatches/window)")
          + ("" if moe["skipped"] else
             f"; moe within budget "
             f"({moe['compiled_launches_per_step']:.0f} launch/step, "
             f"{moe['reshards_after_warm']} reshards, ep-sharded "
             f"experts)")
          + f"; program store within budget ({ev_after_warm} evictions, "
            f"warm 2nd process {store['second_process_compiles']} "
            f"compiles / {store['second_process_disk_hits']} disk hits)")
    return 0


if __name__ == "__main__":
    if "--store-worker" in sys.argv:
        _store_worker()
        sys.exit(0)
    sys.exit(main())
