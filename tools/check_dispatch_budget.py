#!/usr/bin/env python
"""CI gate: the compiled train step must stay inside its dispatch budget.

Runs a tiny MLP under both step modes and FAILS (exit 1) if the compiled
mode exceeds the documented budget — guarding against silent de-fusion
regressions (an eager op sneaking back into the hot loop, a per-step
re-trace, a group program splitting off the whole-step program):

- compiled mode: exactly ``1`` compiled launch per step
  (``cached_step.dispatch_count``), ``0`` eager op dispatches
  (``ndarray.invoke_count``), ``0`` separate fused group-program launches
  (``fused.dispatch_count`` — the update must ride INSIDE the step
  program), and ``0`` re-traces across constant-shape steps;
- eager mode (comparison lane, printed, not gated): the tape path's
  dispatches/step.

Invoked by the test suite (tests/test_cached_step.py) exactly like
tools/check_fault_sites.py, and runnable standalone:
``JAX_PLATFORMS=cpu python tools/check_dispatch_budget.py``.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the budget the docs promise (docs/PERF.md "Compiled whole-train-step")
BUDGET = {"compiled_launches_per_step": 1, "eager_invokes_per_step": 0,
          "group_launches_per_step": 0, "retraces_after_warm": 0}
STEPS = 5


def _build(seed: int = 0):
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    class Net(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.d1 = nn.Dense(16, in_units=8, activation="relu")
            self.d2 = nn.Dense(4, in_units=16)

        def forward(self, x):
            return self.d2(self.d1(x))

    net = Net()
    net.initialize(mx.init.Xavier())
    rng = onp.random.RandomState(seed)
    for _name, p in sorted(net.collect_params().items()):
        p.data()._set_data(mx.nd.array(rng.randn(*p.shape) * 0.1)._data)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    data = mx.nd.array(rng.randn(6, 8))
    label = mx.nd.array(rng.randn(6, 4))
    loss_fn = lambda n, x, y: ((n(x) - y) ** 2).mean()
    return net, trainer, loss_fn, data, label


def _measure(compiled: bool) -> dict:
    import mxnet_tpu as mx
    from mxnet_tpu import cached_step
    from mxnet_tpu.ndarray import ndarray as _ndmod
    from mxnet_tpu.optimizer import fused

    net, trainer, loss_fn, data, label = _build()
    if compiled:
        step = trainer.compile_step(net, loss_fn)

        def one_step():
            return step(data, label, batch_size=6)
    else:
        def one_step():
            with mx.autograd.record():
                loss = loss_fn(net, data, label)
            loss.backward()
            trainer.step(6)
            return loss

    loss = one_step()                    # warm: trace + state create
    float(loss.asnumpy().ravel()[0])     # drain
    inv0, d0, f0, t0 = (_ndmod.invoke_count(), cached_step.dispatch_count(),
                        fused.dispatch_count(), cached_step.trace_count())
    for _ in range(STEPS):
        loss = one_step()
    float(loss.asnumpy().ravel()[0])     # fence
    out = {
        "mode": "compiled" if compiled else "eager",
        "used_compiled": compiled and step.last_step_compiled,
        "eager_invokes_per_step":
            (_ndmod.invoke_count() - inv0) / STEPS,
        "compiled_launches_per_step":
            (cached_step.dispatch_count() - d0) / STEPS,
        "group_launches_per_step": (fused.dispatch_count() - f0) / STEPS,
        "retraces_after_warm": cached_step.trace_count() - t0,
    }
    out["dispatches_per_step"] = (out["eager_invokes_per_step"]
                                  + out["compiled_launches_per_step"]
                                  + out["group_launches_per_step"])
    return out


def main() -> int:
    compiled = _measure(True)
    eager = _measure(False)
    print(f"{'mode':<10} {'dispatches':>11} {'compiled':>9} {'eager-ops':>10} "
          f"{'group':>6} {'retrace':>8}")
    for row in (compiled, eager):
        print(f"{row['mode']:<10} {row['dispatches_per_step']:>11.1f} "
              f"{row['compiled_launches_per_step']:>9.1f} "
              f"{row['eager_invokes_per_step']:>10.1f} "
              f"{row['group_launches_per_step']:>6.1f} "
              f"{row['retraces_after_warm']:>8d}")
    failures = []
    if not compiled["used_compiled"]:
        failures.append("compiled mode fell back to the eager tape")
    for key, budget in BUDGET.items():
        if compiled[key] > budget:
            failures.append(
                f"{key} = {compiled[key]} exceeds budget {budget}")
    if failures:
        print("check_dispatch_budget: FAILED —", "; ".join(failures),
              file=sys.stderr)
        return 1
    print(f"check_dispatch_budget: compiled step within budget "
          f"({compiled['dispatches_per_step']:.0f} dispatch/step over "
          f"{STEPS} steps; eager tape pays "
          f"{eager['dispatches_per_step']:.0f})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
