"""Run a test many times with fresh seeds to expose flakiness (reference
tools/flakiness_checker.py).

The suite's conftest derives per-test seeds from ``MXNET_TEST_SEED``; this
driver re-runs the chosen test N times with different seeds and reports
every failing seed, so a flaky test becomes reproducible with
``MXNET_TEST_SEED=<seed> pytest <test>``.

    python tools/flakiness_checker.py tests/test_operator.py::test_dot -n 20
"""
import argparse
import os
import random
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_once(test: str, seed: int, timeout: float) -> bool:
    env = dict(os.environ)
    env["MXNET_TEST_SEED"] = str(seed)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)   # never hang on a wedged tunnel
    r = subprocess.run(
        [sys.executable, "-m", "pytest", test, "-x", "-q"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout)
    return r.returncode == 0


def main():
    p = argparse.ArgumentParser(description="flakiness checker")
    p.add_argument("test", help="pytest node id, e.g. tests/t.py::test_x")
    p.add_argument("-n", "--trials", type=int, default=10)
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--seed", type=int, default=None,
                   help="seed the seed sequence itself (reproducible runs)")
    args = p.parse_args()

    rng = random.Random(args.seed)
    failed = []
    for i in range(args.trials):
        seed = rng.randrange(2 ** 31)
        ok = run_once(args.test, seed, args.timeout)
        print(f"trial {i + 1}/{args.trials} seed={seed}: "
              f"{'PASS' if ok else 'FAIL'}", flush=True)
        if not ok:
            failed.append(seed)

    print()
    if failed:
        print(f"FLAKY: {len(failed)}/{args.trials} failures; reproduce "
              f"with e.g. MXNET_TEST_SEED={failed[0]} python -m pytest "
              f"{args.test}")
        sys.exit(1)
    print(f"stable across {args.trials} seeded trials")


if __name__ == "__main__":
    main()
