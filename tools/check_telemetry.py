#!/usr/bin/env python
"""Telemetry CI gate (the observability analog of check_fault_sites):

1. **No unregistered counters.**  Every public ``*_count``-style
   accessor in ``mxnet_tpu/`` must be a view over a declared telemetry
   registry counter — the accessor's base name must match the final
   segment of a registered counter name (``deferred_read_count`` →
   ``cached_step.deferred_read``, ``trace_count`` →
   ``program_store.*.traces``).  Raw module-global counter state
   (``_X_COUNT = 0``) is forbidden outright.

2. **No untested counters.**  Every registered counter's name — or, for
   dynamic per-site/per-instance counters, its declared ``family`` —
   must appear as a literal in at least one file under ``tests/``.

3. **Deterministic steady-state snapshot.**  Two identical 3-step
   windows of a warmed compiled TrainStep must produce byte-identical
   ``telemetry.delta()`` results over the deterministic (cumulative)
   counters — a nondeterministic counter in the steady state is a
   measurement you can't regress against.

4. **Chrome-trace export.**  One compiled train step + one decode batch
   recorded under the profiler must dump valid chrome-trace JSON
   carrying >= 3 distinct span categories (train_step / decode /
   serving / step_phase) — the unified-timeline acceptance bar.

Exit code 0 = all gates green.  Usage:
``python tools/check_telemetry.py [repo_root]`` (run by the suite via
tests/test_telemetry.py).
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
from typing import Dict, List, Set, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.lint import walk_package  # noqa: E402
from tools.lint import rules as _lint_rules  # noqa: E402


def _py_files(root: str):
    for dirpath, _dirs, files in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for f in files:
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def _walk(pkg_dir: str):
    pkg_dir = os.path.abspath(pkg_dir)
    return walk_package(os.path.dirname(pkg_dir),
                        os.path.basename(pkg_dir))


def collect_accessors(pkg_dir: str) -> Dict[str, Set[str]]:
    """Accessor base name (minus ``_count``) -> files declaring it.
    Since graftlint: the shared AST walk's collection (real FunctionDef
    nodes, public non-``reset_*`` names) instead of a regex."""
    return _lint_rules.collect_accessors(_walk(pkg_dir))


def collect_raw_state(pkg_dir: str) -> List[str]:
    """Forbidden pre-registry counter state still in the tree — the
    graftlint ``counter-discipline`` rule's collection (module-global
    ``_X_COUNT = <n>`` and public ``self.x_count = <n>``)."""
    return sorted(f"{src.rel}: {what}" for src, _node, what
                  in _lint_rules.collect_raw_state(_walk(pkg_dir)))


def _base_matches_segment(base: str, seg: str) -> bool:
    return seg in (base, base + "s", base + "es")


def check_registered(accessors: Dict[str, Set[str]],
                     registry: Dict[str, dict]) -> List[str]:
    """Accessor bases with NO matching registered counter."""
    segs = {n.rsplit(".", 1)[-1] for n in registry}
    missing = []
    for base, files in sorted(accessors.items()):
        if not any(_base_matches_segment(base, s) for s in segs):
            missing.append(f"{base}_count (declared in "
                           f"{', '.join(sorted(files))})")
    return missing


def check_tested(registry: Dict[str, dict], tests_dir: str) -> List[str]:
    """Registered counters whose name/family appears in NO test file.
    Counters under ``test.`` are fixtures the suite itself registered
    while this gate runs in-process — skipped."""
    needles: Dict[str, str] = {}
    for name, meta in registry.items():
        if name.startswith("test."):
            continue
        needles[name] = meta.get("family") or name
    blob = []
    for path in _py_files(tests_dir):
        with open(path, encoding="utf-8") as f:
            blob.append(f.read())
    blob = "\n".join(blob)
    missing = sorted({f"{n} (family {needle!r})" if needle != n else n
                      for n, needle in needles.items()
                      if needle not in blob})
    return missing


# ---------------------------------------------------------------------------
# runtime checks (CPU, tiny shapes)
# ---------------------------------------------------------------------------
# counter namespaces a steady-state compiled train step may touch; the
# reproducibility gate compares EXACTLY these so a background thread
# from an unrelated co-resident test cannot flake the check
_DETERMINISTIC_PREFIXES = ("program_store.train_step.", "cached_step.",
                           "spmd.", "sharding.", "metric.", "fused.",
                           "ndarray.", "faults.", "telemetry.")


def _train_fixture():
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    class Net(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.d1 = nn.Dense(16, in_units=8, activation="relu")
            self.out = nn.Dense(4, in_units=16)

        def forward(self, x):
            return self.out(self.d1(x))

    net = Net()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01, "momentum": 0.9})
    step = trainer.compile_step(net, lambda n, x, y: ((n(x) - y) ** 2)
                                .mean())
    rng = onp.random.RandomState(0)
    x = mx.nd.array(rng.randn(8, 8).astype(onp.float32))
    y = mx.nd.array(rng.randn(8, 4).astype(onp.float32))
    return step, x, y


def _steady_delta(telemetry, step, x, y, n=3) -> Dict[str, object]:
    base = telemetry.snapshot()
    for _ in range(n):
        loss = step(x, y, batch_size=8)
    loss.asnumpy()
    kinds = telemetry.registered()
    return {k: v for k, v in telemetry.delta(base).items()
            if k.startswith(_DETERMINISTIC_PREFIXES)
            and kinds.get(k, {}).get("kind") == "cumulative"}


def check_deterministic_snapshot() -> List[str]:
    from mxnet_tpu import telemetry

    step, x, y = _train_fixture()
    for _ in range(2):                    # warm: trace + compile + AOT
        loss = step(x, y, batch_size=8)
    loss.asnumpy()
    if step.last_fallback_reason is not None:
        return [f"TrainStep fell back eager: {step.last_fallback_reason}"]
    d1 = _steady_delta(telemetry, step, x, y)
    d2 = _steady_delta(telemetry, step, x, y)
    if d1 != d2:
        diff = {k: (d1.get(k), d2.get(k))
                for k in set(d1) | set(d2) if d1.get(k) != d2.get(k)}
        return [f"steady-state TrainStep delta not reproducible: {diff}"]
    if d1.get("program_store.train_step.dispatches") != 3:
        return ["steady-state window did not dispatch 3 compiled steps: "
                f"{d1}"]
    return []


def check_chrome_trace() -> List[str]:
    """One compiled train step + one decode batch under the profiler ->
    the dump must be valid JSON with >= 3 span categories."""
    import numpy as onp

    from mxnet_tpu import profiler, serving_decode, telemetry

    fd, path = tempfile.mkstemp(suffix=".json")
    os.close(fd)
    try:
        profiler.set_config(filename=path)
        profiler.set_state("run")
        step, x, y = _train_fixture()
        tl = profiler.StepTimeline()
        with tl.phase("dispatch"):
            step(x, y, batch_size=8).asnumpy()
        tl.step()
        eng = serving_decode.GenerativeEngine(
            serving_decode.TinyCausalLM(),
            pool=serving_decode.PagePool(pages=64, page=8), max_rows=2)
        try:
            eng.generate(onp.asarray([3, 1, 4]), max_new_tokens=2)
        finally:
            eng.close()
        profiler.set_state("stop")
        out = profiler.dump()
        with open(out) as f:
            trace = json.load(f)          # must be valid JSON
        span_cats = {e["cat"] for e in trace["traceEvents"]
                     if e.get("ph") == "X"}
        want = {"train_step", "decode", "serving", "step_phase"}
        got = span_cats & want
        if len(got) < 3:
            return [f"chrome trace carries {len(got)} span categories "
                    f"{sorted(got)} (need >= 3 of {sorted(want)}); all "
                    f"cats: {sorted(span_cats)}"]
        n_spans = len(telemetry.spans())
        if n_spans < 3:
            return [f"telemetry span buffer has only {n_spans} records"]
    finally:
        os.unlink(path)
    return []


def main(root: str = None) -> int:
    root = root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    pkg = os.path.join(root, "mxnet_tpu")
    tests = os.path.join(root, "tests")
    failures: List[Tuple[str, List[str]]] = []

    accessors = collect_accessors(pkg)
    if not accessors:
        print("check_telemetry: no *_count accessors found under "
              f"{pkg} — regex or layout broke", file=sys.stderr)
        return 1

    raw = collect_raw_state(pkg)
    if raw:
        failures.append(("raw (non-registry) counter state", raw))

    # import every counter-declaring surface, then read the registry
    import mxnet_tpu  # noqa: F401
    from mxnet_tpu import (cached_step, engine, metric,  # noqa: F401
                           profiler, program_store, serving,
                           serving_decode, telemetry)
    from mxnet_tpu.contrib import quantization  # noqa: F401
    from mxnet_tpu.models import transformer_lm  # noqa: F401
    from mxnet_tpu.ops import nn as _ops_nn  # noqa: F401
    from mxnet_tpu.optimizer import fused  # noqa: F401
    from mxnet_tpu.parallel import sharding, spmd  # noqa: F401

    # the runtime checks run FIRST: they instantiate the per-instance
    # counter families (kv_pool, decode.engine) the registry checks
    # then see
    failures.extend(("deterministic steady-state snapshot", [m])
                    for m in check_deterministic_snapshot())
    failures.extend(("chrome-trace export", [m])
                    for m in check_chrome_trace())

    registry = telemetry.registered()
    unregistered = check_registered(accessors, registry)
    if unregistered:
        failures.append(("accessors with no registered counter",
                         unregistered))
    untested = check_tested(registry, tests)
    if untested:
        failures.append(("registered counters never named in a test",
                         untested))

    if failures:
        print("check_telemetry: FAILED", file=sys.stderr)
        for what, items in failures:
            print(f"  [{what}]", file=sys.stderr)
            for it in items:
                print(f"    {it}", file=sys.stderr)
        return 1
    print(f"check_telemetry: {len(accessors)} accessors, "
          f"{len(registry)} registered counters, deterministic "
          "steady-state delta, chrome trace >= 3 span categories")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
