#!/usr/bin/env python
"""Telemetry CI gate (the observability analog of check_fault_sites):

1. **No unregistered counters.**  Every public ``*_count``-style
   accessor in ``mxnet_tpu/`` must be a view over a declared telemetry
   registry counter — the accessor's base name must match the final
   segment of a registered counter name (``deferred_read_count`` →
   ``cached_step.deferred_read``, ``trace_count`` →
   ``program_store.*.traces``).  Raw module-global counter state
   (``_X_COUNT = 0``) is forbidden outright.

2. **No untested counters.**  Every registered counter's name — or, for
   dynamic per-site/per-instance counters, its declared ``family`` —
   must appear as a literal in at least one file under ``tests/``.

3. **Deterministic steady-state snapshot.**  Two identical 3-step
   windows of a warmed compiled TrainStep must produce byte-identical
   ``telemetry.delta()`` results over the deterministic (cumulative)
   counters — a nondeterministic counter in the steady state is a
   measurement you can't regress against.

4. **Chrome-trace export.**  One compiled train step + one decode batch
   recorded under the profiler must dump valid chrome-trace JSON
   carrying >= 3 distinct span categories (train_step / decode /
   serving / step_phase) — the unified-timeline acceptance bar.

5. **Routed requests are traced** (ISSUE 15).  A 2-replica
   ``ReplicaRouter`` driven through a failover, a hedge, and a
   deadline shed must stamp a NON-EMPTY ``trace_id`` on every ``shed``
   / ``failover`` / ``hedge`` event it emits — an unstitchable
   lifecycle record is a regression.

6. **Merge correctness** (ISSUE 15).  Two subprocesses each run the
   identical steady-state TrainStep window and flush one flight-
   recorder shard; ``telemetry.merge`` over the pair must equal
   exactly 2x either process's cumulative window delta on the
   deterministic counters — cross-process aggregation is arithmetic,
   not approximation.

Exit code 0 = all gates green.  Usage:
``python tools/check_telemetry.py [repo_root]`` (run by the suite via
tests/test_telemetry.py; ``--merge-worker`` is gate 6's child entry).
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
from typing import Dict, List, Set, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.lint import walk_package  # noqa: E402
from tools.lint import rules as _lint_rules  # noqa: E402


def _py_files(root: str):
    for dirpath, _dirs, files in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for f in files:
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def _walk(pkg_dir: str):
    pkg_dir = os.path.abspath(pkg_dir)
    return walk_package(os.path.dirname(pkg_dir),
                        os.path.basename(pkg_dir))


def collect_accessors(pkg_dir: str) -> Dict[str, Set[str]]:
    """Accessor base name (minus ``_count``) -> files declaring it.
    Since graftlint: the shared AST walk's collection (real FunctionDef
    nodes, public non-``reset_*`` names) instead of a regex."""
    return _lint_rules.collect_accessors(_walk(pkg_dir))


def collect_raw_state(pkg_dir: str) -> List[str]:
    """Forbidden pre-registry counter state still in the tree — the
    graftlint ``counter-discipline`` rule's collection (module-global
    ``_X_COUNT = <n>`` and public ``self.x_count = <n>``)."""
    return sorted(f"{src.rel}: {what}" for src, _node, what
                  in _lint_rules.collect_raw_state(_walk(pkg_dir)))


def _base_matches_segment(base: str, seg: str) -> bool:
    return seg in (base, base + "s", base + "es")


def check_registered(accessors: Dict[str, Set[str]],
                     registry: Dict[str, dict]) -> List[str]:
    """Accessor bases with NO matching registered counter."""
    segs = {n.rsplit(".", 1)[-1] for n in registry}
    missing = []
    for base, files in sorted(accessors.items()):
        if not any(_base_matches_segment(base, s) for s in segs):
            missing.append(f"{base}_count (declared in "
                           f"{', '.join(sorted(files))})")
    return missing


def check_tested(registry: Dict[str, dict], tests_dir: str) -> List[str]:
    """Registered counters whose name/family appears in NO test file.
    Counters under ``test.`` are fixtures the suite itself registered
    while this gate runs in-process — skipped."""
    needles: Dict[str, str] = {}
    for name, meta in registry.items():
        if name.startswith("test."):
            continue
        needles[name] = meta.get("family") or name
    blob = []
    for path in _py_files(tests_dir):
        with open(path, encoding="utf-8") as f:
            blob.append(f.read())
    blob = "\n".join(blob)
    missing = sorted({f"{n} (family {needle!r})" if needle != n else n
                      for n, needle in needles.items()
                      if needle not in blob})
    return missing


# ---------------------------------------------------------------------------
# runtime checks (CPU, tiny shapes)
# ---------------------------------------------------------------------------
# counter namespaces a steady-state compiled train step may touch; the
# reproducibility gate compares EXACTLY these so a background thread
# from an unrelated co-resident test cannot flake the check
_DETERMINISTIC_PREFIXES = ("program_store.train_step.", "cached_step.",
                           "spmd.", "sharding.", "metric.", "fused.",
                           "ndarray.", "faults.", "telemetry.",
                           "prefix.")


def _train_fixture():
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    class Net(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.d1 = nn.Dense(16, in_units=8, activation="relu")
            self.out = nn.Dense(4, in_units=16)

        def forward(self, x):
            return self.out(self.d1(x))

    net = Net()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01, "momentum": 0.9})
    step = trainer.compile_step(net, lambda n, x, y: ((n(x) - y) ** 2)
                                .mean())
    rng = onp.random.RandomState(0)
    x = mx.nd.array(rng.randn(8, 8).astype(onp.float32))
    y = mx.nd.array(rng.randn(8, 4).astype(onp.float32))
    return step, x, y


def _steady_delta(telemetry, step, x, y, n=3) -> Dict[str, object]:
    base = telemetry.snapshot()
    for _ in range(n):
        loss = step(x, y, batch_size=8)
    loss.asnumpy()
    kinds = telemetry.registered()
    return {k: v for k, v in telemetry.delta(base).items()
            if k.startswith(_DETERMINISTIC_PREFIXES)
            and kinds.get(k, {}).get("kind") == "cumulative"}


def check_deterministic_snapshot() -> List[str]:
    from mxnet_tpu import telemetry

    step, x, y = _train_fixture()
    for _ in range(2):                    # warm: trace + compile + AOT
        loss = step(x, y, batch_size=8)
    loss.asnumpy()
    if step.last_fallback_reason is not None:
        return [f"TrainStep fell back eager: {step.last_fallback_reason}"]
    d1 = _steady_delta(telemetry, step, x, y)
    d2 = _steady_delta(telemetry, step, x, y)
    if d1 != d2:
        diff = {k: (d1.get(k), d2.get(k))
                for k in set(d1) | set(d2) if d1.get(k) != d2.get(k)}
        return [f"steady-state TrainStep delta not reproducible: {diff}"]
    if d1.get("program_store.train_step.dispatches") != 3:
        return ["steady-state window did not dispatch 3 compiled steps: "
                f"{d1}"]
    return []


def check_chrome_trace() -> List[str]:
    """One compiled train step + one decode batch under the profiler ->
    the dump must be valid JSON with >= 3 span categories."""
    import numpy as onp

    from mxnet_tpu import profiler, serving_decode, telemetry

    fd, path = tempfile.mkstemp(suffix=".json")
    os.close(fd)
    try:
        profiler.set_config(filename=path)
        profiler.set_state("run")
        step, x, y = _train_fixture()
        tl = profiler.StepTimeline()
        with tl.phase("dispatch"):
            step(x, y, batch_size=8).asnumpy()
        tl.step()
        eng = serving_decode.GenerativeEngine(
            serving_decode.TinyCausalLM(),
            pool=serving_decode.PagePool(pages=64, page=8), max_rows=2)
        try:
            eng.generate(onp.asarray([3, 1, 4]), max_new_tokens=2)
        finally:
            eng.close()
        profiler.set_state("stop")
        out = profiler.dump()
        with open(out) as f:
            trace = json.load(f)          # must be valid JSON
        span_cats = {e["cat"] for e in trace["traceEvents"]
                     if e.get("ph") == "X"}
        want = {"train_step", "decode", "serving", "step_phase"}
        got = span_cats & want
        if len(got) < 3:
            return [f"chrome trace carries {len(got)} span categories "
                    f"{sorted(got)} (need >= 3 of {sorted(want)}); all "
                    f"cats: {sorted(span_cats)}"]
        n_spans = len(telemetry.spans())
        if n_spans < 3:
            return [f"telemetry span buffer has only {n_spans} records"]
    finally:
        os.unlink(path)
    return []


def check_routed_trace_ids() -> List[str]:
    """ISSUE-15 gate: drive a 2-replica router through a failover, a
    hedged dispatch, and a deadline shed — every ``shed`` / ``failover``
    / ``hedge`` event emitted on those routed requests must carry a
    non-empty ``trace_id``."""
    import time as _time
    from collections import deque as _deque

    from mxnet_tpu import faults, telemetry
    from mxnet_tpu import serving_decode as sd
    from mxnet_tpu.serving_router import ReplicaRouter

    model = sd.TinyCausalLM(vocab=31, d_model=16, n_layers=1, n_heads=2,
                            max_seq=48)
    params = model.init_params(0)
    engines, pools = [], []
    for i in range(2):
        pool = sd.PagePool(pages=32, page=4)
        eng = sd.GenerativeEngine(model, params=params, pool=pool,
                                  max_rows=2, name=f"trace_gate{i}")
        eng.warmup(max_len=8)
        engines.append(eng)
        pools.append(pool)
    router = ReplicaRouter(engines, breaker_errs=4,
                           breaker_cooldown_s=0.2, hedge_pctl=50)
    evs0 = telemetry.events()
    base_seq = evs0[-1]["seq"] if evs0 else 0
    failures: List[str] = []
    orig = engines[0].generate
    try:
        # failover: replica 0 fails its first dispatch
        calls = [0]

        def flaky(*a, **kw):
            calls[0] += 1
            if calls[0] == 1:
                raise faults.TransientFault("trace-gate failover")
            return orig(*a, **kw)

        engines[0].generate = flaky
        router.generate([1, 2, 3], max_new_tokens=3)
        engines[0].generate = orig
        # deadline shed: a 1us budget can never admit
        try:
            router.generate([1, 2, 3], max_new_tokens=3, deadline_us=1)
            failures.append("trace gate: 1us-budget request was not shed")
        except faults.ShedError:
            pass
        # hedge: prime the latency distribution, slow replica-side
        # dispatch past p50, fire once
        router._lat_dispatch = _deque((0.001,) * 16, maxlen=4096)

        def slow(*a, **kw):
            _time.sleep(0.25)
            return orig(*a, **kw)

        engines[0].generate = engines[1].generate = slow
        router.generate([1, 2, 3], max_new_tokens=2)
    finally:
        engines[0].generate = orig
        engines[1].generate = orig
        for eng in engines:
            eng.close()
        router.close()
    new = [e for e in telemetry.events() if e["seq"] > base_seq]
    for want in ("failover", "shed", "hedge"):
        of_kind = [e for e in new if e["kind"] == want]
        if not of_kind:
            failures.append(
                f"trace gate emitted no {want!r} event — the scenario "
                "drill broke, the stamping contract is unverified")
        bad = [e for e in of_kind if not e.get("trace_id")]
        if bad:
            failures.append(
                f"{len(bad)} routed {want!r} event(s) carry no "
                f"trace_id: {bad[:2]}")
    leaked = sum(p.in_use() for p in pools)
    if leaked:
        failures.append(f"trace gate leaked {leaked} KV pages")
    return failures


_MERGE_WORKER_FLAG = "--merge-worker"


def _merge_worker() -> int:
    """Gate-6 child: run the identical steady-state window and flush
    ONE shard whose snapshot is exactly the window's delta (counters
    reset after warmup, so cumulative == since-reset).  The window
    includes a shared-prefix decode hit so the ``prefix.*`` counters
    (ISSUE 16) prove they shard and merge like everything else."""
    from mxnet_tpu import engine, telemetry
    from mxnet_tpu import serving_decode as sd

    step, x, y = _train_fixture()
    for _ in range(2):                    # warm: trace + compile + AOT
        loss = step(x, y, batch_size=8)
    loss.asnumpy()
    # prefix-cache fixture: prime (compile + publish) BEFORE the reset
    # so the measured window sees a pure deterministic full hit
    model = sd.TinyCausalLM(vocab=29, d_model=16, n_layers=1,
                            n_heads=2, max_seq=48)
    eng = sd.GenerativeEngine(model, params=model.init_params(4),
                              pool=sd.PagePool(pages=32, page=4),
                              max_rows=2, name="merge_gate")
    shared = [3, 1, 4, 1, 5, 9, 2, 6]
    eng.generate(shared, max_new_tokens=2)
    telemetry.reset()
    for _ in range(3):
        loss = step(x, y, batch_size=8)
    loss.asnumpy()
    eng.generate(shared, max_new_tokens=2)    # full hit, zero prefill
    eng.close()
    engine.waitall()                      # flushes the flight recorder
    return 0


def check_prefix_zero_when_off() -> List[str]:
    """ISSUE-16 disabled-mode contract: with ``MXNET_PREFIX_CACHE=0`` a
    shared-prompt workload leaves every ``prefix.*`` counter untouched
    and parks nothing in the pool's resident cache — no hashing, no
    index, the pre-cache pool byte-for-byte (the knob is uncached, so
    the env flip takes effect immediately)."""
    from mxnet_tpu import serving_decode as sd
    from mxnet_tpu import telemetry

    prev = os.environ.get("MXNET_PREFIX_CACHE")
    os.environ["MXNET_PREFIX_CACHE"] = "0"
    try:
        model = sd.TinyCausalLM(vocab=29, d_model=16, n_layers=1,
                                n_heads=2, max_seq=48)
        pool = sd.PagePool(pages=32, page=4)
        eng = sd.GenerativeEngine(model, params=model.init_params(2),
                                  pool=pool, max_rows=2,
                                  name="prefix_off_gate")
        base = telemetry.snapshot()
        try:
            for _ in range(2):            # the same prompt twice: the
                eng.generate([5, 4, 3, 2, 1, 6, 7, 8],  # on-path would
                             max_new_tokens=3)          # full-hit here
        finally:
            eng.close()
        moved = {k: v for k, v in telemetry.delta(base).items()
                 if k.startswith("prefix.") and v}
        out: List[str] = []
        if moved:
            out.append("MXNET_PREFIX_CACHE=0 still moved prefix "
                       f"counters: {moved}")
        st = pool.stats()
        if st["cached"] != 0 or st["in_use"] != 0:
            out.append(f"off-path pool holds residue: {st}")
        return out
    finally:
        if prev is None:
            os.environ.pop("MXNET_PREFIX_CACHE", None)
        else:
            os.environ["MXNET_PREFIX_CACHE"] = prev


def check_merge_correctness() -> List[str]:
    """Two processes, identical windows: the shard snapshots must be
    byte-identical on the deterministic counters and the merge must
    equal exactly 2x one of them."""
    import subprocess

    from mxnet_tpu import telemetry

    d = tempfile.mkdtemp(prefix="check-telemetry-merge-")
    env = dict(os.environ)
    env["MXNET_TELEMETRY_DIR"] = d
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("MXNET_FAULT_PLAN", None)
    # the two processes are independent by construction — run them
    # concurrently so the gate pays one worker's wall clock, not two
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), _MERGE_WORKER_FLAG],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env) for _ in range(2)]
    for i, p in enumerate(procs):
        try:
            _out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            p.kill()
            return [f"merge worker {i} timed out"]
        if p.returncode != 0:
            return [f"merge worker {i} failed rc={p.returncode}: "
                    f"{err[-1000:]}"]
    merged = telemetry.merge(d)
    if len(merged["shards"]) != 2:
        return [f"expected 2 shards, merged {merged['shards']}"]
    windows = []
    for proc in merged["processes"]:
        sh = telemetry._read_shard(os.path.join(d, proc["shard"]))
        kinds = (sh["meta"] or {}).get("counter_kinds", {})
        snap = (sh["snapshot"] or {}).get("counters", {})
        windows.append({
            n: v for n, v in snap.items()
            if n.startswith(_DETERMINISTIC_PREFIXES)
            and kinds.get(n) == "cumulative"})
    if windows[0] != windows[1]:
        diff = {k: (windows[0].get(k), windows[1].get(k))
                for k in set(windows[0]) | set(windows[1])
                if windows[0].get(k) != windows[1].get(k)}
        return [f"identical windows produced different shard "
                f"snapshots: {diff}"]
    doubled = {n: 2 * v for n, v in windows[0].items()}
    got = {n: merged["counters"].get(n, 0) for n in doubled}
    if got != doubled:
        diff = {k: (doubled[k], got[k]) for k in doubled
                if doubled[k] != got.get(k)}
        return [f"2-process merge != 2x the single-process window "
                f"delta: {diff}"]
    if windows[0].get("program_store.train_step.dispatches") != 3:
        return ["merge worker window did not dispatch 3 compiled "
                f"steps: {windows[0]}"]
    return []


def main(root: str = None) -> int:
    root = root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    pkg = os.path.join(root, "mxnet_tpu")
    tests = os.path.join(root, "tests")
    failures: List[Tuple[str, List[str]]] = []

    accessors = collect_accessors(pkg)
    if not accessors:
        print("check_telemetry: no *_count accessors found under "
              f"{pkg} — regex or layout broke", file=sys.stderr)
        return 1

    raw = collect_raw_state(pkg)
    if raw:
        failures.append(("raw (non-registry) counter state", raw))

    # import every counter-declaring surface, then read the registry
    import mxnet_tpu  # noqa: F401
    from mxnet_tpu import (cached_step, engine, metric,  # noqa: F401
                           profiler, program_store, serving,
                           serving_decode, telemetry)
    from mxnet_tpu.contrib import quantization  # noqa: F401
    from mxnet_tpu.models import transformer_lm  # noqa: F401
    from mxnet_tpu.ops import nn as _ops_nn  # noqa: F401
    from mxnet_tpu.optimizer import fused  # noqa: F401
    from mxnet_tpu.parallel import sharding, spmd  # noqa: F401

    # the runtime checks run FIRST: they instantiate the per-instance
    # counter families (kv_pool, decode.engine, serving.router) the
    # registry checks then see
    failures.extend(("deterministic steady-state snapshot", [m])
                    for m in check_deterministic_snapshot())
    failures.extend(("chrome-trace export", [m])
                    for m in check_chrome_trace())
    failures.extend(("routed-request trace stamping", [m])
                    for m in check_routed_trace_ids())
    failures.extend(("prefix counters zero with the knob off", [m])
                    for m in check_prefix_zero_when_off())
    failures.extend(("two-process merge correctness", [m])
                    for m in check_merge_correctness())

    registry = telemetry.registered()
    unregistered = check_registered(accessors, registry)
    if unregistered:
        failures.append(("accessors with no registered counter",
                         unregistered))
    untested = check_tested(registry, tests)
    if untested:
        failures.append(("registered counters never named in a test",
                         untested))

    if failures:
        print("check_telemetry: FAILED", file=sys.stderr)
        for what, items in failures:
            print(f"  [{what}]", file=sys.stderr)
            for it in items:
                print(f"    {it}", file=sys.stderr)
        return 1
    print(f"check_telemetry: {len(accessors)} accessors, "
          f"{len(registry)} registered counters, deterministic "
          "steady-state delta, chrome trace >= 3 span categories, "
          "routed events trace-stamped, prefix counters 0 with the "
          "knob off, 2-process merge == 2x window")
    return 0


if __name__ == "__main__":
    if _MERGE_WORKER_FLAG in sys.argv:
        sys.exit(_merge_worker())
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
