"""The graftlint rule set — each rule is one invariant the codebase
already enforces by convention, now machine-checked at the source level.

Catalog (docs/STATIC_ANALYSIS.md is the user-facing version):

  env-discipline      every env read outside config.py goes through the
                      typed registry (config.declare/get) — otherwise
                      docs/ENV_VARS.md regeneration silently misses it
  thread-discipline   every threading.Thread started in mxnet_tpu/ is
                      either owned by an engine drainable or pragma'd
                      daemon-ok(<reason>) — engine.waitall()/preemption
                      drain must never silently miss a queue
  host-sync           no implicit device→host reads in the declared
                      hot-path modules outside pragma'd sync points —
                      the dispatch-budget discipline, statically
  fault-site          every faults.inject("<site>") literal appears in
                      docs/ROBUSTNESS.md's site table AND in a test
  counter-discipline  counter state lives in the telemetry registry:
                      raw counter globals/attrs and *_count += 1
                      increments outside the registry are forbidden
  donation            no read of a local after it was passed in a
                      donated position of a jit'd call in the same
                      scope (XLA may already have aliased the buffer)
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Finding, LintContext, Rule, Source, rule

# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------


def _dotted(node: ast.AST) -> Optional[str]:
    """'os.environ.get' for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _finding(rule_name: str, src: Source, node: ast.AST,
             message: str) -> Finding:
    return Finding(rule_name, src.rel, getattr(node, "lineno", 0),
                   getattr(node, "col_offset", 0), message)


# ---------------------------------------------------------------------------
# env-discipline
# ---------------------------------------------------------------------------

# os.environ.pop is a WRITE (save/restore paths use it); the rule is
# about reads — pop-as-read is rare enough to stay out of scope
_ENV_READ_CALLS = {"os.getenv", "os.environ.get"}


@rule
class EnvDiscipline(Rule):
    name = "env-discipline"
    doc = ("environment reads outside config.py must go through "
           "config.declare/get so the generated docs/ENV_VARS.md table "
           "is provably complete")

    def check(self, src: Source, ctx: LintContext) -> Iterable[Finding]:
        if src.rel.endswith("config.py"):
            return
        for node in src.nodes(ast.Call):
            name = _dotted(node.func)
            if name in _ENV_READ_CALLS:
                if src.disabled(self.name, node):
                    ctx.suppressed += 1
                    continue
                yield _finding(self.name, src, node,
                               f"raw environment read ({name}); declare "
                               "the knob in mxnet_tpu/config.py and read "
                               "it via config.get")
        for node in src.nodes(ast.Subscript):
            if not isinstance(node.ctx, ast.Load):
                continue
            if _dotted(node.value) == "os.environ":
                if src.disabled(self.name, node):
                    ctx.suppressed += 1
                    continue
                yield _finding(self.name, src, node,
                               "raw environment read (os.environ[...]); "
                               "declare the knob in mxnet_tpu/config.py "
                               "and read it via config.get")


# ---------------------------------------------------------------------------
# thread-discipline
# ---------------------------------------------------------------------------

def _scope_registers_drainable(src: Source, node: ast.AST) -> bool:
    """True when the enclosing class (or, for module-level threads, the
    enclosing function) contains a register_drainable(...) call — the
    thread then belongs to an object engine.waitall() drains."""
    scope = src.enclosing(node, ast.ClassDef) \
        or src.enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef)
    if scope is None:
        return False
    for n in ast.walk(scope):
        if isinstance(n, ast.Call):
            fn = _dotted(n.func) or ""
            if fn.split(".")[-1] == "register_drainable":
                return True
    return False


@rule
class ThreadDiscipline(Rule):
    name = "thread-discipline"
    doc = ("every threading.Thread started inside mxnet_tpu/ must belong "
           "to an engine drainable (register_drainable in the same "
           "class/function) or carry '# graftlint: daemon-ok(<reason>)' "
           "— otherwise engine.waitall()/the preemption drain can "
           "silently miss its queue")

    def check(self, src: Source, ctx: LintContext) -> Iterable[Finding]:
        from_imports = {
            a.asname or a.name
            for n in src.nodes(ast.ImportFrom) if n.module == "threading"
            for a in n.names}
        for node in src.nodes(ast.Call):
            fn = _dotted(node.func)
            is_thread = fn == "threading.Thread" or (
                fn == "Thread" and "Thread" in from_imports)
            if not is_thread:
                continue
            if src.daemon_ok(node) is not None:
                ctx.suppressed += 1
                continue
            if src.disabled(self.name, node):
                ctx.suppressed += 1
                continue
            if _scope_registers_drainable(src, node):
                continue
            yield _finding(
                self.name, src, node,
                "thread started outside the drainable registry; register "
                "the owning object with engine.register_drainable or "
                "pragma the line '# graftlint: daemon-ok(<reason>)'")


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

HOT_PATH_MODULES = (
    "mxnet_tpu/cached_step.py",
    "mxnet_tpu/serving_decode.py",
    "mxnet_tpu/engine.py",
    "mxnet_tpu/parallel/spmd.py",
)

_SYNC_ATTR_CALLS = {"asnumpy", "item", "tolist", "block_until_ready"}
_SYNC_FN_CALLS = {"np.asarray", "onp.asarray", "numpy.asarray",
                  "jax.device_get", "jax.block_until_ready"}
_SYNC_CASTS = {"float", "bool"}


@rule
class HostSync(Rule):
    name = "host-sync"
    doc = ("no implicit device→host reads (float()/bool() on arrays, "
           ".item()/.asnumpy()/.tolist(), np.asarray, device_get, "
           "block_until_ready) in the declared hot-path modules outside "
           "pragma'd sync points — the dispatch-budget discipline "
           "checked at the source, not just at runtime")

    def check(self, src: Source, ctx: LintContext) -> Iterable[Finding]:
        if src.rel not in HOT_PATH_MODULES:
            return
        for node in src.nodes(ast.Call):
            what = self._classify(node)
            if what is None:
                continue
            if src.disabled(self.name, node):
                ctx.suppressed += 1
                continue
            yield _finding(
                self.name, src, node,
                f"potential device→host sync ({what}) in a declared "
                "hot-path module; move it off the hot path or mark the "
                "deliberate sync point with '# graftlint: "
                "disable=host-sync -- <reason>'")

    @staticmethod
    def _classify(node: ast.Call) -> Optional[str]:
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _SYNC_ATTR_CALLS:
            return f".{node.func.attr}()"
        fn = _dotted(node.func)
        if fn in _SYNC_FN_CALLS:
            return fn
        # float(x)/bool(x) over a plain name/attribute — a device scalar
        # forced to host.  Constants (float("inf")) and call results
        # (bool(config.get(...))) are host-side already.
        if isinstance(node.func, ast.Name) \
                and node.func.id in _SYNC_CASTS and len(node.args) == 1 \
                and isinstance(node.args[0], (ast.Name, ast.Attribute)):
            return f"{node.func.id}()"
        return None


# ---------------------------------------------------------------------------
# fault-site
# ---------------------------------------------------------------------------

_SITE_NAME_RE = re.compile(r"^[a-z0-9_.]+$")


def collect_fault_sites(ctx: LintContext
                        ) -> Dict[str, List[Tuple[Source, ast.AST]]]:
    """site -> [(source, node)] for every ``inject("<site>")`` literal
    and ``site="<site>"`` keyword in the walked package (the
    check_fault_sites gate reuses this collection)."""
    sites = ctx.data.get("fault_sites")
    if sites is not None:
        return sites
    sites = {}
    for src in ctx.sources:
        for node in src.nodes(ast.Call):
            fn = _dotted(node.func) or ""
            if fn.split(".")[-1] == "inject" and node.args:
                s = _str_const(node.args[0])
                if s and _SITE_NAME_RE.match(s):
                    sites.setdefault(s, []).append((src, node))
            for kw in node.keywords:
                if kw.arg == "site":
                    s = _str_const(kw.value)
                    if s and _SITE_NAME_RE.match(s):
                        sites.setdefault(s, []).append((src, node))
    ctx.data["fault_sites"] = sites
    return sites


@rule
class FaultSite(Rule):
    name = "fault-site"
    doc = ("every faults.inject('<site>') / retry_call(site=...) literal "
           "must appear in docs/ROBUSTNESS.md's site table (documented "
           "recovery) and in at least one test (exercised recovery)")

    def finalize(self, ctx: LintContext) -> Iterable[Finding]:
        sites = collect_fault_sites(ctx)
        if not sites:
            return
        doc = ctx.doc_text("docs", "ROBUSTNESS.md")
        tests = ctx.tests_blob()
        for site, decls in sorted(sites.items()):
            src, node = decls[0]
            if src.disabled(self.name, node):
                ctx.suppressed += 1
                continue
            if f"`{site}`" not in doc:
                yield _finding(
                    self.name, src, node,
                    f"fault site '{site}' is missing from the "
                    "docs/ROBUSTNESS.md site table — document its "
                    "recovery before shipping it")
            if not re.search(r"""["']""" + re.escape(site) + r"""["']""",
                             tests):
                yield _finding(
                    self.name, src, node,
                    f"fault site '{site}' appears in no test under "
                    "tests/ — install a FaultPlan against it and assert "
                    "the documented recovery")


# ---------------------------------------------------------------------------
# counter-discipline
# ---------------------------------------------------------------------------

_RAW_GLOBAL_NAME = re.compile(r"^_[A-Z0-9_]*_COUNTS?$")
_COUNTERISH_ATTR = re.compile(r"^[a-z0-9][a-z0-9_]*_count$")
_ATTR_ALLOW = {"last_count", "step_count"}
_ACCESSOR_SKIP_PREFIXES = ("reset_",)


def collect_accessors(ctx: LintContext) -> Dict[str, Set[str]]:
    """Public ``def <base>_count(...)`` accessors: base -> {rel paths}.
    The check_telemetry gate cross-checks these against the runtime
    counter registry (shared-walk replacement for its old regex)."""
    acc = ctx.data.get("accessors")
    if acc is not None:
        return acc
    acc = {}
    for src in ctx.sources:
        for node in src.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            name = node.name
            if not name.endswith("_count") or name.startswith("_") \
                    or name.startswith(_ACCESSOR_SKIP_PREFIXES):
                continue
            acc.setdefault(name[: -len("_count")], set()).add(src.rel)
    ctx.data["accessors"] = acc
    return acc


def collect_raw_state(ctx: LintContext) -> List[Tuple[Source, ast.AST, str]]:
    """Raw (non-registry) counter state: module globals ``_X_COUNT = 0``
    and public ``self.x_count = <n>`` attributes."""
    raw = ctx.data.get("raw_counter_state")
    if raw is not None:
        return raw
    raw = []
    for src in ctx.sources:
        for node in src.nodes(ast.Assign):
            if not (isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, (int, float))
                    and not isinstance(node.value.value, bool)):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) \
                        and _RAW_GLOBAL_NAME.match(tgt.id) \
                        and isinstance(src.parent(node), ast.Module):
                    raw.append((src, node, f"{tgt.id} = ..."))
                elif isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self" \
                        and _COUNTERISH_ATTR.match(tgt.attr) \
                        and tgt.attr not in _ATTR_ALLOW:
                    raw.append((src, node, f"self.{tgt.attr} = ..."))
    ctx.data["raw_counter_state"] = raw
    return raw


@rule
class CounterDiscipline(Rule):
    name = "counter-discipline"
    doc = ("counter state must live in the telemetry registry "
           "(telemetry.counter / CounterGroup): raw counter globals, "
           "public self.*_count attributes, and *_count += increments "
           "outside the registry are invisible to snapshot()/delta() "
           "and the CI determinism gate")

    def check(self, src: Source, ctx: LintContext) -> Iterable[Finding]:
        for s, node, what in collect_raw_state(ctx):
            if s is not src:
                continue
            if src.disabled(self.name, node):
                ctx.suppressed += 1
                continue
            yield _finding(
                self.name, src, node,
                f"raw counter state ({what}); declare it with "
                "telemetry.counter/CounterGroup so it rides "
                "snapshot()/delta()")
        for node in src.nodes(ast.AugAssign):
            if not isinstance(node.op, ast.Add):
                continue
            tgt = node.target
            name = None
            if isinstance(tgt, ast.Name) \
                    and _RAW_GLOBAL_NAME.match(tgt.id):
                name = tgt.id
            elif isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self" \
                    and _COUNTERISH_ATTR.match(tgt.attr) \
                    and tgt.attr not in _ATTR_ALLOW:
                name = f"self.{tgt.attr}"
            if name is None:
                continue
            if src.disabled(self.name, node):
                ctx.suppressed += 1
                continue
            yield _finding(
                self.name, src, node,
                f"raw counter increment ({name} += ...); go through the "
                "telemetry registry (Counter.inc / CounterGroup.inc)")

    def collect(self, src: Source, ctx: LintContext) -> None:
        collect_accessors(ctx)   # shared with the check_telemetry gate


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------

_JIT_NAMES = {"jit", "pjit"}


def _jit_donated_positions(call: ast.Call) -> Optional[List[int]]:
    """For ``jax.jit(f, donate_argnums=...)``-style calls: the donated
    positional indices (literal ints only), else None."""
    fn = _dotted(call.func) or ""
    if fn.split(".")[-1] not in _JIT_NAMES:
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return [v.value]
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for el in v.elts:
                if isinstance(el, ast.Constant) \
                        and isinstance(el.value, int):
                    out.append(el.value)
                else:
                    return None     # dynamic — can't reason statically
            return out
    return None


@rule
class DonationSafety(Rule):
    name = "donation"
    doc = ("a local passed in a donated position of a jit'd call is "
           "DEAD — XLA may alias its buffer into the output; any later "
           "read in the same scope sees poisoned memory on device")

    def check(self, src: Source, ctx: LintContext) -> Iterable[Finding]:
        # cheap pre-filter: the per-function flow analysis below is the
        # one expensive pass in the rule set — only run it on files
        # that mention donation at all
        if "donate_argnums" not in src.text:
            return
        for fn in src.nodes(ast.FunctionDef, ast.AsyncFunctionDef):
            # only consider this function's own statements (nested
            # function bodies analyze separately)
            nested = {id(sub) for child in ast.walk(fn)
                      if isinstance(child, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))
                      and child is not fn
                      for sub in ast.walk(child) if sub is not child}
            jitted: Dict[str, List[int]] = {}
            # var -> end line of the call that donated it
            dead: Dict[str, int] = {}
            events: List[Tuple[int, int, object]] = []
            for node in ast.walk(fn):
                if id(node) in nested:
                    continue
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    pos = _jit_donated_positions(node.value)
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            if pos:
                                jitted[tgt.id] = pos
                            else:
                                jitted.pop(tgt.id, None)
                # same-line ordering: donating calls (0) kill before
                # assignments (1) revive before loads (2) are judged —
                # so `x = g(x)` leaves x alive (it holds the result)
                if isinstance(node, ast.Call):
                    prio = 0
                elif isinstance(node, (ast.Assign, ast.AugAssign,
                                       ast.For, ast.withitem)):
                    prio = 1
                else:
                    prio = 2
                events.append((getattr(node, "lineno", 0), prio, node))
            # second pass in line order: donating calls kill names,
            # reassignment revives them, later loads get flagged
            for _, _, node in sorted(events, key=lambda e: (e[0], e[1])):
                if isinstance(node, ast.Call):
                    pos = None
                    if isinstance(node.func, ast.Name) \
                            and node.func.id in jitted:
                        pos = jitted[node.func.id]
                    elif isinstance(node.func, ast.Call):
                        pos = _jit_donated_positions(node.func)
                    if pos:
                        end = getattr(node, "end_lineno", node.lineno)
                        for p in pos:
                            if p < len(node.args) and isinstance(
                                    node.args[p], ast.Name):
                                dead[node.args[p].id] = end
                if isinstance(node, (ast.Assign, ast.AugAssign, ast.For,
                                     ast.withitem)):
                    for t in ast.walk(node):
                        if isinstance(t, ast.Name) \
                                and isinstance(t.ctx, ast.Store):
                            dead.pop(t.id, None)
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Load) \
                        and node.id in dead \
                        and node.lineno > dead[node.id]:
                    if src.disabled(self.name, node):
                        ctx.suppressed += 1
                        dead.pop(node.id)
                        continue
                    yield _finding(
                        self.name, src, node,
                        f"'{node.id}' was donated to a jit'd call (line "
                        f"{dead[node.id]}) and read afterwards — the "
                        "buffer may already be aliased into the output; "
                        "keep a copy or stop donating it")
                    dead.pop(node.id)   # one finding per donation
