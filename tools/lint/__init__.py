"""graftlint — AST-based invariant linter for the ``mxnet_tpu`` runtime.

The reference framework's ThreadedEngine made concurrency safe *by
construction*: every mutation flowed through a dependency-tracking
scheduler, so "did you register your async work?" was not a question a
reviewer had to ask.  Our JAX port re-introduced free-threaded host code
(prefetcher, async checkpoint writer, serving stager/dispatcher,
telemetry bus, preemption drain) whose safety invariants lived only in
prose (docs/ROBUSTNESS.md, docs/OBSERVABILITY.md) and in disjoint
regex-based CI gates.  graftlint makes those invariants *machine
checkable at the source level*: one AST walk over ``mxnet_tpu/``, a
registered rule set over it, pragma suppressions with reasons, a
checked-in baseline for grandfathered findings (target: empty), and
machine-readable JSON output — plus a runtime lock-order detector
(``tools.lint.runtime``) that records the cross-thread lock-acquisition
graph over a real train-step + decode + preemption-drain scenario and
fails on ordering cycles.

Entry points::

    python -m tools.lint --all          # static rules + runtime detector
    python -m tools.lint --static       # static rules only
    python -m tools.lint --runtime      # lock-order scenario (fresh
                                        # process; import nothing first)

See docs/STATIC_ANALYSIS.md for the rule catalog, pragma syntax,
baseline policy, and the add-a-rule checklist.
"""
from .core import (Finding, LintContext, Source, RULES, rule,  # noqa: F401
                   load_baseline, run_static, walk_package)
from . import rules as _rules  # noqa: F401  (registers the rule set)

__all__ = ["Finding", "LintContext", "Source", "RULES", "rule",
           "run_static", "walk_package", "load_baseline"]
