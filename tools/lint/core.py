"""graftlint core: one parse per file, a rule registry over the shared
walk, pragma suppression, and the baseline.

Design (the Relay argument, arXiv:1810.00952, applied to our own
runtime): make the program structure explicit ONCE — ``Source`` parses a
file into an AST with a by-node-type index, parent links, and the pragma
map — and every invariant becomes a small pure function over that
structure instead of a bespoke regex scanner.  Rules implement either
``check(src, ctx)`` (per-file) or ``collect(src, ctx)`` +
``finalize(ctx)`` (cross-file: fault sites vs the docs table, counter
accessors vs the registry).

Suppression pragmas (always carry a reason — a bare switch-off is a
review smell the syntax refuses):

    # graftlint: disable=<rule>[,<rule>...] -- <reason>
    # graftlint: daemon-ok(<reason>)            (thread-discipline only)

A pragma suppresses findings for any node whose line span touches the
pragma line, so multi-line calls annotate naturally.  Suppressed
findings are counted (``suppressed`` in the JSON report) — silence is
visible, never free.

Baseline: ``tools/lint/baseline.json`` holds grandfathered finding keys
(``rule::path::message``, line-number free so edits don't churn it).
The shipped baseline is EMPTY for ``mxnet_tpu/`` — every historical
finding was either fixed or pragma'd with a reason in the PR that
introduced the linter; the file exists so a future emergency landing
has a documented escape hatch (see docs/STATIC_ANALYSIS.md).
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Set

__all__ = ["Finding", "Source", "LintContext", "RULES", "rule",
           "walk_package", "run_static", "load_baseline", "PRAGMA_RE"]

# daemon-ok's closing paren is optional so reasons may wrap onto the
# next comment line
PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*"
    r"(?:disable=(?P<rules>[a-z0-9_,-]+)(?:\s*--\s*(?P<reason>.*))?"
    r"|daemon-ok\((?P<daemon_reason>[^)\n]*)\)?)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""
    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    col: int
    message: str

    @property
    def key(self) -> str:
        """Baseline identity: line-free so unrelated edits above a
        grandfathered finding don't churn the baseline file."""
        return f"{self.rule}::{self.path}::{self.message}"

    def to_json(self) -> Dict[str, Any]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: " \
               f"[{self.rule}] {self.message}"


class Source:
    """One parsed file: AST + node index + parent links + pragmas."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        # one walk builds everything rules need: nodes grouped by type
        # and child -> parent links (enclosing-scope queries)
        self._by_type: Dict[type, List[ast.AST]] = {}
        self._parent: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            self._by_type.setdefault(type(node), []).append(node)
            for child in ast.iter_child_nodes(node):
                self._parent[child] = node
        # pragma maps: line -> disabled rule set / daemon-ok reason
        self.disabled_at: Dict[int, Set[str]] = {}
        self.daemon_ok_at: Dict[int, str] = {}
        for i, line in enumerate(self.lines, start=1):
            if "graftlint" not in line:
                continue
            m = PRAGMA_RE.search(line)
            if not m:
                continue
            if m.group("rules"):
                self.disabled_at.setdefault(i, set()).update(
                    r.strip() for r in m.group("rules").split(",") if r)
            else:
                self.daemon_ok_at[i] = (m.group("daemon_reason")
                                        or "").strip()

    # -- queries ---------------------------------------------------------
    def nodes(self, *types: type) -> Iterable[ast.AST]:
        for t in types:
            yield from self._by_type.get(t, ())

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parent.get(node)

    def enclosing(self, node: ast.AST, *types: type) -> Optional[ast.AST]:
        """Nearest ancestor of one of ``types`` (e.g. the enclosing
        FunctionDef / ClassDef), or None."""
        cur = self._parent.get(node)
        while cur is not None:
            if isinstance(cur, types):
                return cur
            cur = self._parent.get(cur)
        return None

    def _span(self, node: ast.AST) -> range:
        """The line span pragmas apply over: the ENCLOSING STATEMENT's
        lines (a flagged call may sit on a continuation line), extended
        upward through the contiguous comment block immediately above —
        pragmas with long reasons sit on their own lines."""
        stmt: ast.AST = node
        cur: Optional[ast.AST] = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self._parent.get(cur)
        if cur is not None:
            stmt = cur
        lo = getattr(stmt, "lineno", 0)
        hi = getattr(stmt, "end_lineno", lo) or lo
        hi = max(hi, getattr(node, "end_lineno", 0) or 0)
        while lo > 1 and self.lines[lo - 2].lstrip().startswith("#"):
            lo -= 1
        return range(lo, hi + 1)

    def disabled(self, rule_name: str, node: ast.AST) -> bool:
        """True when a ``disable=`` pragma touches the node's line span
        (or the span's first line ends with one — decorators excluded)."""
        for ln in self._span(node):
            rules = self.disabled_at.get(ln)
            if rules and (rule_name in rules or "all" in rules):
                return True
        return False

    def daemon_ok(self, node: ast.AST) -> Optional[str]:
        """The ``daemon-ok(<reason>)`` pragma reason touching the node's
        span, if any (empty reasons don't count — the syntax demands a
        justification)."""
        for ln in self._span(node):
            reason = self.daemon_ok_at.get(ln)
            if reason:
                return reason
        return None


@dataclass
class LintContext:
    """Shared cross-file state for one lint run."""
    root: str
    pkg_rel: str = "mxnet_tpu"
    sources: List[Source] = field(default_factory=list)
    data: Dict[str, Any] = field(default_factory=dict)
    suppressed: int = 0

    def doc_text(self, *rel: str) -> str:
        path = os.path.join(self.root, *rel)
        try:
            with open(path, encoding="utf-8") as f:
                return f.read()
        except OSError:
            return ""

    def tests_blob(self) -> str:
        """Concatenated text of tests/ (cached) — the "does a test name
        this literal" corpus shared by the fault-site and counter
        rules."""
        blob = self.data.get("_tests_blob")
        if blob is None:
            parts = []
            for path in _py_files(os.path.join(self.root, "tests")):
                try:
                    with open(path, encoding="utf-8") as f:
                        parts.append(f.read())
                except OSError:
                    pass
            blob = self.data["_tests_blob"] = "\n".join(parts)
        return blob


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

class Rule:
    """Base rule.  Subclasses set ``name``/``doc`` and implement
    ``check`` (per-file) and/or ``collect`` + ``finalize``
    (cross-file)."""

    name: str = ""
    doc: str = ""

    def check(self, src: Source, ctx: LintContext) -> Iterable[Finding]:
        return ()

    def collect(self, src: Source, ctx: LintContext) -> None:
        pass

    def finalize(self, ctx: LintContext) -> Iterable[Finding]:
        return ()


RULES: Dict[str, Rule] = {}


def rule(cls: Callable[[], Rule]) -> Callable[[], Rule]:
    """Class decorator: instantiate and register."""
    inst = cls()
    if not inst.name:
        raise ValueError(f"rule {cls!r} has no name")
    if inst.name in RULES:
        raise ValueError(f"duplicate rule {inst.name!r}")
    RULES[inst.name] = inst
    return cls


# ---------------------------------------------------------------------------
# walking + running
# ---------------------------------------------------------------------------

def _py_files(root: str) -> Iterable[str]:
    for dirpath, dirs, files in os.walk(root):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


# parsed-tree cache: several gates walk the same unchanged tree in one
# process (check_fault_sites, check_telemetry x2, the suite's real-tree
# run).  Keyed by (root, pkg) and VALIDATED against a per-file
# (path, mtime_ns, size) snapshot — an edited file invalidates the
# entry, so interactive relint stays correct.  Source objects are
# immutable after construction; every hit still gets a FRESH
# LintContext (rules mutate ctx.data / ctx.suppressed).
_WALK_CACHE: Dict[tuple, tuple] = {}


def _tree_sig(pkg_dir: str) -> tuple:
    sig = []
    for path in _py_files(pkg_dir):
        try:
            st = os.stat(path)
            sig.append((path, st.st_mtime_ns, st.st_size))
        except OSError:
            sig.append((path, -1, -1))
    return tuple(sig)


def walk_package(root: str, pkg_rel: str = "mxnet_tpu") -> LintContext:
    """Parse every ``.py`` under ``root/pkg_rel`` once into a
    LintContext.  A file that fails to parse becomes a synthetic
    ``parse-error`` finding downstream (stored in ctx.data)."""
    root = os.path.abspath(root)
    pkg_dir = os.path.join(root, pkg_rel)
    key = (root, pkg_rel)
    sig = _tree_sig(pkg_dir)
    hit = _WALK_CACHE.get(key)
    if hit is not None and hit[0] == sig:
        sources, errors = hit[1], hit[2]
    else:
        sources, errors = [], []
        for path in _py_files(pkg_dir):
            rel = os.path.relpath(path, root)
            try:
                with open(path, encoding="utf-8") as f:
                    text = f.read()
                sources.append(Source(path, rel, text))
            except (SyntaxError, UnicodeDecodeError, OSError) as e:
                line = getattr(e, "lineno", 0) or 0
                errors.append(
                    Finding("parse-error", rel.replace(os.sep, "/"),
                            line, 0, f"cannot lint: {e}"))
        _WALK_CACHE[key] = (sig, sources, errors)
    ctx = LintContext(root=root, pkg_rel=pkg_rel)
    ctx.sources = list(sources)
    ctx.data["parse_errors"] = list(errors)
    return ctx


def run_static(root: str, pkg_rel: str = "mxnet_tpu",
               only: Optional[Set[str]] = None,
               disable: Set[str] = frozenset(),
               ctx: Optional[LintContext] = None
               ) -> tuple[List[Finding], LintContext]:
    """Run the registered static rules over one shared walk.  Returns
    (findings, ctx); pragma-suppressed findings are dropped (counted in
    ``ctx.suppressed``), baseline filtering is the caller's job (CLI)."""
    if ctx is None:
        ctx = walk_package(root, pkg_rel)
    active = [r for n, r in sorted(RULES.items())
              if (only is None or n in only) and n not in disable]
    findings: List[Finding] = list(ctx.data.get("parse_errors", ()))
    for r in active:
        for src in ctx.sources:
            r.collect(src, ctx)
    for r in active:
        for src in ctx.sources:
            for f in r.check(src, ctx):
                findings.append(f)
    for r in active:
        findings.extend(r.finalize(ctx))
    # pragma suppression happens inside rules (they hold the node); any
    # finding reaching here is live.  Deterministic order:
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings, ctx


def load_baseline(path: Optional[str] = None) -> Set[str]:
    """Grandfathered finding keys (see Finding.key)."""
    if path is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "baseline.json")
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return set()
    return set(data.get("findings", []))
