"""graftlint CLI.

    python -m tools.lint --all            # static + runtime lock-order
    python -m tools.lint --static         # static rules only
    python -m tools.lint --runtime        # lock-order scenario (must be
                                          # a fresh process; --all
                                          # spawns one)
    python -m tools.lint --list-rules
    python -m tools.lint --rules env-discipline,host-sync
    python -m tools.lint --disable donation
    python -m tools.lint --all --json benchmark/artifacts/graftlint.json

Exit code 0 = no non-baseline findings (and, when the runtime layer
ran, an acyclic lock-acquisition graph); 1 otherwise.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Any, Dict, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.lint import RULES, load_baseline, run_static  # noqa: E402
from tools.lint import runtime as _runtime  # noqa: E402


def _run_runtime_subprocess(root: str, timeout: float) -> Dict[str, Any]:
    """The scenario needs module-level locks instrumented, i.e. a
    process that enables instrumentation BEFORE importing mxnet_tpu —
    spawn one."""
    env = dict(os.environ)
    env["MXNET_LINT_RUNTIME"] = "1"
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--runtime", "--json", "-"],
        capture_output=True, text=True, timeout=timeout, cwd=root,
        env=env)
    if proc.returncode not in (0, 1):
        return {"error": f"runtime scenario exited {proc.returncode}",
                "stderr": proc.stderr[-4000:]}
    try:
        # --json - prints the report as the last stdout line
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": "runtime scenario produced no JSON report",
                "stdout": proc.stdout[-2000:],
                "stderr": proc.stderr[-4000:]}


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="graftlint: AST invariant linter + runtime "
                    "lock-order detector for mxnet_tpu")
    p.add_argument("--all", action="store_true",
                   help="static rules + runtime lock-order scenario")
    p.add_argument("--static", action="store_true",
                   help="static rules only")
    p.add_argument("--runtime", action="store_true",
                   help="runtime lock-order scenario (fresh process "
                        "only: nothing may have imported mxnet_tpu)")
    p.add_argument("--rules", default=None,
                   help="comma list: run only these rules")
    p.add_argument("--disable", default="",
                   help="comma list: skip these rules")
    p.add_argument("--root", default=_REPO, help="repo root")
    p.add_argument("--pkg", default="mxnet_tpu",
                   help="package dir (relative to root) to lint")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default tools/lint/baseline.json)")
    p.add_argument("--json", dest="json_path", default=None,
                   help="write the machine-readable report here "
                        "('-' = stdout)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--runtime-timeout", type=float, default=600.0)
    a = p.parse_args(argv)

    if a.list_rules:
        for name, r in sorted(RULES.items()):
            print(f"{name:<20} {r.doc}")
        return 0

    if not (a.all or a.static or a.runtime):
        a.static = True        # bare invocation = static lint

    report: Dict[str, Any] = {"root": a.root, "pkg": a.pkg}
    rc = 0

    if a.all or a.static:
        only = set(a.rules.split(",")) if a.rules else None
        disable = {r for r in a.disable.split(",") if r}
        unknown = ((only or set()) | disable) - set(RULES)
        if unknown:
            print(f"unknown rule(s): {sorted(unknown)}; see --list-rules",
                  file=sys.stderr)
            return 2
        findings, ctx = run_static(a.root, a.pkg, only=only,
                                   disable=disable)
        baseline = load_baseline(a.baseline)
        live = [f for f in findings if f.key not in baseline]
        grandfathered = len(findings) - len(live)
        report["static"] = {
            "findings": [f.to_json() for f in live],
            "grandfathered": grandfathered,
            "suppressed": ctx.suppressed,
            "files": len(ctx.sources),
            "rules": sorted((only or set(RULES)) - disable),
        }
        for f in live:
            print(str(f), file=sys.stderr)
        if live:
            rc = 1
        print(f"graftlint static: {len(ctx.sources)} files, "
              f"{len(report['static']['rules'])} rules, "
              f"{len(live)} findings ({grandfathered} baselined, "
              f"{ctx.suppressed} pragma-suppressed)")

    if a.runtime and not a.all:
        # in-process scenario: only valid in a fresh interpreter
        try:
            rt = _runtime.run_scenario()
        except RuntimeError as e:
            print(f"graftlint runtime: {e}", file=sys.stderr)
            return 2
        report["runtime"] = rt
    elif a.all:
        rt = _run_runtime_subprocess(a.root, a.runtime_timeout)
        report["runtime"] = rt

    rt = report.get("runtime")
    if rt is not None:
        if rt.get("error"):
            print(f"graftlint runtime: FAILED — {rt['error']}",
                  file=sys.stderr)
            if rt.get("stderr"):
                print(rt["stderr"], file=sys.stderr)
            rc = 1
        else:
            cycles = rt.get("cycles", [])
            print(f"graftlint runtime: {rt['locks']} locks, "
                  f"{rt['acquisitions']} acquisitions, "
                  f"{len(rt['edges'])} order edges, "
                  f"{len(cycles)} cycles")
            if cycles:
                print("LOCK-ORDER CYCLES (potential deadlock):",
                      file=sys.stderr)
                for c in cycles:
                    print("  " + " <-> ".join(c), file=sys.stderr)
                rc = 1

    if a.json_path:
        blob = json.dumps(report, indent=2, sort_keys=True)
        if a.json_path == "-":
            print(blob if not a.runtime or a.all
                  else json.dumps(report.get("runtime", report)))
        else:
            os.makedirs(os.path.dirname(os.path.abspath(a.json_path)),
                        exist_ok=True)
            with open(a.json_path, "w") as f:
                f.write(blob + "\n")
    return rc


if __name__ == "__main__":
    sys.exit(main())
