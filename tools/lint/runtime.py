"""Runtime concurrency-hazard layer: a deterministic lock-order
deadlock detector for the async runtime's lock set.

PRs 5-11 accreted free-threaded host code — prefetcher, serving
stager/dispatcher, decode scheduler, async checkpoint writer, telemetry
bus, preemption drain — each with its own locks.  A deadlock between
them needs two threads to acquire the same two locks in opposite order;
that *ordering* property is checkable without ever hitting the unlucky
interleaving: instrument every ``threading.Lock``/``RLock`` acquisition,
record the directed graph "lock B acquired while lock A was held", and
fail on cycles.  The graph is deterministic for a deterministic
scenario, so the check regresses like any other gate.

Usage (the knob ``MXNET_LINT_RUNTIME=1`` gates instrumentation; off by
default — production processes pay zero overhead):

    python -m tools.lint --runtime      # fresh process: instruments
                                        # BEFORE importing mxnet_tpu,
                                        # runs one compiled train step +
                                        # one decode batch + one
                                        # preemption drain, reports

``enable()`` must run before the locks you care about are created —
module-level locks (telemetry registry, preemption state, spmd init)
are born at import, which is why the CLI spawns a fresh process for the
scenario.  Instance-level detection: edges connect lock *instances*
(two per-instance locks from one creation site never false-cycle), but
cycles are reported by creation *site* (file:line), which is what a
human fixes.
"""
from __future__ import annotations

import os
import sys
import threading
import traceback
from typing import Any, Dict, List, Optional, Set, Tuple

__all__ = ["LockOrderRecorder", "enable", "disable", "recorder",
           "run_scenario", "instrumentation_requested"]

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the real constructors, captured once at import (before any patching)
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock


def instrumentation_requested() -> bool:
    """The MXNET_LINT_RUNTIME knob, read raw: this runs BEFORE
    mxnet_tpu (and its config registry) may be imported — that ordering
    is the whole point.  The knob is declared in mxnet_tpu/config.py so
    docs/ENV_VARS.md documents it."""
    return os.environ.get("MXNET_LINT_RUNTIME", "0").strip() in (
        "1", "true", "on")


def _creation_site() -> str:
    """file:line of the frame that created the lock, skipping threading
    internals and this module; repo paths are relativized so reports are
    stable across checkouts."""
    for frame in traceback.extract_stack()[-3::-1]:
        fname = frame.filename
        base = os.path.basename(fname)
        if base == "threading.py" or fname == __file__:
            continue
        if fname.startswith(_REPO):
            fname = os.path.relpath(fname, _REPO)
        return f"{fname}:{frame.lineno}"
    return "<unknown>"


class _TLS(threading.local):
    def __init__(self):
        self.held: List["_InstrumentedLock"] = []


class LockOrderRecorder:
    """Collects the cross-thread lock-acquisition graph."""

    def __init__(self):
        self.active = False
        self._tls = _TLS()
        self._graph_lock = _REAL_LOCK()   # leaf: never held while
        # acquiring an instrumented lock
        # instance-id -> creation site
        self.sites: Dict[int, str] = {}
        # (holder-id, acquired-id) -> example (holder site, acquired
        # site, thread name)
        self.edges: Dict[Tuple[int, int], Tuple[str, str, str]] = {}
        self.acquisitions = 0

    # -- wrapper callbacks ----------------------------------------------
    def on_create(self, lock: "_InstrumentedLock") -> None:
        with self._graph_lock:
            self.sites[lock.uid] = lock.site

    def on_acquire(self, lock: "_InstrumentedLock") -> None:
        held = self._tls.held
        if held:
            holder = held[-1]
            if holder.uid != lock.uid:
                edge = (holder.uid, lock.uid)
                with self._graph_lock:
                    self.acquisitions += 1
                    if edge not in self.edges:
                        self.edges[edge] = (
                            holder.site, lock.site,
                            threading.current_thread().name)
        else:
            with self._graph_lock:
                self.acquisitions += 1
        held.append(lock)

    def on_release(self, lock: "_InstrumentedLock") -> None:
        held = self._tls.held
        # locks release LIFO in the common case, but out-of-order
        # release is legal — remove the newest matching entry
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    # -- analysis --------------------------------------------------------
    def cycles(self) -> List[List[str]]:
        """Lock-order cycles: strongly connected components of size > 1
        in the instance graph (self-edges can't exist — reacquiring the
        same instance records no edge), reported as sorted creation
        sites.  Iterative Tarjan — complete (a cycle exists iff some
        SCC has > 1 node) and linear in the graph size."""
        adj: Dict[int, Set[int]] = {}
        for a, b in self.edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        index: Dict[int, int] = {}
        low: Dict[int, int] = {}
        on_stack: Set[int] = set()
        stack: List[int] = []
        counter = [0]
        sccs: List[List[int]] = []

        for root in adj:
            if root in index:
                continue
            work: List[Tuple[int, Any]] = [(root, iter(adj[root]))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for nxt in it:
                    if nxt not in index:
                        index[nxt] = low[nxt] = counter[0]
                        counter[0] += 1
                        stack.append(nxt)
                        on_stack.add(nxt)
                        work.append((nxt, iter(adj[nxt])))
                        advanced = True
                        break
                    if nxt in on_stack:
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        v = stack.pop()
                        on_stack.discard(v)
                        comp.append(v)
                        if v == node:
                            break
                    if len(comp) > 1:
                        sccs.append(comp)
        return [sorted({self.sites.get(u, "?") for u in comp})
                for comp in sccs]

    def report(self) -> Dict[str, Any]:
        site_edges = sorted({
            (ha, hb, t) for (_, _), (ha, hb, t) in self.edges.items()})
        return {
            "locks": len(self.sites),
            "acquisitions": self.acquisitions,
            "edges": [{"held": a, "acquired": b, "thread": t}
                      for a, b, t in site_edges],
            "cycles": self.cycles(),
        }


class _InstrumentedLock:
    """Wraps a real Lock/RLock; records successful acquisitions.  After
    ``disable()`` the wrapper stays functional (locks outlive the
    recording window) but stops recording."""

    _UID = [0]
    _UID_LOCK = _REAL_LOCK()

    def __init__(self, inner, recorder: "LockOrderRecorder"):
        self._inner = inner
        self._recorder = recorder
        with self._UID_LOCK:
            self._UID[0] += 1
            self.uid = self._UID[0]
        self.site = _creation_site()
        recorder.on_create(self)

    def acquire(self, *args, **kwargs):
        ok = self._inner.acquire(*args, **kwargs)
        if ok and self._recorder.active:
            self._recorder.on_acquire(self)
        return ok

    def release(self):
        if self._recorder.active:
            self._recorder.on_release(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __getattr__(self, name):
        # RLock internals the Condition protocol needs (_is_owned,
        # _acquire_restore, _release_save) delegate to the inner lock;
        # cv.wait() windows therefore bypass recording, which is safe:
        # a waiting thread acquires nothing
        return getattr(self._inner, name)

    def __repr__(self):
        return f"<graftlint {self._inner!r} @ {self.site}>"


_RECORDER: Optional[LockOrderRecorder] = None


def recorder() -> Optional[LockOrderRecorder]:
    return _RECORDER


def enable() -> LockOrderRecorder:
    """Patch threading.Lock/RLock with instrumented factories.  Locks
    created from here on are recorded; locks created earlier are not —
    call before importing the modules under observation."""
    global _RECORDER
    if _RECORDER is not None and _RECORDER.active:
        return _RECORDER
    rec = LockOrderRecorder()
    rec.active = True
    _RECORDER = rec

    def make_lock():
        return _InstrumentedLock(_REAL_LOCK(), rec)

    def make_rlock():
        return _InstrumentedLock(_REAL_RLOCK(), rec)

    threading.Lock = make_lock          # type: ignore[assignment]
    threading.RLock = make_rlock        # type: ignore[assignment]
    return rec


def disable() -> Optional[LockOrderRecorder]:
    """Restore the real constructors and stop recording.  Existing
    wrapped locks keep working (pass-through)."""
    global _RECORDER
    threading.Lock = _REAL_LOCK         # type: ignore[assignment]
    threading.RLock = _REAL_RLOCK       # type: ignore[assignment]
    rec = _RECORDER
    if rec is not None:
        rec.active = False
    return rec


# ---------------------------------------------------------------------------
# the gate scenario
# ---------------------------------------------------------------------------

def run_scenario() -> Dict[str, Any]:
    """The acceptance scenario: one compiled train step window + one
    decode batch + one preemption drain, recorded under instrumentation.
    MUST run in a process that has not imported mxnet_tpu yet (the CLI
    spawns one); module-level locks are then all instrumented.

    Returns the recorder report plus scenario markers; ``cycles`` empty
    == acyclic acquisition graph == the gate passes."""
    if "mxnet_tpu" in sys.modules:
        raise RuntimeError(
            "run_scenario() needs a fresh process: mxnet_tpu is already "
            "imported, its module-level locks escaped instrumentation "
            "(use `python -m tools.lint --runtime`)")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if not instrumentation_requested():
        # the scenario IS the lint harness: reflect that in the knob so
        # subprocesses / config introspection see instrumentation is on
        os.environ["MXNET_LINT_RUNTIME"] = "1"
    rec = enable()
    try:
        import numpy as onp

        import mxnet_tpu as mx
        from mxnet_tpu import engine, preemption, serving_decode
        from mxnet_tpu import gluon
        from mxnet_tpu.gluon import nn

        # -- one compiled train step (check_telemetry's fixture) --------
        class Net(gluon.HybridBlock):
            def __init__(self):
                super().__init__()
                self.d1 = nn.Dense(16, in_units=8, activation="relu")
                self.out = nn.Dense(4, in_units=16)

            def forward(self, x):
                return self.out(self.d1(x))

        net = Net()
        net.initialize(mx.init.Xavier())
        net.hybridize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.01, "momentum": 0.9})
        step = trainer.compile_step(
            net, lambda n, x, y: ((n(x) - y) ** 2).mean())
        rng = onp.random.RandomState(0)
        x = mx.nd.array(rng.randn(8, 8).astype(onp.float32))
        y = mx.nd.array(rng.randn(8, 4).astype(onp.float32))
        # prefetch so the transfer thread's locks enter the graph
        batches = engine.prefetch(iter([(x, y)] * 3), depth=2)
        for bx, by in batches:
            step(bx, by, batch_size=8)
        engine.waitall()

        # -- one decode batch -------------------------------------------
        eng = serving_decode.GenerativeEngine(
            serving_decode.TinyCausalLM(),
            pool=serving_decode.PagePool(pages=64, page=8), max_rows=2)
        try:
            eng.generate(onp.asarray([3, 1, 4]), max_new_tokens=2)
        finally:
            eng.close()

        # -- one preemption drain ---------------------------------------
        exits: List[int] = []
        preemption.install(exit_fn=exits.append, grace_s=60.0)
        try:
            preemption.notice()
        finally:
            preemption.uninstall()
        engine.waitall()
        drained_code = exits[0] if exits else None
    finally:
        disable()
    out = rec.report()
    out["scenario"] = {"train_steps": 3, "decode_tokens": 2,
                       "drain_exit_code": drained_code}
    return out
