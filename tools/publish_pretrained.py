#!/usr/bin/env python
"""Train a model zoo network and publish it to the local model store.

Fills the reference's pretrained-weights story
(python/mxnet/gluon/model_zoo/model_store.py) for air-gapped TPU
environments: instead of downloading from the Apache mirror, train a
checkpoint here (synthetic data or an MNIST/CIFAR-shaped npz you provide),
publish it sha1-keyed via ``model_store.publish_model_file``, and every
``get_model(name, pretrained=True)`` in this environment resolves it.

Examples:
    python tools/publish_pretrained.py --model resnet18_v1 --classes 10 \
        --steps 200 --img 32
    python tools/publish_pretrained.py --model mlp --data mnist.npz
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor a JAX_PLATFORMS pin authoritatively: the axon TPU-tunnel
# sitecustomize re-registers platforms and can override the env var, which
# hangs a cpu-pinned training run whenever the tunnel is wedged (same fix
# as tests/conftest.py)
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as onp


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--img", type=int, default=32)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--data", default=None,
                    help="npz with arrays x (N,C,H,W) and y (N,); the "
                         "special value 'digits' uses sklearn's bundled "
                         "real handwritten-digit images (1797 samples, "
                         "held-out test split, measured accuracy); "
                         "synthetic blobs otherwise")
    ap.add_argument("--root", default=None,
                    help="model store root (default: the user cache dir)")
    ap.add_argument("--ship", action="store_true",
                    help="publish into the in-repo shipped store "
                         "(model_zoo/pretrained/ + MANIFEST.json) instead "
                         "of the user cache, recording measured accuracy")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo import model_store, vision

    rng = onp.random.RandomState(args.seed)
    Xte = Yte = None
    if args.data == "digits":
        # REAL data shipped inside scikit-learn: 1797 8x8 handwritten
        # digits (a genuine UCI dataset, no network needed).  The
        # preprocessing + split is the shared single source of truth so
        # the recorded accuracy stays reproducible by the test suite.
        from mxnet_tpu.test_utils import load_digits_split

        X, Y, Xte, Yte = load_digits_split(img_size=args.img)
        args.classes = 10
        print(f"digits: {len(X)} train / {len(Xte)} test", file=sys.stderr)
    elif args.data:
        with onp.load(args.data) as z:
            X, Y = z["x"].astype(onp.float32), z["y"].astype(onp.int32)
    else:
        # separable synthetic blobs: per-class mean images + noise, enough
        # signal that the loss drop proves training happened
        means = rng.rand(args.classes, 3, args.img, args.img) * 2 - 1
        Y = rng.randint(0, args.classes, 2 * args.batch).astype(onp.int32)
        X = (means[Y] + 0.3 * rng.randn(len(Y), 3, args.img, args.img)
             ).astype(onp.float32)

    net = vision.get_model(args.model, classes=args.classes)
    net.initialize(mx.init.Xavier())
    net(nd.array(X[:1]))                       # deferred-shape probe
    net.hybridize()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": args.lr, "momentum": 0.9})
    ce = gloss.SoftmaxCrossEntropyLoss()
    n = len(X)
    t0 = time.time()
    first = last = None
    for step in range(args.steps):
        idx = rng.randint(0, n, args.batch)
        xb, yb = nd.array(X[idx]), nd.array(Y[idx])
        with autograd.record():
            out = net(xb)
            loss = ce(out, yb).mean()
        loss.backward()
        trainer.step(args.batch)
        v = float(loss.asscalar())
        first = v if first is None else first
        last = v
        if step % 20 == 0:
            print(f"step {step}: loss {v:.4f}", file=sys.stderr)
    print(f"trained {args.steps} steps in {time.time() - t0:.1f}s: "
          f"loss {first:.4f} -> {last:.4f}", file=sys.stderr)

    def _accuracy(Xa, Ya, bs=64):
        correct = 0
        for i in range(0, len(Xa), bs):
            out = net(nd.array(Xa[i:i + bs])).asnumpy()
            correct += int((out.argmax(axis=1) == Ya[i:i + bs]).sum())
        return correct / len(Xa)

    acc = {}
    if Xte is not None:
        acc = {"train_acc": round(_accuracy(X, Y), 4),
               "test_acc": round(_accuracy(Xte, Yte), 4)}
        print(f"accuracy: train {acc['train_acc']:.4f} "
              f"test {acc['test_acc']:.4f}", file=sys.stderr)

    with tempfile.TemporaryDirectory() as td:
        params_path = os.path.join(td, f"{args.model}.params")
        net.save_parameters(params_path)
        if args.ship:
            import hashlib
            import json
            import shutil

            shipped = os.path.join(os.path.dirname(model_store.__file__),
                                   "pretrained")
            os.makedirs(shipped, exist_ok=True)
            digest = hashlib.sha1(open(params_path, "rb").read()).hexdigest()
            fname = f"{args.model}-{digest[:8]}.params"
            dst = os.path.join(shipped, fname)
            shutil.copyfile(params_path, dst)
            mpath = os.path.join(shipped, "MANIFEST.json")
            manifest = (json.load(open(mpath)) if os.path.exists(mpath)
                        else {})
            prov = ("trained in-repo by tools/publish_pretrained.py on "
                    f"data={args.data or 'synthetic'} ({args.steps} steps, "
                    f"img {args.img}); accuracies measured on a fixed "
                    "held-out split" if acc else
                    "trained in-repo by tools/publish_pretrained.py on "
                    "synthetic class-mean blobs: architecture-correct demo "
                    "checkpoint; NOT real-data accuracy")
            manifest[args.model] = {"file": fname, "sha1": digest,
                                    "classes": args.classes,
                                    "provenance": prov, **acc}
            json.dump(manifest, open(mpath, "w"), indent=2)
            # drop superseded checkpoints for this model
            for f in os.listdir(shipped):
                if (f.startswith(args.model + "-") and f != fname
                        and f.endswith(".params")):
                    os.remove(os.path.join(shipped, f))
        else:
            dst = model_store.publish_model_file(params_path, args.model,
                                                 root=args.root)
    print(dst)
    return 0


if __name__ == "__main__":
    sys.exit(main())
