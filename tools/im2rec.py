#!/usr/bin/env python
"""im2rec: build .lst files and packed RecordIO datasets from image folders.

Reference: ``tools/im2rec.py`` (same CLI surface: ``--list`` mode walks an
image root into train/val .lst splits; pack mode reads a .lst, optionally
resizes/re-encodes, and writes ``.rec`` + ``.idx`` via IndexedRecordIO).
Output records use the dmlc IRHeader format, so datasets packed here load
in ``mx.io.ImageRecordIter`` / ``ImageRecordFileDataset`` (and in the
reference).
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def list_images(root: str, recursive: bool, exts=EXTS):
    """Yield (index, relpath, label) walking class folders alphabetically
    (reference list_image: label = folder index)."""
    i = 0
    if recursive:
        cat = {}
        for path, dirs, files in sorted(os.walk(root, followlinks=True)):
            dirs.sort()
            files.sort()
            for f in files:
                if os.path.splitext(f)[1].lower() not in exts:
                    continue
                if path not in cat:
                    cat[path] = len(cat)
                yield i, os.path.relpath(os.path.join(path, f), root), \
                    cat[path]
                i += 1
    else:
        for f in sorted(os.listdir(root)):
            if os.path.splitext(f)[1].lower() in exts:
                yield i, f, 0
                i += 1


def write_list(args):
    entries = list(list_images(args.root, args.recursive))
    if args.shuffle:
        random.seed(100)                     # reference uses seed 100
        random.shuffle(entries)
    n = len(entries)
    n_train = int(n * args.train_ratio)
    n_test = int(n * args.test_ratio)
    splits = [("train", entries[:n_train])] if args.train_ratio < 1.0 else \
        [("", entries)]
    if args.test_ratio > 0:
        splits.append(("test", entries[n_train:n_train + n_test]))
    if args.train_ratio + args.test_ratio < 1.0:
        splits.append(("val", entries[n_train + n_test:]))
    for suffix, chunk in splits:
        name = args.prefix + (f"_{suffix}" if suffix else "") + ".lst"
        with open(name, "w") as f:
            for j, (idx, rel, label) in enumerate(chunk):
                f.write(f"{j}\t{label}\t{rel}\n")
        print(f"wrote {name} ({len(chunk)} entries)")


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield int(parts[0]), [float(x) for x in parts[1:-1]], parts[-1]


def pack(args):
    import numpy as onp

    from mxnet_tpu import recordio

    try:
        import cv2
    except ImportError:
        cv2 = None

    lst = args.prefix + ".lst" if not args.prefix.endswith(".lst") \
        else args.prefix
    base = lst[:-len(".lst")]
    rec = recordio.MXIndexedRecordIO(base + ".idx", base + ".rec", "w")
    count = 0
    for idx, labels, rel in read_list(lst):
        path = os.path.join(args.root, rel)
        with open(path, "rb") as f:
            buf = f.read()
        needs_transform = args.resize or args.quality != 95 \
            or args.center_crop
        if needs_transform and cv2 is None:
            raise SystemExit(
                "im2rec: --resize/--center-crop/--quality require opencv "
                "(cv2), which is not importable; install it or drop the "
                "transform flags to pack raw bytes")
        if needs_transform:
            img = cv2.imdecode(onp.frombuffer(buf, onp.uint8),
                               cv2.IMREAD_COLOR)
            if args.center_crop and img.shape[0] != img.shape[1]:
                m = min(img.shape[:2])
                y0 = (img.shape[0] - m) // 2
                x0 = (img.shape[1] - m) // 2
                img = img[y0:y0 + m, x0:x0 + m]
            if args.resize:
                small = min(img.shape[:2])
                scale = args.resize / small
                img = cv2.resize(img, (int(round(img.shape[1] * scale)),
                                       int(round(img.shape[0] * scale))))
            ext = ".png" if args.encoding == ".png" else ".jpg"
            params = [cv2.IMWRITE_JPEG_QUALITY, args.quality] \
                if ext == ".jpg" else [cv2.IMWRITE_PNG_COMPRESSION, 3]
            ok, enc = cv2.imencode(ext, img, params)
            assert ok, path
            buf = enc.tobytes()
        if len(labels) == 1:
            header = recordio.IRHeader(0, labels[0], idx, 0)
        else:
            header = recordio.IRHeader(0, labels, idx, 0)
        rec.write_idx(idx, recordio.pack(header, buf))
        count += 1
        if count % 1000 == 0:
            print(f"packed {count} images")
    rec.close()
    print(f"wrote {base}.rec / {base}.idx ({count} records)")


def _str2bool(v: str) -> bool:
    """argparse-safe bool: bool("False") is True, so parse the text."""
    if v.lower() in ("1", "true", "yes", "on"):
        return True
    if v.lower() in ("0", "false", "no", "off", ""):
        return False
    raise argparse.ArgumentTypeError(f"expected a boolean, got {v!r}")


def main():
    p = argparse.ArgumentParser(
        description="Create an image list or a RecordIO dataset "
                    "(reference tools/im2rec.py)")
    p.add_argument("prefix", help="prefix of .lst/.rec files")
    p.add_argument("root", help="root folder of images")
    p.add_argument("--list", action="store_true",
                   help="create an image list instead of a record file")
    p.add_argument("--recursive", action="store_true",
                   help="walk class subfolders; label = folder index")
    p.add_argument("--shuffle", type=_str2bool, default=True,
                   help="shuffle the list (pass False/0/no to disable)")
    p.add_argument("--train-ratio", type=float, default=1.0)
    p.add_argument("--test-ratio", type=float, default=0.0)
    p.add_argument("--resize", type=int, default=0,
                   help="resize shorter edge to this size")
    p.add_argument("--center-crop", action="store_true")
    p.add_argument("--quality", type=int, default=95)
    p.add_argument("--encoding", choices=[".jpg", ".png"], default=".jpg")
    args = p.parse_args()
    if args.list:
        write_list(args)
    else:
        pack(args)


if __name__ == "__main__":
    main()
