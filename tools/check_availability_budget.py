#!/usr/bin/env python
"""CI gate: the fault-tolerant serving plane has a MEASURED
availability budget.

The serving analog of check_recovery_budget: runs the
``mxnet_tpu.drills`` ROUTER scenario matrix — a replica killed
mid-decode (plus a preemption notice through the still-routing
process), a wedged-dispatch hang, a circuit-breaker flap, and a
deadline storm — against a 2-replica ``serving_router.ReplicaRouter``
and FAILS (exit 1) unless:

- **every scenario is green**: 0 dropped requests (every submission
  ends delivered or typed-shed — ``draining`` during the drain,
  ``deadline`` past its budget, never a hang or a bare error), every
  delivered response token-exact vs the uninterrupted
  ``eager_generate`` oracle;
- **failover is bounded**: chaos-phase p99 ≤
  ``failover_p99_mult`` × steady-state p99 + ``failover_p99_slack_s``
  (the slack absorbs the wedge timeout and breaker cooldown, which are
  deliberate, documented waits — the point is a loud regression, not a
  race);
- **nothing leaks**: 0 KV pages in use across every replica pool after
  ``engine.waitall()``, including after the mid-decode kill;
- **the breaker re-admits within the probe budget**
  (``breaker_readmit_s``): after a flap burst ends, the half-open
  probe must close the breaker again — ejection is supposed to be
  temporary;
- **deadlines are honest**: a request with an infeasible
  ``deadline_us`` sheds ``ShedError(kind="deadline")`` without
  consuming more than budget + ``deadline_overrun_s``;
- **the elastic fleet heals itself** (ISSUE 17): in the scale storm
  the autoscaler grows 1 → 3 with every joiner serving its first
  request inside ``join_first_serve_s`` of its spawn (0 fresh compiles
  off the shared program cache) and shrinks back 3 → 1 where every
  scale-down is a graceful preemption (drain → typed draining sheds →
  exit 83); in the host-loss cell a SIGKILL'd remote replica costs at
  most ``kill_recover_s`` before the fleet delivers again, with every
  admitted request still delivered token-exact;
- **a poisoned draft costs zero availability** (ISSUE 19): in the
  ``spec_draft_poison`` cell a wedged draft model must auto-disable
  speculation via the cost table (``spec.autodisabled``) and degrade
  to plain decode in-place — 0 dropped requests, token streams
  unchanged, clean page-pool audit across both KV geometries.

Invoked by the test suite (tests/test_serving_router.py) exactly like
the other gates, and runnable standalone:
``python tools/check_availability_budget.py [scenario ...]``.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the budget docs/ROBUSTNESS.md promises.  Seconds bounds are
# CI-generous (a loaded runner must not flake); the drill REPORTS the
# real measured numbers and bench.py's decode lane tracks them per
# round.
BUDGET = {
    "dropped": 0,
    "leaked_kv_pages": 0,
    "failover_p99_mult": 10.0,
    "failover_p99_slack_s": 5.0,
    "breaker_readmit_s": 8.0,
    "deadline_overrun_s": 1.0,     # enforced inside the drill itself
    # the ISSUE-17 elastic-fleet walls: spawn → warm join → first
    # request served (a whole JAX boot rides inside this), and
    # SIGKILL'd host → next delivered request
    "join_first_serve_s": 90.0,
    "kill_recover_s": 10.0,
}


def main(argv=None) -> int:
    from mxnet_tpu.drills import ROUTER_SCENARIOS, run_drill

    names = [a for a in (argv or []) if not a.startswith("-")] \
        or ROUTER_SCENARIOS
    root = tempfile.mkdtemp(prefix="mxnet-availability-gate-")
    failures = []
    for name in names:
        rep = run_drill(name, root)
        for f in rep["failures"]:
            failures.append(f"{name}: {f}")
        if rep.get("dropped"):
            failures.append(
                f"{name}: {rep['dropped']} request(s) dropped "
                "(budget: 0 — every request delivered or typed-shed)")
        if rep.get("leaked_pages") not in (None,
                                           BUDGET["leaked_kv_pages"]):
            failures.append(
                f"{name}: {rep['leaked_pages']} KV pages leaked "
                "(budget: 0)")
        steady, chaos = rep.get("steady_p99_s"), rep.get("chaos_p99_s")
        if steady and chaos is not None:
            cap = (steady * BUDGET["failover_p99_mult"]
                   + BUDGET["failover_p99_slack_s"])
            if chaos > cap:
                failures.append(
                    f"{name}: chaos p99 {chaos:.3f}s exceeds "
                    f"{BUDGET['failover_p99_mult']}x steady p99 "
                    f"({steady:.3f}s) + "
                    f"{BUDGET['failover_p99_slack_s']}s slack")
        if name == "router_flap":
            ra = rep.get("re_admit_s")
            if ra is not None and ra > BUDGET["breaker_readmit_s"]:
                failures.append(
                    f"{name}: breaker re-admitted after {ra:.2f}s "
                    f"(probe budget {BUDGET['breaker_readmit_s']}s)")
        if name == "router_scale_storm":
            js = rep.get("join_to_first_served_s")
            if js is not None and js > BUDGET["join_first_serve_s"]:
                failures.append(
                    f"{name}: slowest join served its first request "
                    f"after {js:.2f}s (wall "
                    f"{BUDGET['join_first_serve_s']}s)")
        if name == "spec_draft_poison":
            # ISSUE 19: the wedged draft costs ZERO availability — the
            # drill's own cell checks pin auto-disable + degrade; here
            # we pin that speculation actually ran before the poison
            # (a cell that never speculated proves nothing)
            spec = rep.get("spec") or []
            if spec and not any(s.get("spec_rounds") for s in spec):
                failures.append(
                    f"{name}: no spec rounds before the poison — the "
                    "drill exercised plain decode only")
        if name == "router_host_loss":
            kr = rep.get("kill_to_recovered_s")
            if kr is not None and kr > BUDGET["kill_recover_s"]:
                failures.append(
                    f"{name}: first delivery {kr:.2f}s after the "
                    f"SIGKILL (wall {BUDGET['kill_recover_s']}s)")
        line = {k: rep.get(k) for k in
                ("scenario", "ok", "dropped", "leaked_pages",
                 "steady_p99_s", "chaos_p99_s", "failovers",
                 "breaker_opens", "breaker_closes", "re_admit_s",
                 "drain_s", "join_to_first_served_s",
                 "kill_to_recovered_s", "spec_autodisabled",
                 "drill_wall_s")}
        print(f"check_availability_budget: {json.dumps(line, default=str)}")
    if failures:
        print("check_availability_budget: FAIL", file=sys.stderr)
        for f in failures:
            print(f"  {f}", file=sys.stderr)
        return 1
    print(f"check_availability_budget: {len(names)} scenario(s) green — "
          "0 dropped, 0 leaked pages, failover p99 inside budget, "
          "breaker re-admitted, deadlines honest")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
