"""Rebuild the .idx for a RecordIO .rec file (reference tools/rec2idx.py).

The index maps record key -> byte offset so `MXIndexedRecordIO` (and the
DataLoader random samplers over record datasets) can seek.  Scans the .rec
sequentially and writes ``<key>\t<offset>`` lines.

    python tools/rec2idx.py data.rec [data.idx]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from mxnet_tpu.recordio import MXRecordIO


def build_index(rec_path: str, idx_path: str) -> int:
    reader = MXRecordIO(rec_path, "r")
    n = 0
    with open(idx_path, "w") as idx:
        while True:
            pos = reader.tell()
            record = reader.read()
            if record is None:
                break
            idx.write(f"{n}\t{pos}\n")
            n += 1
    reader.close()
    return n


def main():
    p = argparse.ArgumentParser(
        description="create an index file from a .rec file")
    p.add_argument("record", help="path to the .rec file")
    p.add_argument("index", nargs="?", default=None,
                   help="output .idx path (default: alongside the .rec)")
    args = p.parse_args()
    idx = args.index or args.record.rsplit(".", 1)[0] + ".idx"
    n = build_index(args.record, idx)
    print(f"wrote {n} entries to {idx}")


if __name__ == "__main__":
    main()
