#!/usr/bin/env python
"""Perf-regression CI gate over bench-artifact telemetry (ISSUE 15;
ROADMAP item 5's "measurement substrate finally defending itself").

Every bench lane stamps a full namespaced telemetry snapshot
(``telemetry``, PR 10) and — for subprocess-fleet lanes — a merged
``fleet_telemetry`` (this PR).  This gate diffs those snapshots between
two artifacts (``BENCH_r{N-1}`` vs ``BENCH_r{N}`` by default, or
``--baseline``/``--candidate``) and FAILS LOUDLY when a counter family
the PRs 1–14 wins were bought in regresses past its declared tolerance:

- **retraces** (``program_store.<ns>.traces``): tolerance 0 — one extra
  steady-state retrace is the classic silent perf killer.
- **dispatches** (``program_store.<ns>.dispatches``): the 1-dispatch/
  step contract; small ratio slack for workload jitter.
- **host syncs** (``ndarray.host_sync``, ``metric.host_sync``): the
  PR-5 pipeline win.
- **shed rate** (``*.shed``, ``*.sheds``, ``*.shed_<kind>``): serving
  availability (PRs 8/14).
- **program-cache misses** (``program_store.<ns>.misses`` and the disk
  ``cache_misses`` lane alias): the PR-7 cold-start win.
- **prefix-cache misses** (``prefix.miss_blocks``, ``prefix.evictions``
  and the ``prefix_miss_blocks`` lane alias): the ISSUE-16
  shared-prompt prefill win — a hit-rate drop surfaces as miss-block
  growth on the same workload, an undersized pool as eviction churn.
- **speculative decoding** (ISSUE 19): ``spec.acceptance_rate`` and
  ``spec.tokens_per_target_dispatch`` gate FALLING (bigger is better
  — an acceptance drop starves the k-for-1 verify win), while
  ``spec.fallback_rounds`` / ``spec.autodisabled`` gate rising churn;
  the sampled-decode dispatch/retrace counters
  (``program_store.serving_spec.*``) ride the existing retrace and
  dispatch rules with tolerance 0 on retraces.

Counter names are instance-normalized (``decode.engine3.shed`` →
``decode.engine*.shed``) and summed per lane, so a renumbered engine
instance between rounds cannot fake a delta.  Lanes match by their
``metric`` name; a lane present on only one side is reported, never
fatal.  Artifacts that predate telemetry stamping (e.g. the committed
``BENCH_r04``/``BENCH_r05`` pair) have nothing comparable: the gate
prints exactly that and passes — vacuous green is loud, not silent.

A regression can be WAIVED with a reasoned entry in
``tools/perf_delta_waivers.json`` (graftlint-baseline style: shipped
empty, every entry needs ``lane``, ``counter``, and a non-empty
``reason``); waived regressions are reported but do not fail.

``--self-test`` verifies the gate catches an injected +1-retrace
candidate (and is run by the suite).  Exit 0 = no unwaived regression.

Usage::

    python tools/check_perf_delta.py                  # newest r-pair
    python tools/check_perf_delta.py --baseline A.json --candidate B.json
    python tools/check_perf_delta.py --self-test
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Callable, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WAIVER_PATH = os.path.join(REPO, "tools", "perf_delta_waivers.json")


class Rule:
    """One gated counter family: ``match`` selects normalized counter
    names, a candidate value above ``base * (1 + tol) + slack`` is a
    regression.  ``falling=True`` inverts the direction for
    bigger-is-better gauges (e.g. ``spec.acceptance_rate``): a
    candidate below ``base * (1 - tol) - slack`` regresses."""

    def __init__(self, label: str, match: Callable[[str], bool],
                 tol: float, slack: float, falling: bool = False):
        self.label = label
        self.match = match
        self.tol = tol
        self.slack = slack
        self.falling = falling

    def regressed(self, base: float, cand: float) -> bool:
        if self.falling:
            return cand < base * (1.0 - self.tol) - self.slack
        return cand > base * (1.0 + self.tol) + self.slack


RULES: Tuple[Rule, ...] = (
    Rule("retrace",
         lambda n: n.startswith("program_store.") and n.endswith(".traces"),
         tol=0.0, slack=0.0),
    Rule("dispatch",
         lambda n: n.startswith("program_store.")
         and n.endswith(".dispatches"),
         tol=0.10, slack=2.0),
    Rule("host-sync",
         lambda n: n in ("ndarray.host_sync", "metric.host_sync"),
         tol=0.10, slack=2.0),
    Rule("shed-rate",
         lambda n: re.search(r"\.sheds?$", n) is not None
         or re.search(r"\.shed_[a-z]+$", n) is not None,
         tol=0.10, slack=2.0),
    Rule("program-cache-miss",
         lambda n: n.startswith("program_store.") and n.endswith(".misses"),
         tol=0.10, slack=2.0),
    Rule("prefix-miss",
         lambda n: n in ("prefix.miss_blocks", "prefix.evictions"),
         tol=0.10, slack=2.0),
    # ISSUE 17: fleet churn is a cost — a benchmarked workload that
    # suddenly needs more scale events (or errors) to hit the same
    # numbers has regressed its stability, not just its latency
    Rule("fleet-churn",
         lambda n: n.startswith("router.fleet*.")
         and n.split(".")[-1] in ("scale_ups", "scale_downs",
                                  "scale_errors"),
         tol=0.10, slack=2.0),
    # ISSUE 18: sharding regressions — a workload that suddenly needs
    # steady-state host-side reshards, silently-replicated batches, or
    # more refused (replicated) spec dims has lost its SPMD scaling
    # even if wall-clock momentarily survives
    Rule("spmd-reshard",
         lambda n: n in ("spmd.reshard", "spmd.replicated_batch"),
         tol=0.0, slack=0.0),
    Rule("sharding-refusal",
         lambda n: n == "sharding.legalize_refusal",
         tol=0.10, slack=2.0),
    # memory-per-chip gauges (spmd.param_bytes_per_device /
    # spmd.opt_bytes_per_device): a candidate whose per-device param or
    # optimizer-state footprint grows >10% over baseline on the same
    # lane has regressed its sharding placement (e.g. a leaf fell back
    # to replication)
    Rule("spmd-bytes-per-device",
         lambda n: n in ("spmd.param_bytes_per_device",
                         "spmd.opt_bytes_per_device"),
         tol=0.10, slack=1024.0),
    # ISSUE 19: the speculative-decoding family.  Acceptance is the
    # lever the whole k-for-1 win hangs on — a drop past 5% on the
    # same workload means the draft/verify pair degraded and every
    # verify dispatch is buying fewer tokens; it must fail loudly, not
    # rot silently behind a still-green wall-clock number.  Same for
    # tokens-per-target-dispatch, the win itself.
    Rule("spec-acceptance",
         lambda n: n == "spec.acceptance_rate",
         tol=0.05, slack=0.02, falling=True),
    Rule("spec-tokens-per-dispatch",
         lambda n: n == "spec.tokens_per_target_dispatch",
         tol=0.10, slack=0.1, falling=True),
    # churn: a workload that suddenly needs more fallback rounds or
    # auto-disables has lost speculation where it used to pay
    Rule("spec-churn",
         lambda n: n in ("spec.fallback_rounds", "spec.autodisabled"),
         tol=0.10, slack=2.0),
    # ISSUE 20: the every-axis-mesh family.  The pp lane's measured
    # bubble fraction growing means the scan-internal GPipe schedule
    # lost fill/drain overlap (slack absorbs wall-clock jitter on the
    # slope fit); the moe lane's dropped slots growing on the same
    # bench batch means the routing/capacity balance regressed — the
    # aux loss stopped doing its job; tokens/s/chip is the ep win
    # itself (falling gate, generous for CPU-fallback noise).
    Rule("pp-bubble",
         lambda n: n == "pp.bubble_fraction_measured",
         tol=0.10, slack=0.05),
    Rule("moe-drop",
         lambda n: n == "moe.dropped_slots",
         tol=0.10, slack=2.0),
    Rule("moe-throughput",
         lambda n: n == "moe.tokens_per_s_per_chip",
         tol=0.30, slack=100.0, falling=True),
)

# lane-level scalar aliases gated alongside the namespaced counters
# (older artifacts carry only these; keys -> rule label)
LANE_KEY_RULES: Dict[str, str] = {
    "retrace_count": "retrace",
    "cache_misses": "program-cache-miss",
    "prefix_miss_blocks": "prefix-miss",
}
_LANE_KEY_RULE = {r.label: r for r in RULES}

_INSTANCE_RE = re.compile(r"^((?:serving\.router|serving\.engine|"
                          r"decode\.engine|kv_pool|router\.fleet))\d+\.")


def normalize(name: str) -> str:
    """Strip per-process instance numbering (``decode.engine3.shed`` →
    ``decode.engine*.shed``) so re-numbered instances compare."""
    return _INSTANCE_RE.sub(r"\1*.", name)


def lane_counters(lane: Dict[str, Any]) -> Optional[Dict[str, float]]:
    """The lane's comparable counters: the fleet merge when present,
    else its single-process snapshot — instance-normalized and summed.
    None when the lane predates telemetry stamping."""
    snap = lane.get("fleet_telemetry") or lane.get("telemetry")
    if not isinstance(snap, dict):
        return None
    out: Dict[str, float] = {}
    for name, val in snap.items():
        if not isinstance(val, (int, float)) or isinstance(val, bool):
            continue
        key = normalize(name)
        out[key] = out.get(key, 0.0) + val
    return out


def extract_lanes(artifact: Any) -> List[Dict[str, Any]]:
    """Every lane dict in a bench artifact, tolerant of the three
    shapes in the wild: the committed ``{"parsed": {..., "lanes":
    [...]}}`` round files, a bare ``{"lanes": [...]}`` payload (the
    head itself is a lane), and a plain list of lanes."""
    if isinstance(artifact, list):
        return [l for l in artifact if isinstance(l, dict)]
    if not isinstance(artifact, dict):
        return []
    node = artifact.get("parsed", artifact)
    if not isinstance(node, dict):
        return []
    lanes = [l for l in node.get("lanes", []) if isinstance(l, dict)]
    if "metric" in node:
        head = {k: v for k, v in node.items() if k != "lanes"}
        if not any(l.get("metric") == head.get("metric") for l in lanes):
            lanes.insert(0, head)
    return lanes


def load_artifact(path: str) -> List[Dict[str, Any]]:
    with open(path) as f:
        return extract_lanes(json.load(f))


def load_waivers(path: str) -> List[Dict[str, str]]:
    """Reasoned waivers only: every entry must name its lane, counter,
    and a non-empty reason — an unreasoned waiver fails the gate
    outright (the graftlint baseline policy)."""
    if not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    waivers = data.get("waivers", [])
    for w in waivers:
        if not (w.get("lane") and w.get("counter")
                and str(w.get("reason", "")).strip()):
            raise SystemExit(
                f"check_perf_delta: waiver {w!r} in {path} lacks "
                "lane/counter/reason — waivers must be reasoned")
    return waivers


def _waived(waivers: List[Dict[str, str]], lane: str,
            counter: str) -> Optional[Dict[str, str]]:
    for w in waivers:
        if w["lane"] == lane and w["counter"] == counter:
            return w
    return None


def compare(baseline: List[Dict[str, Any]],
            candidate: List[Dict[str, Any]],
            waivers: List[Dict[str, str]]) -> Dict[str, Any]:
    """Diff matched lanes' counters under the rule table.  Returns the
    full report; ``report['regressions']`` non-empty = gate fails."""
    base_by = {l.get("metric"): l for l in baseline if l.get("metric")}
    cand_by = {l.get("metric"): l for l in candidate if l.get("metric")}
    report: Dict[str, Any] = {
        "lanes_compared": [], "lanes_skipped": [], "counters_compared": 0,
        "regressions": [], "waived": [], "improvements": [],
    }
    for metric in sorted(set(base_by) & set(cand_by)):
        b = lane_counters(base_by[metric])
        c = lane_counters(cand_by[metric])
        rows: List[Tuple[str, Rule, float, float]] = []
        if b is not None and c is not None:
            for name in sorted(set(b) | set(c)):
                for rule in RULES:
                    if rule.match(name):
                        rows.append((name, rule, b.get(name, 0.0),
                                     c.get(name, 0.0)))
                        break
        # lane-level scalar aliases (the only signal pre-PR-10 rounds
        # carry) gate under the same tolerances
        for key, label in LANE_KEY_RULES.items():
            bv, cv = base_by[metric].get(key), cand_by[metric].get(key)
            if isinstance(bv, (int, float)) and isinstance(cv, (int, float)):
                rows.append((f"lane:{key}", _LANE_KEY_RULE[label], bv, cv))
        if not rows:
            report["lanes_skipped"].append(metric)
            continue
        report["lanes_compared"].append(metric)
        for name, rule, bv, cv in rows:
            report["counters_compared"] += 1
            if rule.regressed(bv, cv):
                entry = {"lane": metric, "counter": name,
                         "rule": rule.label, "baseline": bv,
                         "candidate": cv,
                         "tolerance": f"+{rule.tol:.0%} +{rule.slack:g}"}
                w = _waived(waivers, metric, name)
                if w is not None:
                    entry["reason"] = w["reason"]
                    report["waived"].append(entry)
                else:
                    report["regressions"].append(entry)
            elif (cv > bv) if rule.falling else (cv < bv):
                report["improvements"].append(
                    {"lane": metric, "counter": name, "rule": rule.label,
                     "baseline": bv, "candidate": cv})
    report["lanes_baseline_only"] = sorted(set(base_by) - set(cand_by))
    report["lanes_candidate_only"] = sorted(set(cand_by) - set(base_by))
    return report


def default_pair() -> Optional[Tuple[str, str]]:
    """The two newest committed ``BENCH_r{N}.json`` rounds."""
    rounds = []
    for p in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        m = re.match(r"BENCH_r(\d+)\.json$", os.path.basename(p))
        if m:
            rounds.append((int(m.group(1)), p))
    if len(rounds) < 2:
        return None
    rounds.sort()
    return rounds[-2][1], rounds[-1][1]


def run_gate(baseline_path: str, candidate_path: str,
             waiver_path: str = WAIVER_PATH,
             emit_json: bool = False) -> int:
    baseline = load_artifact(baseline_path)
    candidate = load_artifact(candidate_path)
    waivers = load_waivers(waiver_path)
    report = compare(baseline, candidate, waivers)
    report["baseline"] = os.path.basename(baseline_path)
    report["candidate"] = os.path.basename(candidate_path)
    if emit_json:
        print(json.dumps(report, indent=2))
    for w in report["waived"]:
        print(f"check_perf_delta: WAIVED [{w['rule']}] lane "
              f"{w['lane']!r} counter {w['counter']} "
              f"{w['baseline']:g} -> {w['candidate']:g}: {w['reason']}")
    if report["regressions"]:
        print(f"check_perf_delta: FAILED — "
              f"{report['candidate']} regresses vs {report['baseline']}",
              file=sys.stderr)
        for r in report["regressions"]:
            print(f"  [{r['rule']}] lane {r['lane']!r}: counter "
                  f"{r['counter']} rose {r['baseline']:g} -> "
                  f"{r['candidate']:g} (tolerance {r['tolerance']})",
                  file=sys.stderr)
        return 1
    if not report["lanes_compared"]:
        print(f"check_perf_delta: PASS (vacuous) — no lane of "
              f"{report['baseline']} vs {report['candidate']} carries "
              "comparable telemetry (pre-PR-10 artifacts); nothing to "
              "regress against yet")
        return 0
    print(f"check_perf_delta: PASS — {len(report['lanes_compared'])} "
          f"lane(s), {report['counters_compared']} gated counter(s), "
          f"{len(report['waived'])} waived, "
          f"{len(report['improvements'])} improved "
          f"({report['baseline']} -> {report['candidate']})")
    return 0


def self_test() -> int:
    """The injected-regression check: a synthetic candidate with ONE
    extra steady-state retrace (and nothing else changed) must fail,
    and the failure must name the counter and the lane."""
    base_lane = {
        "metric": "decode_continuous_tokens_per_s", "value": 100.0,
        "telemetry": {"program_store.serving_decode.traces": 5,
                      "program_store.serving_decode.dispatches": 64,
                      "ndarray.host_sync": 16,
                      "decode.engine0.shed": 1,
                      "prefix.hit_blocks": 90,
                      "prefix.miss_blocks": 10},
    }
    cand_lane = json.loads(json.dumps(base_lane))
    cand_lane["telemetry"]["program_store.serving_decode.traces"] = 6
    report = compare([base_lane], [cand_lane], waivers=[])
    bad = [r for r in report["regressions"]
           if r["counter"] == "program_store.serving_decode.traces"
           and r["lane"] == "decode_continuous_tokens_per_s"
           and r["rule"] == "retrace"]
    if not bad:
        print("check_perf_delta: SELF-TEST FAILED — a +1 retrace "
              f"candidate was not flagged ({report['regressions']})",
              file=sys.stderr)
        return 1
    # a collapsed prefix-cache hit rate (same workload, misses way up)
    # must trip the prefix-miss rule
    miss_lane = json.loads(json.dumps(base_lane))
    miss_lane["telemetry"]["prefix.miss_blocks"] = 60
    miss_lane["telemetry"]["prefix.hit_blocks"] = 40
    report = compare([base_lane], [miss_lane], waivers=[])
    bad = [r for r in report["regressions"]
           if r["counter"] == "prefix.miss_blocks"
           and r["rule"] == "prefix-miss"]
    if not bad:
        print("check_perf_delta: SELF-TEST FAILED — a collapsed "
              "prefix hit rate was not flagged "
              f"({report['regressions']})", file=sys.stderr)
        return 1
    # ISSUE 19: an acceptance-rate DROP (bigger-is-better gauge) must
    # trip the falling spec-acceptance rule, and spec retraces gate at
    # tolerance 0 like every other namespace
    spec_base = {
        "metric": "decode_speculative_tokens_per_s", "value": 250.0,
        "telemetry": {"spec.acceptance_rate": 0.95,
                      "spec.tokens_per_target_dispatch": 4.2,
                      "spec.fallback_rounds": 1,
                      "spec.autodisabled": 0,
                      "program_store.serving_spec.traces": 7},
    }
    spec_drop = json.loads(json.dumps(spec_base))
    spec_drop["telemetry"]["spec.acceptance_rate"] = 0.55
    report = compare([spec_base], [spec_drop], waivers=[])
    bad = [r for r in report["regressions"]
           if r["counter"] == "spec.acceptance_rate"
           and r["rule"] == "spec-acceptance"]
    if not bad:
        print("check_perf_delta: SELF-TEST FAILED — a collapsed spec "
              "acceptance rate was not flagged "
              f"({report['regressions']})", file=sys.stderr)
        return 1
    spec_rise = json.loads(json.dumps(spec_base))
    spec_rise["telemetry"]["spec.acceptance_rate"] = 1.0
    report = compare([spec_base], [spec_rise], waivers=[])
    if report["regressions"]:
        print("check_perf_delta: SELF-TEST FAILED — an IMPROVED spec "
              "acceptance rate was flagged as a regression "
              f"({report['regressions']})", file=sys.stderr)
        return 1
    # ISSUE 20: a grown pp bubble fraction and a moe drop-count spike
    # must trip their rules; a moe throughput IMPROVEMENT must not
    axis_base = {
        "metric": "pp_bubble_fraction", "value": 0.2,
        "telemetry": {"pp.bubble_fraction_measured": 0.20,
                      "moe.dropped_slots": 3,
                      "moe.tokens_per_s_per_chip": 2500.0},
    }
    bubble_rise = json.loads(json.dumps(axis_base))
    bubble_rise["telemetry"]["pp.bubble_fraction_measured"] = 0.40
    report = compare([axis_base], [bubble_rise], waivers=[])
    bad = [r for r in report["regressions"]
           if r["counter"] == "pp.bubble_fraction_measured"
           and r["rule"] == "pp-bubble"]
    if not bad:
        print("check_perf_delta: SELF-TEST FAILED — a doubled pp "
              "bubble fraction was not flagged "
              f"({report['regressions']})", file=sys.stderr)
        return 1
    drop_rise = json.loads(json.dumps(axis_base))
    drop_rise["telemetry"]["moe.dropped_slots"] = 40
    report = compare([axis_base], [drop_rise], waivers=[])
    bad = [r for r in report["regressions"]
           if r["counter"] == "moe.dropped_slots"
           and r["rule"] == "moe-drop"]
    if not bad:
        print("check_perf_delta: SELF-TEST FAILED — a moe capacity-"
              "drop spike was not flagged "
              f"({report['regressions']})", file=sys.stderr)
        return 1
    tok_rise = json.loads(json.dumps(axis_base))
    tok_rise["telemetry"]["moe.tokens_per_s_per_chip"] = 4000.0
    report = compare([axis_base], [tok_rise], waivers=[])
    if report["regressions"]:
        print("check_perf_delta: SELF-TEST FAILED — an IMPROVED moe "
              "throughput was flagged as a regression "
              f"({report['regressions']})", file=sys.stderr)
        return 1
    clean = compare([base_lane], [json.loads(json.dumps(base_lane))],
                    waivers=[])
    if clean["regressions"]:
        print("check_perf_delta: SELF-TEST FAILED — an identical "
              f"candidate was flagged ({clean['regressions']})",
              file=sys.stderr)
        return 1
    print("check_perf_delta: self-test OK (+1 retrace flagged, "
          "acceptance drop flagged, pp bubble rise flagged, moe drop "
          "spike flagged, identical snapshot clean)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", default=None)
    ap.add_argument("--candidate", default=None)
    ap.add_argument("--waivers", default=WAIVER_PATH)
    ap.add_argument("--json", action="store_true", dest="emit_json")
    ap.add_argument("--self-test", action="store_true", dest="self_test")
    a = ap.parse_args(argv)
    if a.self_test:
        return self_test()
    if (a.baseline is None) != (a.candidate is None):
        ap.error("--baseline and --candidate go together")
    if a.baseline is None:
        pair = default_pair()
        if pair is None:
            print("check_perf_delta: fewer than two BENCH_r*.json "
                  "rounds in the repo root; nothing to diff",
                  file=sys.stderr)
            return 1
        a.baseline, a.candidate = pair
    return run_gate(a.baseline, a.candidate, a.waivers,
                    emit_json=a.emit_json)


if __name__ == "__main__":
    sys.exit(main())
