#!/usr/bin/env python
"""CI gate: preemption survival has a MEASURED recovery budget.

The recovery analog of check_dispatch_budget/check_fault_sites: runs
the full `mxnet_tpu.drills` scenario matrix — real subprocesses, real
SIGTERM/SIGKILL, a 4→2 device mesh change, a corrupted checkpoint, a
mid-stream decode kill — and FAILS (exit 1) unless:

- **every drill scenario is green** (bit-exact resumed loss
  trajectories, token-exact decode completions/re-queues, typed
  ``draining`` sheds, the distinguished preemption exit code);
- **graceful drain replays 0 steps** (the SIGTERM checkpoint is the
  exact pre-signal state) and a SIGKILL replays exactly the
  save-interval gap;
- **warm recovery performs 0 fresh compiles**: every restart resumes
  from ``MXNET_PROGRAM_CACHE_DIR`` disk hits only (the PR-7 promise,
  now enforced under failure, including after the topology change);
- **nothing leaks**: 0 KV pages after the decode drain's
  ``waitall()``, 0 temp checkpoint files after a kill;
- **recovery fits the wall-clock budget**: checkpoint restore under
  ``RECOVERY_S_MAX`` and process-start→first-resumed-step under
  ``RECOVERY_WALL_S_MAX`` (generous CI bounds — the point is a loud
  regression, not a race).

Invoked by the test suite (tests/test_preemption.py) exactly like the
other gates, and runnable standalone:
``python tools/check_recovery_budget.py [scenario ...]``.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the budget docs/ROBUSTNESS.md promises.  The seconds bounds are CI-
# generous (a loaded runner must not flake) — the drill REPORTS the
# real measured numbers; bench.py's elastic lane tracks them per round.
BUDGET = {
    "graceful_steps_replayed": 0,
    "warm_recovery_fresh_compiles": 0,
    "leaked_kv_pages": 0,
    "leaked_tmp_files": 0,
    "recovery_s_max": 60.0,
    "recovery_wall_s_max": 120.0,
}


def main(argv=None) -> int:
    from mxnet_tpu.drills import SCENARIOS, run_drill

    names = [a for a in (argv or []) if not a.startswith("-")] or SCENARIOS
    root = tempfile.mkdtemp(prefix="mxnet-recovery-gate-")
    failures = []
    reports = []
    for name in names:
        rep = run_drill(name, root)
        reports.append(rep)
        for f in rep["failures"]:
            failures.append(f"{name}: {f}")
        # the cross-scenario budget lines (scenario-internal contracts —
        # restore points, bit-exactness, typed sheds — already fail
        # through rep['failures'])
        if rep.get("fresh_compiles") is not None and \
                rep["fresh_compiles"] != BUDGET["warm_recovery_fresh_compiles"]:
            failures.append(
                f"{name}: warm recovery performed {rep['fresh_compiles']} "
                "fresh compiles (budget: 0 — disk hits only)")
        if rep.get("leaked_pages") not in (None, BUDGET["leaked_kv_pages"]):
            failures.append(
                f"{name}: {rep['leaked_pages']} KV pages leaked "
                "(budget: 0)")
        if rep.get("leaked_tmp"):
            failures.append(
                f"{name}: temp checkpoint litter {rep['leaked_tmp']} "
                "(budget: 0 files)")
        if rep.get("recovery_s") is not None and \
                rep["recovery_s"] > BUDGET["recovery_s_max"]:
            failures.append(
                f"{name}: checkpoint restore took {rep['recovery_s']:.2f}s "
                f"(budget {BUDGET['recovery_s_max']}s)")
        if rep.get("recovery_wall_s") is not None and \
                rep["recovery_wall_s"] > BUDGET["recovery_wall_s_max"]:
            failures.append(
                f"{name}: restart->first-step took "
                f"{rep['recovery_wall_s']:.2f}s "
                f"(budget {BUDGET['recovery_wall_s_max']}s)")
        line = {k: rep.get(k) for k in
                ("scenario", "ok", "recovery_s", "recovery_wall_s",
                 "steps_replayed", "drain_s", "fresh_compiles",
                 "disk_hits", "restored_at", "drill_wall_s")}
        print(f"check_recovery_budget: {json.dumps(line, default=str)}")
    if failures:
        print("check_recovery_budget: FAIL", file=sys.stderr)
        for f in failures:
            print(f"  {f}", file=sys.stderr)
        return 1
    print(f"check_recovery_budget: {len(names)} scenario(s) green, "
          "0 fresh compiles on warm recovery, 0 leaks, inside the "
          "recovery budget")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
