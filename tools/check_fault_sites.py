#!/usr/bin/env python
"""Static check: every fault-injection site shipped in ``mxnet_tpu/``
must be exercised by at least one test.

A site is any string literal passed as ``faults.inject("<site>")`` or as
``site="<site>"`` (the ``retry_call`` keyword).  A site counts as tested
when the same quoted string appears anywhere under ``tests/`` — the
fault-matrix suite (tests/test_faults.py) installs a FaultPlan against
it and asserts the documented recovery.  New sites therefore cannot ship
untested; the suite itself runs this check (tests/test_faults.py).

Since graftlint landed, this is a thin wrapper over the shared AST walk
(``tools.lint``): site collection is the ``fault-site`` rule's collector
(one parse, real call nodes instead of a regex), and the full rule —
which ADDITIONALLY requires every site to appear in docs/ROBUSTNESS.md's
site table — runs via ``python -m tools.lint``.  This entrypoint keeps
the original contract (tests-coverage only, same exit codes) so existing
suite hooks don't break.

Exit code 0 = every site covered; 1 = missing coverage (sites listed on
stderr).  Usage: python tools/check_fault_sites.py [repo_root]
"""
from __future__ import annotations

import os
import re
import sys
from typing import Dict, Set

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.lint import walk_package  # noqa: E402
from tools.lint.rules import collect_fault_sites  # noqa: E402


def _py_files(root: str):
    for dirpath, _dirs, files in os.walk(root):
        for f in files:
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def collect_sites(pkg_dir: str) -> Dict[str, Set[str]]:
    """Site -> set of source files (relative) declaring it — the
    graftlint shared-walk collection."""
    pkg_dir = os.path.abspath(pkg_dir)
    ctx = walk_package(os.path.dirname(pkg_dir),
                       os.path.basename(pkg_dir))
    return {site: {src.rel for src, _node in decls}
            for site, decls in collect_fault_sites(ctx).items()}


def tested_sites(tests_dir: str, sites) -> Set[str]:
    covered: Set[str] = set()
    pats = {s: re.compile(r"""["']""" + re.escape(s) + r"""["']""")
            for s in sites}
    for path in _py_files(tests_dir):
        with open(path, encoding="utf-8") as f:
            text = f.read()
        for s, pat in pats.items():
            if s not in covered and pat.search(text):
                covered.add(s)
    return covered


def main(root: str = None) -> int:
    root = root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    pkg, tests = os.path.join(root, "mxnet_tpu"), os.path.join(root, "tests")
    sites = collect_sites(pkg)
    if not sites:
        print("check_fault_sites: no injection sites found under "
              f"{pkg} — the shared walk or layout broke", file=sys.stderr)
        return 1
    covered = tested_sites(tests, sites)
    missing = sorted(set(sites) - covered)
    if missing:
        print("check_fault_sites: injection sites with NO test coverage "
              "(reference them from a test, e.g. via faults.FaultPlan):",
              file=sys.stderr)
        for s in missing:
            print(f"  {s!r}  (declared in {', '.join(sorted(sites[s]))})",
                  file=sys.stderr)
        return 1
    print(f"check_fault_sites: {len(sites)} sites, all covered: "
          f"{sorted(sites)}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
