"""``mx.nd`` — the imperative NDArray namespace.

Every operator registered with namespace 'nd' is exposed here as a function
(generated in :mod:`.register`), mirroring the reference's generated
``mxnet.ndarray.op`` module.
"""
from __future__ import annotations

import sys as _sys
import types as _types

import numpy as _onp

from ..context import Context, cpu, current_context
from ..ops import registry as _registry
from . import sparse
from . import utils
from .ndarray import NDArray, array, invoke
from .register import make_op_func
from .utils import load, save, save_legacy

_this = _sys.modules[__name__]

# --- generate op functions -------------------------------------------------
_seen = set()
for _name, _schema in list(_registry._OPS.items()):
    if "nd" not in _schema.namespaces:
        continue
    if _name in _seen:
        continue
    _seen.add(_name)
    if not hasattr(_this, _name):
        setattr(_this, _name, make_op_func(_schema))

op = _this  # reference exposes mx.nd.op alias


def __getattr__(name):
    """Late-registered ops (contrib.quantization, library.register_op,
    reference-name aliases) resolve through the registry on first access —
    the analog of the reference regenerating its namespace after MXLoadLib.
    """
    if name in ("np", "npx"):
        # 1.x hybrid_forward passes F=this module; reference code reaches
        # the numpy surfaces as F.np / F.npx
        import importlib

        mod = importlib.import_module(
            "mxnet_tpu.numpy" if name == "np" else
            "mxnet_tpu.numpy_extension")
        setattr(_this, name, mod)
        return mod
    schema = _registry.find_op(name)
    if schema is not None and "nd" in schema.namespaces:
        fn = make_op_func(schema)
        setattr(_this, name, fn)
        return fn
    raise AttributeError(f"module '{__name__}' has no attribute '{name}'")


# --- creation helpers with MXNet calling conventions -----------------------
def zeros(shape, ctx=None, dtype="float32", **kwargs):
    import jax.numpy as jnp

    from .ndarray import _wrap

    ctx = ctx or current_context()
    import jax

    return _wrap(
        jax.device_put(jnp.zeros(shape, _np_dtype(dtype)), ctx.jax_device), ctx
    )


def ones(shape, ctx=None, dtype="float32", **kwargs):
    import jax
    import jax.numpy as jnp

    from .ndarray import _wrap

    ctx = ctx or current_context()
    return _wrap(
        jax.device_put(jnp.ones(shape, _np_dtype(dtype)), ctx.jax_device), ctx
    )


def full(shape, val, ctx=None, dtype="float32", **kwargs):
    import jax
    import jax.numpy as jnp

    from .ndarray import _wrap

    ctx = ctx or current_context()
    return _wrap(
        jax.device_put(jnp.full(shape, val, _np_dtype(dtype)), ctx.jax_device), ctx
    )


def empty(shape, ctx=None, dtype="float32"):
    return zeros(shape, ctx=ctx, dtype=dtype)


def _np_dtype(dtype):
    import jax.numpy as jnp

    if dtype is None:
        return jnp.float32
    if dtype == "bfloat16":
        return jnp.bfloat16
    return _onp.dtype(dtype) if isinstance(dtype, str) else dtype


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    out = invoke(
        _registry.get_op("arange"),
        [],
        {"start": start, "stop": stop, "step": step, "repeat": repeat, "dtype": dtype},
    )
    if ctx is not None:
        import jax

        out._ctx = ctx
        out._data = jax.device_put(out._data, ctx.jax_device)
    return out


def waitall():
    """Block until all async work completes (reference MXNDArrayWaitAll).

    JAX dispatches asynchronously; an effects barrier drains the stream."""
    import jax

    try:
        jax.effects_barrier()
    except Exception:
        pass


def concatenate(arrays, axis=0, always_copy=True):
    return invoke(_registry.get_op("concat"), list(arrays), {"dim": axis})


def moveaxis(data, source, destination):
    import numpy as onp

    axes = list(range(data.ndim))
    src = [source] if isinstance(source, int) else list(source)
    dst = [destination] if isinstance(destination, int) else list(destination)
    for s, d in sorted(zip(src, dst), key=lambda x: x[1]):
        axes.remove(s)
        axes.insert(d, s)
    return invoke(_registry.get_op("transpose"), [data], {"axes": tuple(axes)})


# --- random submodule ------------------------------------------------------
random = _types.ModuleType(__name__ + ".random")
_sys.modules[random.__name__] = random


def _make_random(name, schema_name=None):
    schema = _registry.get_op(schema_name or name)
    base = make_op_func(schema)

    def fn(*args, **kwargs):
        return base(*args, **kwargs)

    fn.__name__ = name
    return fn


random.gamma = _make_random("gamma", "random_gamma")
for _rn in [
    "uniform",
    "normal",
    "exponential",
    "poisson",
    "negative_binomial",
    "randint",
    "randn",
    "multinomial",
    "shuffle",
    "bernoulli",
]:
    setattr(random, _rn, _make_random(_rn))
random.seed = __import__("mxnet_tpu.random", fromlist=["seed"]).seed

# linalg submodule
linalg = _types.ModuleType(__name__ + ".linalg")
_sys.modules[linalg.__name__] = linalg
for _ln in _registry.list_ops():
    if _ln.startswith("linalg_"):
        setattr(linalg, _ln[len("linalg_"):], getattr(_this, _ln))

# contrib submodule (foreach/while_loop/cond + contrib ops)
contrib = _types.ModuleType(__name__ + ".contrib")
_sys.modules[contrib.__name__] = contrib
from ..ops.control_flow import cond, foreach, while_loop  # noqa: E402

contrib.foreach = foreach
contrib.while_loop = while_loop
contrib.cond = cond
def _contrib_getattr(name):
    """Any registry op resolves under nd.contrib (the reference's
    generated contrib namespace covers every _contrib_* registration).
    Delegates to the nd module resolver so nd.contrib.X IS nd.X."""
    schema = _registry.find_op(name) or _registry.find_op(f"_contrib_{name}")
    if schema is not None and "nd" in schema.namespaces:
        fn = getattr(_this, schema.name)    # shared wrapper (one identity)
        setattr(contrib, name, fn)
        return fn
    raise AttributeError(f"module '{contrib.__name__}' has no attribute "
                         f"'{name}'")


contrib.__getattr__ = _contrib_getattr

def to_dlpack_for_read(data):
    from ..dlpack import to_dlpack_for_read as _f

    return _f(data)


def to_dlpack_for_write(data):
    from ..dlpack import to_dlpack_for_write as _f

    return _f(data)


def from_dlpack(ext):
    from ..dlpack import from_dlpack as _f

    return _f(ext)


__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "waitall", "save", "load", "concatenate", "random", "linalg",
           "contrib", "invoke", "to_dlpack_for_read", "to_dlpack_for_write",
           "from_dlpack"]
