"""Reference (Apache MXNet) binary ``.params`` format: read and write.

The migration story: checkpoints produced by the reference framework load
directly here, and checkpoints saved with ``save_legacy`` load in the
reference.  Layout reverse-engineered from the reference's serializers
(behavioral spec, fresh implementation):

- file header (``src/ndarray/ndarray.cc:1930`` NDArray::Save list form):
  uint64 magic ``0x112``, uint64 reserved, dmlc ``vector<NDArray>``
  (uint64 count + per-element NDArray record), dmlc ``vector<string>``
  (uint64 count + per-string uint64 length + bytes)
- NDArray record (``ndarray.cc:1697``): uint32 version magic
  (V1 ``0xF993fac8`` int64 shapes / V2 ``0xF993fac9`` +storage type /
  V3 ``0xF993faca`` np-shape semantics; anything else = ancient format
  where the magic IS the uint32 ndim followed by uint32 extents);
  V2/V3 add int32 storage type (sparse adds aux shapes/types — dense
  only here); TShape = int32 ndim + int64[ndim] (uint32[ndim] for the
  ancient form); Context = int32 dev_type + int32 dev_id
  (``include/mxnet/base.h:145``); int32 dtype flag (mshadow order);
  raw little-endian data bytes.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple, Union

import numpy as onp

LIST_MAGIC = 0x112
V1_MAGIC = 0xF993FAC8
V2_MAGIC = 0xF993FAC9
V3_MAGIC = 0xF993FACA

# mshadow type flags (include/mxnet/base.h TypeFlag order)
_FLAG_TO_DTYPE = {
    0: onp.float32, 1: onp.float64, 2: onp.float16, 3: onp.uint8,
    4: onp.int32, 5: onp.int8, 6: onp.int64, 7: onp.bool_,
}
_DTYPE_TO_FLAG = {onp.dtype(v): k for k, v in _FLAG_TO_DTYPE.items()}


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise ValueError("truncated legacy .params file")
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]

    def i32(self) -> int:
        return struct.unpack("<i", self.take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self.take(8))[0]


def _read_shape(r: _Reader, int64_ext: bool, ndim: int = None) -> Tuple:
    if ndim is None:
        ndim = r.i32()
    if ndim < 0:          # np-shape "unknown" marker — only for none arrays
        return None
    fmt, size = ("<q", 8) if int64_ext else ("<I", 4)
    return tuple(struct.unpack(fmt, r.take(size))[0] for _ in range(ndim))


def _read_ndarray(r: _Reader) -> onp.ndarray:
    magic = r.u32()
    np_shape = magic == V3_MAGIC
    if magic in (V2_MAGIC, V3_MAGIC):
        stype = r.i32()
        if stype != 0:    # kDefaultStorage == 0 (ndarray.h:60)
            raise NotImplementedError(
                "legacy sparse (row_sparse/csr) records are not supported; "
                "densify in the reference before exporting")
        shape = _read_shape(r, int64_ext=True)
    elif magic == V1_MAGIC:
        shape = _read_shape(r, int64_ext=True)
    else:                 # ancient: magic IS the ndim, uint32 extents
        shape = _read_shape(r, int64_ext=False, ndim=magic)
    # "none" records END here — no ctx/dtype/data follow (ndarray.cc Load:
    # legacy semantics: ndim == 0; np semantics: unknown shape ndim == -1)
    if shape is None or (not np_shape and len(shape) == 0):
        return onp.zeros((0,), onp.float32)
    r.i32()               # dev_type
    r.i32()               # dev_id
    flag = r.i32()
    dtype = _FLAG_TO_DTYPE.get(flag)
    if dtype is None:
        raise ValueError(f"unknown legacy dtype flag {flag}")
    count = 1
    for d in shape:
        count *= d
    data = onp.frombuffer(r.take(count * onp.dtype(dtype).itemsize),
                          dtype=dtype)
    return data.reshape(shape).copy()


def is_legacy_file(head: bytes) -> bool:
    return len(head) >= 8 and struct.unpack("<Q", head[:8])[0] == LIST_MAGIC


def load_if_legacy(fname: str):
    """Single detection point: the legacy payload if ``fname`` carries the
    reference magic, else None (caller falls through to its own format)."""
    with open(fname, "rb") as f:
        head = f.read(8)
    if not is_legacy_file(head):
        return None
    return load_legacy(fname)


def load_legacy(fname: str):
    """Load a reference-format .params file -> dict (named) or list."""
    with open(fname, "rb") as f:
        r = _Reader(f.read())
    if r.u64() != LIST_MAGIC:
        raise ValueError(f"{fname} is not a legacy MXNet NDArray file")
    r.u64()               # reserved
    arrays = [_read_ndarray(r) for _ in range(r.u64())]
    names: List[str] = []
    for _ in range(r.u64()):
        names.append(r.take(r.u64()).decode())
    if names and len(names) != len(arrays):
        raise ValueError("corrupt legacy file: name/array count mismatch")
    if names:
        return dict(zip(names, arrays))
    return arrays


def save_legacy(fname: str, data: Union[Dict[str, onp.ndarray],
                                        List[onp.ndarray]]) -> None:
    """Write arrays in the reference's V2 dense format, loadable by the
    reference's ``mx.nd.load``."""
    if isinstance(data, dict):
        names = list(data)
        arrays = [onp.asarray(data[n]) for n in names]
    else:
        names = []
        arrays = [onp.asarray(a) for a in data]
    out = [struct.pack("<QQ", LIST_MAGIC, 0), struct.pack("<Q", len(arrays))]
    for a in arrays:
        if a.dtype not in _DTYPE_TO_FLAG:
            raise TypeError(f"dtype {a.dtype} has no legacy flag (cast "
                            "bf16 etc. to float32 first)")
        if a.ndim == 0 or a.size == 0:
            # legacy (non-np) V2 semantics treat ndim==0 as a "none"
            # record with no payload; writing one would desync the
            # reference's loader on the NEXT record
            raise ValueError(
                "legacy format cannot represent 0-d or zero-size arrays "
                f"(shape {a.shape}); reshape scalars to (1,) first")
        out.append(struct.pack("<Ii", V2_MAGIC, 0))          # V2, dense
        out.append(struct.pack("<i", a.ndim))
        out.append(struct.pack(f"<{a.ndim}q", *a.shape))
        out.append(struct.pack("<ii", 1, 0))                  # cpu(0)
        out.append(struct.pack("<i", _DTYPE_TO_FLAG[a.dtype]))
        out.append(onp.ascontiguousarray(a).tobytes())
    out.append(struct.pack("<Q", len(names)))
    for n in names:
        raw = n.encode()
        out.append(struct.pack("<Q", len(raw)))
        out.append(raw)
    with open(fname, "wb") as f:
        f.write(b"".join(out))
