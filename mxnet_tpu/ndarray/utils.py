"""NDArray save/load (reference ``src/ndarray/ndarray.cc`` Save/Load +
``python/mxnet/ndarray/utils.py:149-222``).

Format: a single ``.npz`` container.  List saves use keys ``arr_0..n``;
dict saves use the user keys prefixed with ``k:``.  This replaces the
reference's dmlc serialized header + raw chunks with a standard,
version-tolerant container (numpy owns the compat story).
"""
from __future__ import annotations

import os
from typing import Dict, List, Union

import numpy as onp

from ..context import Context, cpu
from .ndarray import NDArray, array

__all__ = ["save", "load", "save_legacy", "imdecode"]


def save(fname: str, data):
    if isinstance(data, NDArray):
        data = [data]
    payload = {}
    if isinstance(data, dict):
        for k, v in data.items():
            if not isinstance(v, NDArray):
                raise TypeError("save only supports NDArray values")
            payload["k:" + k] = v.asnumpy()
    elif isinstance(data, (list, tuple)):
        for i, v in enumerate(data):
            if not isinstance(v, NDArray):
                raise TypeError("save only supports NDArray values")
            payload[f"arr_{i}"] = v.asnumpy()
    else:
        raise TypeError(f"cannot save {type(data)}")
    with open(fname, "wb") as f:
        onp.savez(f, **payload)


def save_legacy(fname: str, data):
    """Write the reference's binary .params format (loadable by Apache
    MXNet's ``mx.nd.load`` — the export half of the migration story)."""
    from . import legacy_format

    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        if not all(isinstance(v, NDArray) for v in data.values()):
            raise TypeError("save_legacy only supports NDArray values")
        payload = {k: v.asnumpy() for k, v in data.items()}
    elif isinstance(data, (list, tuple)):
        if not all(isinstance(v, NDArray) for v in data):
            raise TypeError("save_legacy only supports NDArray values")
        payload = [v.asnumpy() for v in data]
    else:
        raise TypeError(f"cannot save {type(data)}")
    legacy_format.save_legacy(fname, payload)


def load(fname: str, ctx: Context = None):
    # auto-detect the reference's binary format (magic 0x112): real
    # Apache-MXNet checkpoints load transparently
    from . import legacy_format

    out = legacy_format.load_if_legacy(fname)
    if out is not None:
        if isinstance(out, dict):
            return {k: array(v, ctx=ctx) for k, v in out.items()}
        return [array(v, ctx=ctx) for v in out]
    with onp.load(fname, allow_pickle=False) as z:
        keys = list(z.keys())
        if keys and keys[0].startswith("k:"):
            return {k[2:]: array(z[k], ctx=ctx) for k in keys}
        out: List[NDArray] = []
        for i in range(len(keys)):
            out.append(array(z[f"arr_{i}"], ctx=ctx))
        return out


def imdecode(buf, flag=1, to_rgb=True):
    raise NotImplementedError("use mx.image.imdecode")
