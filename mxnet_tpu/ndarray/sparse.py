"""Sparse NDArray storage types: row_sparse and csr.

Reference analog: ``include/mxnet/ndarray.h:63-82`` storage types +
``python/mxnet/ndarray/sparse.py``.  SURVEY.md §7 scopes TPU sparse to what
is load-bearing: **row_sparse embedding gradients** (large vocab, few rows
touched per step) and their optimizer updates.  Design: a RowSparseNDArray
keeps (indices, values) host-free on device; `sparse update` ops apply via
``at[].add`` scatters which XLA lowers to efficient dynamic-update-slices —
no giant dense gradient materializes.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..context import Context, current_context
from .ndarray import NDArray, _wrap

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "zeros", "dot", "cast_storage", "retain", "add_n"]


class BaseSparseNDArray:
    """Common surface mirrored from the reference sparse arrays."""

    shape: Tuple[int, ...]

    @property
    def ndim(self):
        return len(self.shape)

    def __repr__(self):
        return (f"<{type(self).__name__} {'x'.join(map(str, self.shape))} "
                f"@{self._ctx}>")

    def wait_to_read(self):
        pass


class RowSparseNDArray(BaseSparseNDArray):
    """Rows at ``indices`` hold ``data``; all other rows are zero
    (reference kRowSparseStorage)."""

    stype = "row_sparse"

    def __init__(self, data, indices, shape, ctx: Optional[Context] = None):
        self._ctx = ctx or current_context()
        self.data = data if isinstance(data, jax.Array) else jnp.asarray(data)
        self.indices = (indices if isinstance(indices, jax.Array)
                        else jnp.asarray(indices, jnp.int32))
        self.shape = tuple(shape)
        if self.data.shape[0] != self.indices.shape[0]:
            raise MXNetError("data and indices row counts differ")

    @property
    def dtype(self):
        return onp.dtype(self.data.dtype)

    @property
    def context(self):
        return self._ctx

    ctx = context

    def asnumpy(self) -> onp.ndarray:
        out = onp.zeros(self.shape, self.dtype)
        # duplicate indices accumulate, like the reference's kAddTo merge
        onp.add.at(out, onp.asarray(self.indices), onp.asarray(self.data))
        return out

    def tostype(self, stype: str):
        if stype == "row_sparse":
            return self
        if stype == "default":
            dense = jnp.zeros(self.shape, self.data.dtype)
            dense = dense.at[self.indices].add(self.data)
            return _wrap(dense, self._ctx)
        raise MXNetError(f"cannot convert row_sparse to {stype}")

    def todense(self) -> NDArray:
        return self.tostype("default")

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            other.data = self.data
            other.indices = self.indices
            return other
        return self.todense().copyto(other)

    def retain(self, row_ids) -> "RowSparseNDArray":
        """Keep only the requested rows (reference sparse.retain — the
        row_sparse_pull building block)."""
        row_ids = jnp.asarray(
            row_ids._data if isinstance(row_ids, NDArray) else row_ids,
            jnp.int32)
        # dense lookup per requested id (ids is small)
        dense = jnp.zeros((self.shape[0],) + self.data.shape[1:],
                          self.data.dtype).at[self.indices].add(self.data)
        return RowSparseNDArray(dense[row_ids], row_ids, self.shape,
                                self._ctx)

    def __add__(self, other):
        if isinstance(other, RowSparseNDArray):
            return RowSparseNDArray(
                jnp.concatenate([self.data, other.data]),
                jnp.concatenate([self.indices, other.indices]),
                self.shape, self._ctx)
        raise TypeError("row_sparse + dense: densify first via tostype")

    def compact(self) -> "RowSparseNDArray":
        """Merge duplicate indices (sorted unique rows)."""
        uniq, inv = jnp.unique(self.indices, return_inverse=True,
                               size=self.indices.shape[0],
                               fill_value=self.shape[0])
        summed = jnp.zeros((uniq.shape[0],) + self.data.shape[1:],
                           self.data.dtype).at[inv].add(self.data)
        keep = uniq < self.shape[0]
        return RowSparseNDArray(summed[keep], uniq[keep], self.shape,
                                self._ctx)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (reference kCSRStorage)."""

    stype = "csr"

    def __init__(self, data, indices, indptr, shape,
                 ctx: Optional[Context] = None):
        self._ctx = ctx or current_context()
        self.data = jnp.asarray(data)
        self.indices = jnp.asarray(indices, jnp.int32)
        self.indptr = jnp.asarray(indptr, jnp.int32)
        self.shape = tuple(shape)

    @property
    def dtype(self):
        return onp.dtype(self.data.dtype)

    @property
    def context(self):
        return self._ctx

    ctx = context

    def asnumpy(self) -> onp.ndarray:
        out = onp.zeros(self.shape, self.dtype)
        indptr = onp.asarray(self.indptr)
        indices = onp.asarray(self.indices)
        data = onp.asarray(self.data)
        for i in range(self.shape[0]):
            sl = slice(indptr[i], indptr[i + 1])
            out[i, indices[sl]] = data[sl]
        return out

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return _wrap(jnp.asarray(self.asnumpy()), self._ctx)
        raise MXNetError(f"cannot convert csr to {stype}")

    def todense(self):
        return self.tostype("default")

    def dot(self, dense: NDArray) -> NDArray:
        """csr @ dense via segment-sum (XLA-friendly SpMV/SpMM) — the
        no-transpose row of the module-level :func:`dot` stype matrix."""
        return dot(self, dense)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create a RowSparseNDArray from (data, indices) or a dense source
    (reference sparse.row_sparse_array)."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = jnp.asarray(data, dtype)
        return RowSparseNDArray(data, jnp.asarray(indices, jnp.int32),
                                shape, ctx)
    dense = onp.asarray(arg1.asnumpy() if isinstance(arg1, NDArray)
                        else arg1, dtype)
    nz_rows = onp.where(onp.any(dense != 0, axis=tuple(
        range(1, dense.ndim))))[0]
    return RowSparseNDArray(jnp.asarray(dense[nz_rows]),
                            jnp.asarray(nz_rows, jnp.int32),
                            shape or dense.shape, ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Create a CSRNDArray (reference sparse.csr_matrix)."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(jnp.asarray(data, dtype), indices, indptr, shape,
                          ctx)
    dense = onp.asarray(arg1.asnumpy() if isinstance(arg1, NDArray)
                        else arg1, dtype)
    return _dense_to_csr(dense, ctx, shape)


def zeros(stype, shape, ctx=None, dtype=None):
    dtype = dtype or onp.float32
    if stype == "row_sparse":
        ncol = shape[1:] if len(shape) > 1 else ()
        return RowSparseNDArray(jnp.zeros((0,) + tuple(ncol), dtype),
                                jnp.zeros((0,), jnp.int32), shape, ctx)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dtype), [], [0] * (shape[0] + 1),
                          shape, ctx)
    raise MXNetError(f"unknown stype {stype}")


# ---------------------------------------------------------------------------
# sparse optimizer updates (reference optimizer_op.cc sparse variants):
# touch ONLY the gradient's rows — the XLA scatter path
# ---------------------------------------------------------------------------


def sgd_update(weight: NDArray, grad: RowSparseNDArray, lr, wd=0.0,
               rescale_grad=1.0):
    g = grad.compact()
    rows = weight._data[g.indices]
    upd = rows - lr * (rescale_grad * g.data + wd * rows)
    weight._set_data(weight._data.at[g.indices].set(upd))
    return weight


def adam_update(weight: NDArray, grad: RowSparseNDArray, mean: NDArray,
                var: NDArray, lr, beta1=0.9, beta2=0.999, epsilon=1e-8,
                wd=0.0, rescale_grad=1.0, lazy_update=True):
    """Lazy adam: moments update only on touched rows (reference
    adam_update w/ lazy_update for row_sparse grads)."""
    g = grad.compact()
    idx = g.indices
    gd = rescale_grad * g.data + wd * weight._data[idx]
    m_rows = beta1 * mean._data[idx] + (1 - beta1) * gd
    v_rows = beta2 * var._data[idx] + (1 - beta2) * gd * gd
    mean._set_data(mean._data.at[idx].set(m_rows))
    var._set_data(var._data.at[idx].set(v_rows))
    upd = weight._data[idx] - lr * m_rows / (jnp.sqrt(v_rows) + epsilon)
    weight._set_data(weight._data.at[idx].set(upd))
    return weight


# ---------------------------------------------------------------------------
# storage-type matrix ops (round-5 breadth: reference
# src/operator/tensor/dot-inl.h sparse dot family and
# src/operator/tensor/cast_storage.cc path matrix)
# ---------------------------------------------------------------------------


def _dense_to_csr(dense: onp.ndarray, ctx=None, shape=None) -> "CSRNDArray":
    """Vectorized dense -> CSR (no per-row Python loop).  ``shape`` may
    declare extra all-zero trailing rows (indptr is padded to match)."""
    shape = tuple(shape) if shape is not None else dense.shape
    rows, cols = onp.nonzero(dense)
    counts = onp.bincount(rows, minlength=shape[0])
    indptr = onp.concatenate([[0], onp.cumsum(counts)])
    return CSRNDArray(dense[rows, cols], cols.astype(onp.int32),
                      indptr.astype(onp.int32), shape, ctx)


def _as_dense_jax(x):
    if isinstance(x, (RowSparseNDArray, CSRNDArray)):
        return x.todense()._data
    if isinstance(x, NDArray):
        return x._data
    return jnp.asarray(x)


def dot(lhs, rhs, transpose_a=False, transpose_b=False, forward_stype=None):
    """Sparse-aware ``dot`` implementing the reference storage-type matrix
    (``src/operator/tensor/dot.cc:54-68`` docstring):

    - dot(csr, default)                                     -> default
    - dot(csr, default, transpose_a=True)                   -> default
    - dot(csr, default, transpose_a=True,
          forward_stype='row_sparse')                       -> row_sparse
    - dot(csr, row_sparse)                                  -> default
    - dot(default, csr)                                     -> csr
    - dot(default, csr, forward_stype='default')            -> default
    - dot(default, csr, transpose_b=True,
          forward_stype='default')                          -> default

    Any other combination falls back to dense computation with default
    output, exactly like the reference's FallBackCompute.  TPU-first note:
    every branch lowers to gather/segment-sum/scatter or an MXU matmul —
    the CSR *container* is host metadata; no device CSR kernels exist
    (SURVEY §7 sparse scoping).
    """
    if isinstance(lhs, CSRNDArray):
        rd = _as_dense_jax(rhs)
        squeeze = False
        if rd.ndim == 1:
            if transpose_b:
                raise MXNetError("dot: cannot transpose a 1-D rhs")
            rd = rd[:, None]                    # SpMV as single-column SpMM
            squeeze = True
        elif transpose_b:
            rd = rd.T
        # row id per nonzero from indptr (shared by both orientations)
        nnz = lhs.data.shape[0]
        row_ids = jnp.searchsorted(lhs.indptr[1:], jnp.arange(nnz),
                                   side="right").astype(jnp.int32)
        if not transpose_a:
            # out[r] += v * rhs[c]: segment-sum over csr rows
            contrib = lhs.data[:, None] * rd[lhs.indices]
            out = jax.ops.segment_sum(contrib, row_ids,
                                      num_segments=lhs.shape[0])
            out = out.astype(rd.dtype)
        else:
            # out[c] += v * rhs[r]  for each nonzero (r, c, v)
            out = jnp.zeros((lhs.shape[1], rd.shape[1]), rd.dtype)
            out = out.at[lhs.indices].add(lhs.data[:, None] * rd[row_ids])
            if forward_stype == "row_sparse":
                uniq = jnp.unique(lhs.indices)
                vals = out[uniq, 0] if squeeze else out[uniq]
                shape = (out.shape[0],) if squeeze else out.shape
                return RowSparseNDArray(vals, uniq.astype(jnp.int32),
                                        shape, lhs._ctx)
        if squeeze:
            out = out[:, 0]
        return _wrap(out, lhs._ctx)
    if isinstance(rhs, CSRNDArray) and not isinstance(lhs, CSRNDArray):
        ld = _as_dense_jax(lhs)
        if transpose_a:
            ld = ld.T
        rd = rhs.todense()._data
        if transpose_b:
            rd = rd.T
        out = ld @ rd
        if (forward_stype in (None, "csr")) and not transpose_b \
                and not transpose_a:
            return _dense_to_csr(onp.asarray(out), rhs._ctx)
        return _wrap(out, rhs._ctx)
    # dense x dense / fallback: densify everything (FallBackCompute)
    ld = _as_dense_jax(lhs)
    rd = _as_dense_jax(rhs)
    if transpose_a:
        ld = ld.T
    if transpose_b:
        rd = rd.T
    return _wrap(ld @ rd, current_context())


def cast_storage(arr, stype: str):
    """Container-level storage cast implementing the full reference path
    matrix (``src/operator/tensor/cast_storage.cc``): default <-> csr,
    default <-> row_sparse, sparse -> default, and identity casts.
    Sparse-to-other-sparse goes through dense like the reference."""
    src = getattr(arr, "stype", "default")
    if stype == src:
        return arr
    if isinstance(arr, (RowSparseNDArray, CSRNDArray)):
        dense = arr.todense()
        if stype == "default":
            return dense
        return cast_storage(dense, stype)           # csr <-> row_sparse
    if stype == "csr":
        d = arr.asnumpy() if isinstance(arr, NDArray) else onp.asarray(arr)
        if d.ndim != 2:
            raise MXNetError("csr storage requires a 2-D array")
        return _dense_to_csr(d, getattr(arr, "_ctx", None))
    if stype == "row_sparse":
        return row_sparse_array(arr, ctx=getattr(arr, "_ctx", None))
    raise MXNetError(f"cast_storage: unknown stype {stype}")


def retain(arr: RowSparseNDArray, indices) -> RowSparseNDArray:
    """Module-level retain (reference mx.nd.sparse.retain)."""
    if not isinstance(arr, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    return arr.retain(indices)


def add_n(*arrays):
    """Sum row_sparse arrays without densifying (reference ElementwiseSum
    sparse branch, src/operator/tensor/elemwise_sum.cc)."""
    rsp = [a for a in arrays if isinstance(a, RowSparseNDArray)]
    if len(rsp) == len(arrays) and rsp:
        out = rsp[0]
        for a in rsp[1:]:
            out = out + a
        return out.compact()
    dense = sum(_as_dense_jax(a) for a in arrays)
    return _wrap(dense, current_context())
