"""Sparse NDArray storage types: row_sparse and csr.

Reference analog: ``include/mxnet/ndarray.h:63-82`` storage types +
``python/mxnet/ndarray/sparse.py``.  SURVEY.md §7 scopes TPU sparse to what
is load-bearing: **row_sparse embedding gradients** (large vocab, few rows
touched per step) and their optimizer updates.  Design: a RowSparseNDArray
keeps (indices, values) host-free on device; `sparse update` ops apply via
``at[].add`` scatters which XLA lowers to efficient dynamic-update-slices —
no giant dense gradient materializes.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import MXNetError
from ..context import Context, current_context
from .ndarray import NDArray, _wrap

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "zeros"]


class BaseSparseNDArray:
    """Common surface mirrored from the reference sparse arrays."""

    shape: Tuple[int, ...]

    @property
    def ndim(self):
        return len(self.shape)

    def __repr__(self):
        return (f"<{type(self).__name__} {'x'.join(map(str, self.shape))} "
                f"@{self._ctx}>")

    def wait_to_read(self):
        pass


class RowSparseNDArray(BaseSparseNDArray):
    """Rows at ``indices`` hold ``data``; all other rows are zero
    (reference kRowSparseStorage)."""

    stype = "row_sparse"

    def __init__(self, data, indices, shape, ctx: Optional[Context] = None):
        self._ctx = ctx or current_context()
        self.data = data if isinstance(data, jax.Array) else jnp.asarray(data)
        self.indices = (indices if isinstance(indices, jax.Array)
                        else jnp.asarray(indices, jnp.int32))
        self.shape = tuple(shape)
        if self.data.shape[0] != self.indices.shape[0]:
            raise MXNetError("data and indices row counts differ")

    @property
    def dtype(self):
        return onp.dtype(self.data.dtype)

    @property
    def context(self):
        return self._ctx

    ctx = context

    def asnumpy(self) -> onp.ndarray:
        out = onp.zeros(self.shape, self.dtype)
        # duplicate indices accumulate, like the reference's kAddTo merge
        onp.add.at(out, onp.asarray(self.indices), onp.asarray(self.data))
        return out

    def tostype(self, stype: str):
        if stype == "row_sparse":
            return self
        if stype == "default":
            dense = jnp.zeros(self.shape, self.data.dtype)
            dense = dense.at[self.indices].add(self.data)
            return _wrap(dense, self._ctx)
        raise MXNetError(f"cannot convert row_sparse to {stype}")

    def todense(self) -> NDArray:
        return self.tostype("default")

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            other.data = self.data
            other.indices = self.indices
            return other
        return self.todense().copyto(other)

    def retain(self, row_ids) -> "RowSparseNDArray":
        """Keep only the requested rows (reference sparse.retain — the
        row_sparse_pull building block)."""
        row_ids = jnp.asarray(
            row_ids._data if isinstance(row_ids, NDArray) else row_ids,
            jnp.int32)
        # dense lookup per requested id (ids is small)
        dense = jnp.zeros((self.shape[0],) + self.data.shape[1:],
                          self.data.dtype).at[self.indices].add(self.data)
        return RowSparseNDArray(dense[row_ids], row_ids, self.shape,
                                self._ctx)

    def __add__(self, other):
        if isinstance(other, RowSparseNDArray):
            return RowSparseNDArray(
                jnp.concatenate([self.data, other.data]),
                jnp.concatenate([self.indices, other.indices]),
                self.shape, self._ctx)
        raise TypeError("row_sparse + dense: densify first via tostype")

    def compact(self) -> "RowSparseNDArray":
        """Merge duplicate indices (sorted unique rows)."""
        uniq, inv = jnp.unique(self.indices, return_inverse=True,
                               size=self.indices.shape[0],
                               fill_value=self.shape[0])
        summed = jnp.zeros((uniq.shape[0],) + self.data.shape[1:],
                           self.data.dtype).at[inv].add(self.data)
        keep = uniq < self.shape[0]
        return RowSparseNDArray(summed[keep], uniq[keep], self.shape,
                                self._ctx)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (reference kCSRStorage)."""

    stype = "csr"

    def __init__(self, data, indices, indptr, shape,
                 ctx: Optional[Context] = None):
        self._ctx = ctx or current_context()
        self.data = jnp.asarray(data)
        self.indices = jnp.asarray(indices, jnp.int32)
        self.indptr = jnp.asarray(indptr, jnp.int32)
        self.shape = tuple(shape)

    @property
    def dtype(self):
        return onp.dtype(self.data.dtype)

    @property
    def context(self):
        return self._ctx

    ctx = context

    def asnumpy(self) -> onp.ndarray:
        out = onp.zeros(self.shape, self.dtype)
        indptr = onp.asarray(self.indptr)
        indices = onp.asarray(self.indices)
        data = onp.asarray(self.data)
        for i in range(self.shape[0]):
            sl = slice(indptr[i], indptr[i + 1])
            out[i, indices[sl]] = data[sl]
        return out

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return _wrap(jnp.asarray(self.asnumpy()), self._ctx)
        raise MXNetError(f"cannot convert csr to {stype}")

    def todense(self):
        return self.tostype("default")

    def dot(self, dense: NDArray) -> NDArray:
        """csr @ dense via segment-sum (XLA-friendly SpMV/SpMM)."""
        d = dense._data if isinstance(dense, NDArray) else jnp.asarray(dense)
        # row id per nonzero from indptr
        nnz = self.data.shape[0]
        row_ids = jnp.searchsorted(self.indptr[1:], jnp.arange(nnz),
                                   side="right").astype(jnp.int32)
        contrib = self.data[:, None] * d[self.indices]
        out = jax.ops.segment_sum(contrib, row_ids,
                                  num_segments=self.shape[0])
        return _wrap(out.astype(d.dtype), self._ctx)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create a RowSparseNDArray from (data, indices) or a dense source
    (reference sparse.row_sparse_array)."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = jnp.asarray(data, dtype)
        return RowSparseNDArray(data, jnp.asarray(indices, jnp.int32),
                                shape, ctx)
    dense = onp.asarray(arg1.asnumpy() if isinstance(arg1, NDArray)
                        else arg1, dtype)
    nz_rows = onp.where(onp.any(dense != 0, axis=tuple(
        range(1, dense.ndim))))[0]
    return RowSparseNDArray(jnp.asarray(dense[nz_rows]),
                            jnp.asarray(nz_rows, jnp.int32),
                            shape or dense.shape, ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Create a CSRNDArray (reference sparse.csr_matrix)."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        return CSRNDArray(jnp.asarray(data, dtype), indices, indptr, shape,
                          ctx)
    dense = onp.asarray(arg1.asnumpy() if isinstance(arg1, NDArray)
                        else arg1, dtype)
    indptr = [0]
    indices = []
    data = []
    for row in dense:
        nz = onp.nonzero(row)[0]
        indices.extend(nz.tolist())
        data.extend(row[nz].tolist())
        indptr.append(len(indices))
    return CSRNDArray(onp.asarray(data, dense.dtype), indices, indptr,
                      shape or dense.shape, ctx)


def zeros(stype, shape, ctx=None, dtype=None):
    dtype = dtype or onp.float32
    if stype == "row_sparse":
        ncol = shape[1:] if len(shape) > 1 else ()
        return RowSparseNDArray(jnp.zeros((0,) + tuple(ncol), dtype),
                                jnp.zeros((0,), jnp.int32), shape, ctx)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dtype), [], [0] * (shape[0] + 1),
                          shape, ctx)
    raise MXNetError(f"unknown stype {stype}")


# ---------------------------------------------------------------------------
# sparse optimizer updates (reference optimizer_op.cc sparse variants):
# touch ONLY the gradient's rows — the XLA scatter path
# ---------------------------------------------------------------------------


def sgd_update(weight: NDArray, grad: RowSparseNDArray, lr, wd=0.0,
               rescale_grad=1.0):
    g = grad.compact()
    rows = weight._data[g.indices]
    upd = rows - lr * (rescale_grad * g.data + wd * rows)
    weight._set_data(weight._data.at[g.indices].set(upd))
    return weight


def adam_update(weight: NDArray, grad: RowSparseNDArray, mean: NDArray,
                var: NDArray, lr, beta1=0.9, beta2=0.999, epsilon=1e-8,
                wd=0.0, rescale_grad=1.0, lazy_update=True):
    """Lazy adam: moments update only on touched rows (reference
    adam_update w/ lazy_update for row_sparse grads)."""
    g = grad.compact()
    idx = g.indices
    gd = rescale_grad * g.data + wd * weight._data[idx]
    m_rows = beta1 * mean._data[idx] + (1 - beta1) * gd
    v_rows = beta2 * var._data[idx] + (1 - beta2) * gd * gd
    mean._set_data(mean._data.at[idx].set(m_rows))
    var._set_data(var._data.at[idx].set(v_rows))
    upd = weight._data[idx] - lr * m_rows / (jnp.sqrt(v_rows) + epsilon)
    weight._set_data(weight._data.at[idx].set(upd))
    return weight
