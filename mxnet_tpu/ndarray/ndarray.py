"""NDArray: the imperative array type.

TPU-native re-design of the reference NDArray (``include/mxnet/ndarray.h``,
``python/mxnet/ndarray/ndarray.py``).  The reference pairs each array with a
dependency-engine variable so mutation is ordered asynchronously; here the
storage is an immutable ``jax.Array`` living in device memory (HBM via PJRT)
and *mutation is modeled as replacement*: every write installs a fresh
jax.Array and bumps ``version`` (the engine-var version analog).  JAX's async
dispatch supplies the "ops return immediately / sync at asnumpy()" illusion
that the reference built the threaded engine for:

- ``wait_to_read``/``wait_to_write``  -> ``block_until_ready`` on the buffer
- exceptions thrown by device code surface at sync points (MXNetError), the
  reference's ``ExceptionRef`` story (src/engine/threaded_engine.h:64).

Operator dispatch (``invoke``) is the analog of ``MXImperativeInvokeImpl``
(src/c_api/c_api_ndarray.cc:91): unwrap arrays, run the registered pure-JAX
fn (optionally under ``jax.vjp`` when autograd is recording), wrap outputs.
"""
from __future__ import annotations

import numbers
import time as _time
from collections import OrderedDict
from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as onp

from .. import autograd
from .. import engine as _engine
from .. import profiler as _profiler
from .. import program_store as _pstore
from ..base import (MXNetError, S64_DEMOTING_PLATFORMS, bounded_cache_put,
                    enable_x64 as _enable_x64, int32_overflow_dim,
                    pow2_col_factor)
from ..context import Context, current_context
from ..ops.registry import OpSchema, find_op, get_op

__all__ = ["NDArray", "invoke", "array", "_wrap", "_on_tape"]

_float_types = (onp.float16, onp.float32, onp.float64, jnp.bfloat16)

# installed by mx.amp.init(): fn(op_name, [jax arrays]) -> [jax arrays];
# _amp_generation bumps on every init/uninit so hybridized-graph caches
# keyed on it retrace under the new policy
_amp_policy = None
_amp_generation = 0


def _dtype_np(dtype) -> onp.dtype:
    if dtype is None:
        return onp.dtype("float32")
    if dtype == jnp.bfloat16 or (isinstance(dtype, str) and dtype == "bfloat16"):
        return jnp.bfloat16  # type: ignore[return-value]
    return onp.dtype(dtype)


class NDArray:
    """An n-dimensional array on a device context."""

    __slots__ = (
        "_data",
        "_ctx",
        "_version",
        "_grad",
        "_ag_grad_req",
        "_ag_node",
        "_ag_out_index",
        "_deferred_init",
        "_dc_sym",
        "_conv_src",   # producer tag for trace-time conv+BN fusion
        "__weakref__",
    )

    # numpy interop precedence (reference ndarray.py __array_priority__)
    __array_priority__ = 1000.0

    def __init__(self, data, ctx: Optional[Context] = None, dtype=None):
        if ctx is None:
            ctx = current_context()
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array):
            want = _dtype_np(dtype) if dtype is not None else None
            src = getattr(data, "dtype", None)
            if onp.dtype(want or src or onp.float32) in (onp.dtype("int64"),
                                                         onp.dtype("uint64")):
                # honest 64-bit integers (same policy as shape_array):
                # the x32 default would silently truncate graph/edge ids.
                # device_put must stay INSIDE the x64 scope — outside it
                # the transfer canonicalizes through int32, wrapping
                # values past 2^31 even though the dtype reads int64
                with _enable_x64(True):
                    data = jnp.asarray(data, dtype=want)
                    data = jax.device_put(data, ctx.jax_device)
            else:
                data = jnp.asarray(data, dtype=want)
                data = jax.device_put(data, ctx.jax_device)
        elif dtype is not None and data.dtype != _dtype_np(dtype):
            data = data.astype(_dtype_np(dtype))
        self._data = data
        self._ctx = ctx
        self._version = 0
        self._grad = None
        self._ag_grad_req = "null"
        self._ag_node = None
        self._ag_out_index = 0
        self._deferred_init = None
        self._dc_sym = None

    # ------------------------------------------------------------------
    # core properties
    # ------------------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._data.shape)

    @property
    def dtype(self):
        dt = self._data.dtype
        return dt if dt == jnp.bfloat16 else onp.dtype(dt)

    @property
    def size(self) -> int:
        return int(onp.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def ctx(self) -> Context:
        return self._ctx

    context = ctx

    @property
    def stype(self) -> str:
        return "default"

    @property
    def T(self) -> "NDArray":
        return invoke("transpose", [self], {})

    @property
    def version(self) -> int:
        """Write-version of this array (engine var version analog)."""
        return self._version

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    # ------------------------------------------------------------------
    # mutation-as-replacement
    # ------------------------------------------------------------------
    def _set_data(self, new_data: jax.Array):
        if tuple(new_data.shape) != self.shape:
            raise MXNetError(
                f"cannot write shape {tuple(new_data.shape)} into NDArray of "
                f"shape {self.shape}"
            )
        self._data = new_data
        self._version += 1
        try:
            # a mutated array is no longer the tagged conv's output —
            # a later BatchNorm must not fuse against the pre-mutation conv
            del self._conv_src
        except AttributeError:
            pass

    # ------------------------------------------------------------------
    # sync / host transfer
    # ------------------------------------------------------------------
    def wait_to_read(self):
        try:
            self._data.block_until_ready()
        except Exception as e:  # XLA runtime errors surface here
            raise MXNetError(str(e)) from e

    def wait_to_write(self):
        self.wait_to_read()

    # standard DLPack protocol (reference dlpack.py exposes the
    # to_dlpack_* helpers; the dunder makes torch.from_dlpack(nd) work)
    def __dlpack__(self, **kwargs):
        self.wait_to_read()
        # forward the consumer's protocol args (stream sync etc.)
        return self._data.__dlpack__(**kwargs)

    def __dlpack_device__(self):
        return self._data.__dlpack_device__()

    def to_dlpack_for_read(self):
        from ..dlpack import to_dlpack_for_read

        return to_dlpack_for_read(self)

    def to_dlpack_for_write(self):
        from ..dlpack import to_dlpack_for_write

        return to_dlpack_for_write(self)

    def asnumpy(self) -> onp.ndarray:
        _HOST_SYNC.inc()
        self.wait_to_read()
        return onp.asarray(self._data)

    def item(self):
        return self.asnumpy().item()

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.asscalar())
        raise ValueError(
            "The truth value of an NDArray with multiple elements is ambiguous."
        )

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    # ------------------------------------------------------------------
    # autograd
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype=None):
        """Allocate a gradient buffer (reference ndarray.py attach_grad)."""
        grad = _wrap(jnp.zeros(self.shape, self._data.dtype), self._ctx)
        self._mark_variable(grad, grad_req)

    def _mark_variable(self, grad: "NDArray", grad_req: str):
        self._grad = grad
        self._ag_grad_req = grad_req
        self._ag_node = None

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        autograd.backward([self], [out_grad], retain_graph, train_mode)

    def detach(self) -> "NDArray":
        out = _wrap(self._data, self._ctx)
        return out

    # ------------------------------------------------------------------
    # conversion / copies
    # ------------------------------------------------------------------
    def astype(self, dtype, copy=True) -> "NDArray":
        dt = _dtype_np(dtype)
        if not copy and self._data.dtype == dt:
            return self
        return invoke("cast", [self], {"dtype": dt})

    def copy(self) -> "NDArray":
        return invoke("_copy", [self], {})

    def copyto(self, other: Union["NDArray", Context]) -> "NDArray":
        if isinstance(other, NDArray):
            other._set_data(
                jax.device_put(self._data, other._ctx.jax_device).astype(
                    other._data.dtype
                )
            )
            return other
        out = NDArray(jax.device_put(self._data, other.jax_device), ctx=other)
        return out

    def as_in_context(self, context: Context) -> "NDArray":
        if context == self._ctx:
            return self
        return self.copyto(context)

    as_in_ctx = as_in_context

    def as_np_ndarray(self):
        from ..numpy.multiarray import ndarray as np_ndarray

        out = np_ndarray.__new__(np_ndarray)
        NDArray.__init__(out, self._data, ctx=self._ctx)
        out._ag_node = self._ag_node
        out._ag_out_index = self._ag_out_index
        out._grad = self._grad
        out._ag_grad_req = self._ag_grad_req
        return out

    def as_nd_ndarray(self):
        out = NDArray.__new__(NDArray)
        NDArray.__init__(out, self._data, ctx=self._ctx)
        out._ag_node = self._ag_node
        out._ag_out_index = self._ag_out_index
        out._grad = self._grad
        out._ag_grad_req = self._ag_grad_req
        return out

    def tostype(self, stype):
        if stype == "default":
            return self
        from .sparse import cast_storage as _cast_storage

        # dense -> csr / row_sparse container (reference ndarray.py
        # tostype -> cast_storage, src/operator/tensor/cast_storage.cc)
        return _cast_storage(self, stype)

    # ------------------------------------------------------------------
    # shape ops (methods mirror reference method surface)
    # ------------------------------------------------------------------
    def reshape(self, *shape, **kwargs) -> "NDArray":
        if "shape" in kwargs:
            shape = kwargs["shape"]
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return invoke("reshape", [self], {"shape": tuple(shape)})

    def reshape_like(self, other) -> "NDArray":
        return invoke("reshape", [self], {"shape": other.shape})

    def transpose(self, *axes) -> "NDArray":
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return invoke("transpose", [self], {"axes": axes or None})

    def swapaxes(self, dim1, dim2) -> "NDArray":
        return invoke("swapaxes", [self], {"dim1": dim1, "dim2": dim2})

    def flatten(self) -> "NDArray":
        return invoke("flatten", [self], {})

    def expand_dims(self, axis) -> "NDArray":
        return invoke("expand_dims", [self], {"axis": axis})

    def squeeze(self, axis=None) -> "NDArray":
        return invoke("squeeze", [self], {"axis": axis})

    def broadcast_to(self, shape) -> "NDArray":
        return invoke("broadcast_to", [self], {"shape": tuple(shape)})

    def broadcast_like(self, other) -> "NDArray":
        return invoke("broadcast_to", [self], {"shape": other.shape})

    def tile(self, reps) -> "NDArray":
        return invoke("tile", [self], {"reps": reps})

    def repeat(self, repeats, axis=None) -> "NDArray":
        return invoke("repeat", [self], {"repeats": repeats, "axis": axis})

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return invoke(
            "split",
            [self],
            {"num_outputs": num_outputs, "axis": axis, "squeeze_axis": squeeze_axis},
        )

    def slice(self, begin, end, step=None) -> "NDArray":
        return invoke("slice", [self], {"begin": begin, "end": end, "step": step})

    def slice_axis(self, axis, begin, end) -> "NDArray":
        return invoke("slice_axis", [self], {"axis": axis, "begin": begin, "end": end})

    def take(self, indices, axis=0, mode="clip") -> "NDArray":
        return invoke("take", [self, _as_nd(indices, self._ctx)], {"axis": axis, "mode": mode})

    def one_hot(self, depth, on_value=1.0, off_value=0.0, dtype="float32"):
        return invoke("one_hot", [self], {"depth": depth, "on_value": on_value,
                                          "off_value": off_value, "dtype": dtype})

    # reductions
    def sum(self, axis=None, keepdims=False, **kw) -> "NDArray":
        return invoke("sum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False, **kw) -> "NDArray":
        return invoke("mean", [self], {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False, **kw) -> "NDArray":
        return invoke("max", [self], {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False, **kw) -> "NDArray":
        return invoke("min", [self], {"axis": axis, "keepdims": keepdims})

    def prod(self, axis=None, keepdims=False, **kw) -> "NDArray":
        return invoke("prod", [self], {"axis": axis, "keepdims": keepdims})

    def norm(self, ord=2, axis=None, keepdims=False) -> "NDArray":
        return invoke("norm", [self], {"ord": ord, "axis": axis, "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False) -> "NDArray":
        return invoke("argmax", [self], {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False) -> "NDArray":
        return invoke("argmin", [self], {"axis": axis, "keepdims": keepdims})

    def clip(self, a_min=None, a_max=None) -> "NDArray":
        return invoke("clip", [self], {"a_min": a_min, "a_max": a_max})

    def abs(self) -> "NDArray":
        return invoke("abs", [self], {})

    def sqrt(self) -> "NDArray":
        return invoke("sqrt", [self], {})

    def square(self) -> "NDArray":
        return invoke("square", [self], {})

    def exp(self) -> "NDArray":
        return invoke("exp", [self], {})

    def log(self) -> "NDArray":
        return invoke("log", [self], {})

    def relu(self) -> "NDArray":
        return invoke("relu", [self], {})

    def sigmoid(self) -> "NDArray":
        return invoke("sigmoid", [self], {})

    def tanh(self) -> "NDArray":
        return invoke("tanh", [self], {})

    def softmax(self, axis=-1) -> "NDArray":
        return invoke("softmax", [self], {"axis": axis})

    def log_softmax(self, axis=-1) -> "NDArray":
        return invoke("log_softmax", [self], {"axis": axis})

    def dot(self, other) -> "NDArray":
        return invoke("dot", [self, _as_nd(other, self._ctx)], {})

    def tolist(self):
        return self.asnumpy().tolist()

    # ------------------------------------------------------------------
    # indexing
    # ------------------------------------------------------------------
    def __getitem__(self, key) -> "NDArray":
        key = _index_unwrap(key)
        _check_int_bounds(key, self.shape)
        if _needs_x64_index(self.shape) and self._on_x64_native_backend():
            # >int32-range dims (the reference's USE_INT64_TENSOR_SIZE
            # analog): on cpu, index constants must stay s64 or XLA's
            # gather drops them as out-of-bounds after truncation.  On
            # TPU the _index op itself lowers static keys to literal-
            # bound slices (the compiler demotes s64 types wholesale).
            with _enable_x64(True):
                return invoke("_index", [self], {"key": key})
        return invoke("_index", [self], {"key": key})

    def _on_x64_native_backend(self) -> bool:
        try:
            dev = next(iter(self._data.devices()))
        except Exception:       # tracers carry no device
            return False
        return dev.platform not in S64_DEMOTING_PLATFORMS

    def __setitem__(self, key, value):
        key = _index_unwrap(key)
        if isinstance(value, NDArray):
            value = value._data
        elif isinstance(value, numbers.Number):
            pass
        else:
            value = jnp.asarray(value)
        _check_int_bounds(key, self.shape)
        if key is Ellipsis or (isinstance(key, slice) and
                               key == slice(None)):
            if isinstance(value, numbers.Number):
                self._set_data(jnp.full(self.shape, value, self._data.dtype))
            else:
                self._set_data(
                    jnp.broadcast_to(jnp.asarray(value, self._data.dtype), self.shape)
                )
        elif _needs_x64_index(self.shape):
            # NO plain-scatter path here even for small offsets: the
            # functional .at[].set implies a full-buffer copy, and any
            # copy ALONG a >2^31 dim is corrupt on the TPU runtime
            new = _big_static_set(self._data, key, value)
            if new is not None:
                self._set_data(new)
            elif self._on_x64_native_backend():
                with _enable_x64(True):
                    self._set_data(self._data.at[key].set(value))
            else:
                raise MXNetError(
                    "only static int/contiguous-slice scalar writes are "
                    "supported into a >int32-range dim on the TPU runtime "
                    "(its compiler demotes s64 indices and corrupts copies "
                    "along >2^31 dims); reshape to a 2-D view whose dims "
                    "fit int32 for general writes")
        else:
            self._set_data(self._data.at[key].set(value))

    # ------------------------------------------------------------------
    # arithmetic operators
    # ------------------------------------------------------------------
    def _binary(self, op_name, other, reverse=False):
        if isinstance(other, numbers.Number):
            args = [self]
            attrs = {"scalar": float(other), "reverse": reverse}
            return invoke(f"{op_name}_scalar", args, attrs)
        other = _as_nd(other, self._ctx)
        a, b = (other, self) if reverse else (self, other)
        return invoke(f"broadcast_{op_name}", [a, b], {})

    def _inplace(self, op_name, other):
        """In-place update.  While recording, the array takes over the
        result's tape node so gradients stay correct (mutation-as-replacement
        keeps the tape functional); in-place on a *leaf* variable during
        recording is an error, as in the reference."""
        if autograd.is_recording() and self._ag_grad_req != "null":
            raise MXNetError(
                "in-place operation on a variable with attached grad is not "
                "allowed while autograd is recording"
            )
        # snapshot: the tape must reference the pre-mutation value, not self
        # (otherwise the node's input aliases its own output -> cyclic tape)
        src = _wrap(self._data, self._ctx)
        src._ag_node = self._ag_node
        src._ag_out_index = self._ag_out_index
        out = src._binary(op_name, other)
        self._set_data(out._data)
        self._ag_node = out._ag_node
        self._ag_out_index = out._ag_out_index
        return self

    def __add__(self, other):
        return self._binary("add", other)

    def __radd__(self, other):
        return self._binary("add", other, reverse=True)

    def __iadd__(self, other):
        return self._inplace("add", other)

    def __sub__(self, other):
        return self._binary("sub", other)

    def __rsub__(self, other):
        return self._binary("sub", other, reverse=True)

    def __isub__(self, other):
        return self._inplace("sub", other)

    def __mul__(self, other):
        return self._binary("mul", other)

    def __rmul__(self, other):
        return self._binary("mul", other, reverse=True)

    def __imul__(self, other):
        return self._inplace("mul", other)

    def __truediv__(self, other):
        return self._binary("div", other)

    def __rtruediv__(self, other):
        return self._binary("div", other, reverse=True)

    def __itruediv__(self, other):
        return self._inplace("div", other)

    def __mod__(self, other):
        return self._binary("mod", other)

    def __rmod__(self, other):
        return self._binary("mod", other, reverse=True)

    def __pow__(self, other):
        return self._binary("power", other)

    def __rpow__(self, other):
        return self._binary("power", other, reverse=True)

    def __matmul__(self, other):
        return self.dot(other)

    def __neg__(self):
        return invoke("negative", [self], {})

    def __abs__(self):
        return invoke("abs", [self], {})

    def __eq__(self, other):
        if other is None:
            return False
        return self._binary("equal", other)

    def __ne__(self, other):
        if other is None:
            return True
        return self._binary("not_equal", other)

    def __gt__(self, other):
        return self._binary("greater", other)

    def __ge__(self, other):
        return self._binary("greater_equal", other)

    def __lt__(self, other):
        return self._binary("lesser", other)

    def __le__(self, other):
        return self._binary("lesser_equal", other)

    __hash__ = None  # mutable container semantics, like the reference

    def __repr__(self):
        try:
            arr = self.asnumpy()
            body = str(arr)
        except MXNetError as e:
            body = f"<error: {e}>"
        return f"{body}\n<NDArray {'x'.join(map(str, self.shape))} @{self._ctx}>"


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------


def _on_tape(arr) -> bool:
    return isinstance(arr, NDArray) and (
        arr._ag_node is not None or arr._ag_grad_req != "null"
    )


def _flavor_of(inputs) -> type:
    """The array FLAVOR a computation's outputs should carry: first input
    that is an NDArray subclass (mx.np ndarray) wins, else legacy NDArray.
    One rule for the eager invoke path and the hybridized trace — flavors
    differ semantically (np comparisons yield bool; nd yields float 0/1),
    so they must never drift apart."""
    for i in inputs:
        if isinstance(i, NDArray) and type(i) is not NDArray:
            return type(i)
    return NDArray


def _wrap(data: jax.Array, ctx: Context, cls=None) -> "NDArray":
    out = (cls or NDArray).__new__(cls or NDArray)
    out._data = data
    out._ctx = ctx
    out._version = 0
    out._grad = None
    out._ag_grad_req = "null"
    out._ag_node = None
    out._ag_out_index = 0
    out._deferred_init = None
    out._dc_sym = None
    return out


def _as_nd(x, ctx: Context) -> "NDArray":
    if isinstance(x, NDArray):
        return x
    return NDArray(x, ctx=ctx)


def _index_unwrap(key):
    if isinstance(key, NDArray):
        return key._data
    if isinstance(key, tuple):
        return tuple(k._data if isinstance(k, NDArray) else k for k in key)
    return key


def _needs_x64_index(shape):
    """True when any dim exceeds int32 range, so index constants must be
    s64 (the reference's int64-tensor-size build analog)."""
    return any(int32_overflow_dim(d) for d in shape)


_BIG_SPLICE_JIT: dict = {}


def _big_static_set(data, key, value):
    """Scalar write into a static int/contiguous-slice region of a
    >int32-range 1-D array.

    The TPU runtime moves data correctly only when every dim of the
    moved region fits int32 — ANY scatter/copy along a >2^31 dim lands
    at corrupt offsets (measured, docs/PERF.md), including the
    full-buffer copy a functional `.at[].set` implies.  So the write is
    a pure ELEMENTWISE pass over a (dim/C, C) view: reshape is
    metadata-only (verified exact past 2^31), the target region becomes
    a (row, col) iota mask, and `where` selects value vs old — no index
    tensors, no scatter, per-dim extents all int32.  Returns None for
    patterns this cannot express (the caller falls back): non-scalar
    values, stepped slices, multi-dim arrays, odd dims with no small
    factor."""
    k = key[0] if isinstance(key, tuple) and len(key) == 1 else key
    if data.ndim != 1:
        return None
    n = data.shape[0]
    if isinstance(k, bool):
        return None
    if isinstance(k, (int, onp.integer)):
        s = int(k) + (n if int(k) < 0 else 0)
        e = s + 1
    elif isinstance(k, slice):
        try:
            s, e, st = k.indices(n)
        except TypeError:
            return None
        if st != 1:
            return None
        if e <= s:
            return data                  # empty region: numpy no-op
    else:
        return None
    if isinstance(value, NDArray) or getattr(value, "ndim", 0):
        return None                      # scalar writes only on this path
    C = pow2_col_factor(n)
    if not C:
        return None
    rows = n // C
    # region bounds travel as int32 OPERANDS (they are only compared to
    # iota, never used as indices, so s64 demotion is irrelevant): one
    # executable per (shape, dtype), not one per write offset
    rs, cs = divmod(s, C)
    re_, ce = divmod(e - 1, C)           # inclusive end position
    ck = (data.shape, str(data.dtype), C)
    fn = _BIG_SPLICE_JIT.get(ck)
    if fn is None:

        def masked_set(d, v, b):
            mat = d.reshape(rows, C)
            row = jax.lax.broadcasted_iota(jnp.int32, (rows, C), 0)
            col = jax.lax.broadcasted_iota(jnp.int32, (rows, C), 1)
            after = (row > b[0]) | ((row == b[0]) & (col >= b[1]))
            before = (row < b[2]) | ((row == b[2]) & (col <= b[3]))
            return jnp.where(after & before, v, mat).reshape(n)

        fn = bounded_cache_put(_BIG_SPLICE_JIT, ck, jax.jit(masked_set))
    return fn(data, jnp.asarray(value, data.dtype),
              jnp.asarray([rs, cs, re_, ce], jnp.int32))


def _check_int_bounds(key, shape):
    """Raise IndexError for out-of-range CONCRETE integer indices — jax
    silently clips them, the reference raises (test_ndarray indexing
    contract).  numpy integer SCALARS count as concrete ints too: an
    out-of-range onp.int64 key must raise, not become a silently-masked
    no-op write (ADVICE r5).  Array/traced indices keep jax's clip
    semantics (that IS the documented device behavior for gather)."""
    _int_scalar = (int, onp.integer)
    ints = (key,) if isinstance(key, _int_scalar) else \
        tuple(k for k in key if isinstance(k, _int_scalar)) \
        if isinstance(key, tuple) else ()
    if not ints:
        return
    dims = iter(shape)
    keys = key if isinstance(key, tuple) else (key,)
    for k in keys:
        if k is None or k is Ellipsis:
            # newaxis consumes no dim; Ellipsis realigns dims from the
            # right — bounds past it are rare, skip the strict check
            if k is Ellipsis:
                return
            continue
        d = next(dims, None)
        if d is None:
            raise IndexError(f"too many indices for shape {shape}")
        if isinstance(k, _int_scalar) and not isinstance(k, bool) \
                and not (-d <= int(k) < d):
            raise IndexError(
                f"index {k} is out of bounds for axis with size {d}")


# operator dispatches since import: with fused.dispatch_count() this gives
# benchmark/eager_latency.py the dispatches-per-step lane a denominator
from .. import telemetry as _telemetry  # noqa: E402

_INVOKE = _telemetry.counter(
    "ndarray.invoke", "eager operator dispatches since import")


def invoke_count() -> int:
    """Number of eager operator dispatches since import (view over the
    ``ndarray.invoke`` registry counter)."""
    return int(_INVOKE.value)


# blocking host reads (asnumpy/item/float/bool, plus the deferred AMP
# flag read in cached_step) since import: tools/check_dispatch_budget.py
# gates the steady-state train step on this staying at 0 (non-AMP) /
# <= 1 deferred read (AMP) — the pipeline engine's host-sync budget
_HOST_SYNC = _telemetry.counter(
    "ndarray.host_sync",
    "blocking device->host value reads (asnumpy/item/float/bool + the "
    "deferred AMP flag read)")


def host_sync_count() -> int:
    """Number of blocking device->host value reads since import (view
    over the ``ndarray.host_sync`` registry counter)."""
    return int(_HOST_SYNC.value)


def count_host_sync() -> None:
    """Record one blocking host read performed outside asnumpy (e.g. a
    bool() on a raw jax scalar)."""
    _HOST_SYNC.inc()


def invoke(
    op: Union[str, OpSchema],
    inputs: Sequence[NDArray],
    attrs: dict,
    out: Optional[Union[NDArray, Sequence[NDArray]]] = None,
):
    """Imperative operator dispatch (MXImperativeInvokeImpl analog).

    - Unwraps NDArray inputs to jax.Arrays.
    - If autograd is recording and any input is tape-connected and the op is
      differentiable, runs under ``jax.vjp`` and records a TapeNode.
    - Wraps outputs; honours ``out=`` by writing into the destination
      (reference's kWriteTo into provided output arrays).
    """
    _INVOKE.inc()
    schema = get_op(op) if isinstance(op, str) else op
    ctx = inputs[0]._ctx if inputs else current_context()
    arrays = [i._data for i in inputs]

    if _profiler.ops_active():
        _t0 = _time.perf_counter_ns()
        try:
            return _invoke_body(schema, ctx, arrays, inputs, attrs, out)
        finally:
            _profiler.record_op(schema.name, _t0, _time.perf_counter_ns())
    return _invoke_body(schema, ctx, arrays, inputs, attrs, out)


def _make_op_fn(schema, attrs):
    if schema.num_inputs == -1:
        fn = lambda *arrs: schema.fn(list(arrs), **attrs)
    else:
        fn = lambda *arrs: schema.fn(*arrs, **attrs)

    if _amp_policy is not None:
        # mx.amp per-op cast lists: casting INSIDE fn keeps it within the
        # vjp boundary, so backward re-casts cotangents to each input's
        # original dtype (the reference amp_cast op's FGradient behavior)
        inner_fn = fn
        fn = lambda *arrs: inner_fn(*_amp_policy(schema.name, list(arrs)))
    return fn


# Per-op jit cache for the EAGER hot path (SURVEY §7: "per-op jit-compiled
# XLA computation with a compilation cache").  An op fn is typically a
# handful of jnp primitives; unjitted, each primitive is a separate device
# dispatch — through the TPU tunnel that is a multi-ms RTT apiece.  Jitting
# per (op, fn identity, amp generation, static attrs) collapses an op
# invocation to ONE cached executable launch (the reference engine's
# operator-bulking role, src/engine/threaded_engine.h:507-528).
# Ops whose python body cannot trace (data-dependent shapes, host
# round-trips) are detected by failure and permanently fall back.
_EAGER_JIT_BAD: set = set()
_EAGER_JIT_KEYCOUNT: dict = {}
_EAGER_JIT_MAX_ENTRIES = 512      # default namespace cap (override via
                                  # MXNET_PROGRAM_CACHE_CAPS eager_jit=N)
_EAGER_JIT_MAX_PER_OP = 64        # attr-cardinality cutoff: beyond this the
                                  # op recompiles per call (slice with a
                                  # moving begin etc.) — jit is a net loss


def _eager_jit_evicted(old_key, _fn) -> None:
    # cutoff counts LIVE entries: an evicted executable hands its op's
    # slot back so LRU churn can never accumulate into a per-op ban
    live = _EAGER_JIT_KEYCOUNT.get(old_key[0], 1) - 1
    if live > 0:
        _EAGER_JIT_KEYCOUNT[old_key[0]] = live
    else:
        _EAGER_JIT_KEYCOUNT.pop(old_key[0], None)


# the eager per-op executables are the ProgramStore 'eager_jit'
# namespace (one global scope): same LRU/metrics surface as the
# whole-program caches, values are plain shape-polymorphic jit
# callables (no AOT pinning — one (op, attrs) key serves every shape)
_EAGER_JIT_CACHE = _pstore.scope("eager_jit", on_evict=_eager_jit_evicted)

# trace-time failure types: the op BODY cannot be traced (host value
# inspection, data-dependent output shape).  Only these justify a
# permanent per-op ban; anything else (bad user input, dtype errors) must
# not disable the cache for later valid calls.
_TRACE_FAILURES = tuple(
    t for t in (
        getattr(jax.errors, "ConcretizationTypeError", None),
        getattr(jax.errors, "TracerArrayConversionError", None),
        getattr(jax.errors, "TracerBoolConversionError", None),
        getattr(jax.errors, "TracerIntegerConversionError", None),
        getattr(jax.errors, "NonConcreteBooleanIndexError", None),
        getattr(jax.errors, "UnexpectedTracerError", None),
    ) if t is not None)


def _attrs_key(v):
    if isinstance(v, (list, tuple)):
        return tuple(_attrs_key(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _attrs_key(x)) for k, x in v.items()))
    return v


# per-op cache opt-out (MXNET_EAGER_JIT_EXCLUDE): single-primitive
# reductions measured SLOWER through the cache than plain dispatch
# (docs/PERF.md chip table: mean(axis) 0.62x — one primitive is already
# one dispatch; the cache only adds lookup + executable-launch overhead).
# Memoized on the raw string so the per-dispatch cost is one dict read.
_EAGER_JIT_EXCLUDE_MEMO: tuple = (None, frozenset())


def _eager_jit_excluded(name: str) -> bool:
    global _EAGER_JIT_EXCLUDE_MEMO
    from .. import config as _config

    raw = _config.get("MXNET_EAGER_JIT_EXCLUDE")
    if raw != _EAGER_JIT_EXCLUDE_MEMO[0]:
        _EAGER_JIT_EXCLUDE_MEMO = (raw, frozenset(
            s.strip() for s in (raw or "").split(",") if s.strip()))
    return name in _EAGER_JIT_EXCLUDE_MEMO[1]


def _eager_jit_lookup(schema, attrs, arrays):
    from .. import config as _config

    mode = _config.get("MXNET_EAGER_JIT")
    if not mode or schema.name in _EAGER_JIT_BAD:
        return None
    if mode != 2 and jax.default_backend() != "tpu":
        return None                       # RTT-bound paths only by default
    if _eager_jit_excluded(schema.name):
        return None                       # measured net-loss ops (mean etc.)
    if any(isinstance(a, jax.core.Tracer) for a in arrays):
        # inside an outer trace an inner jit becomes a separate XLA call
        # and would break producer-consumer fusion in hybridized graphs
        return None
    try:
        key = (schema.name, id(schema.fn), _amp_generation,
               tuple(sorted((k, _attrs_key(v)) for k, v in attrs.items())))
        hash(key)
    except TypeError:
        return None                       # unhashable attr: plain dispatch
    fn = _EAGER_JIT_CACHE.lookup(key)
    if fn is not None:
        return fn
    # cutoff counts LIVE entries (decremented on eviction, see
    # _eager_jit_evicted): a hot op with few attr sets must never
    # accumulate into a ban via LRU churn or amp generation bumps
    n_keys = _EAGER_JIT_KEYCOUNT.get(schema.name, 0) + 1
    if n_keys > _EAGER_JIT_MAX_PER_OP:
        _EAGER_JIT_BAD.add(schema.name)   # attrs vary per call: jit loses
        return None
    _EAGER_JIT_KEYCOUNT[schema.name] = n_keys
    fn = jax.jit(_make_op_fn(schema, attrs))
    _EAGER_JIT_CACHE.insert(key, fn)
    return fn


def _invoke_body(schema, ctx, arrays, inputs, attrs, out):

    # Record every differentiable op while the scope is active (the reference
    # records all ops under record(), not just ones touching marked vars —
    # autograd.grad() may later differentiate w.r.t. any graph input).
    record = autograd.is_recording() and schema.differentiable and len(inputs) > 0

    # honest int64 indexing at scale: an s64-typed input (index arrays keep
    # int64 per the creation policy above) meeting a >int32-range dim must
    # dispatch under x64 on backends that execute s64 natively (cpu), or
    # jax demotes the indices to int32 with silent wraparound (gather
    # lands at the wrong offset).  NOT applied on TPU: its compiler
    # demotes s64 element types wholesale (buffers then mismatch the
    # executable), so TPU-capable ops (take, scalar get/set item) carry
    # their own int32-factorized >int32 paths instead.  The cheap dtype
    # test runs first: >99% of eager dispatches fail it in one tuple
    # check and never walk shapes.
    if (any(a.dtype in _X64_ITYPES for a in arrays)
            and any(_needs_x64_index(a.shape) for a in arrays)
            and ctx.jax_device is not None
            and ctx.jax_device.platform not in S64_DEMOTING_PLATFORMS):
        with _enable_x64(True):
            return _invoke_tail(schema, ctx, arrays, inputs, attrs, out,
                                _make_op_fn(schema, attrs), None, record)

    if schema.draws_key and attrs.get("key") is None:
        # the op body draws from the global PRNG chain: tracing it into a
        # cached executable would leak a tracer into the chain AND bake
        # the drawn key as a constant (every cache hit returning the same
        # "random" numbers) — plain dispatch only
        jitted = None
    else:
        jitted = _eager_jit_lookup(schema, attrs, arrays)
    fn = jitted if jitted is not None else _make_op_fn(schema, attrs)
    return _invoke_tail(schema, ctx, arrays, inputs, attrs, out, fn, jitted,
                        record)


_X64_ITYPES = (onp.dtype("int64"), onp.dtype("uint64"))


def _invoke_tail(schema, ctx, arrays, inputs, attrs, out, fn, jitted, record):
    while True:
        try:
            if record:
                raw_out, vjp_fn = jax.vjp(fn, *arrays)
            else:
                raw_out = fn(*arrays)
            break
        except Exception as e:
            if jitted is not None:
                # retry unjitted; ban the op ONLY for trace-time failures
                # (op body can't trace: host value inspection, dynamic
                # output shape).  Input-dependent errors (dtype, shape
                # mismatch) must not disable the cache for valid calls.
                # NotImplementedError counts as trace-time too: op bodies
                # raise it when they cannot express the pattern under a
                # trace (big-dim take with tracer indices) — without the
                # ban every call repays the failed trace (ADVICE r5).
                if isinstance(e, _TRACE_FAILURES + (NotImplementedError,)):
                    _EAGER_JIT_BAD.add(schema.name)
                jitted = None
                fn = _make_op_fn(schema, attrs)
                continue
            if record and isinstance(e, (TypeError,
                                         jax.errors.JaxRuntimeError)):
                # non-differentiable in practice (int dtypes etc.) — plain
                record = False
                continue
            raise

    multi = isinstance(raw_out, (tuple, list))
    outs_raw = list(raw_out) if multi else [raw_out]
    # outputs keep the array *flavor* of the inputs: dispatching an op on an
    # mx.np ndarray yields mx.np ndarrays (reference keeps np/nd worlds apart
    # via distinct generated namespaces; here one registry serves both)
    out_cls = _flavor_of(inputs)
    outputs = [_wrap(o, ctx, out_cls) for o in outs_raw]

    if _engine.is_naive():
        # MXNET_ENGINE_TYPE=NaiveEngine: synchronous dispatch — block per
        # op so errors surface at the faulting op, not a later sync point
        # (reference src/engine/naive_engine.cc debugging role); inside a
        # bulk scope the barrier fires every bulk_size ops instead
        _engine.naive_sync([o._data for o in outputs])

    if record:
        node = autograd.TapeNode(
            vjp_fn,
            list(inputs),
            len(outputs),
            [tuple(o.shape) for o in outs_raw],
            [o.dtype for o in outs_raw],
            name=schema.name,
            # replay (higher-order grads) runs under a trace: hand it the
            # PLAIN fn so replayed ops stay inline (an inner jit would be
            # a separate XLA call boundary, breaking fusion)
            fn=_make_op_fn(schema, attrs) if jitted is not None else fn,
            input_vals=list(arrays),
        )
        for i, o in enumerate(outputs):
            o._ag_node = node
            o._ag_out_index = i

    from .. import _deferred_compute as _dc

    if _dc.is_active():
        _dc.record(schema, list(inputs), attrs, outputs)

    if out is not None:
        dests = [out] if isinstance(out, NDArray) else list(out)
        for d, o in zip(dests, outputs):
            d._set_data(o._data.astype(d._data.dtype) if d._data.dtype != o._data.dtype else o._data)
            d._ag_node = o._ag_node
            d._ag_out_index = o._ag_out_index
            d._dc_sym = o._dc_sym
        return out

    if not multi:
        return outputs[0]
    return outputs


def array(source_array, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    """Create an NDArray from any array-like (reference mx.nd.array)."""
    if isinstance(source_array, NDArray):
        tgt = ctx or source_array._ctx
        out = NDArray(source_array._data, ctx=tgt, dtype=dtype)
        # An explicit ctx must MOVE an already-committed payload (the
        # reference mx.nd.array(nd, ctx=gpu(0)) copies device-to-device);
        # NDArray.__init__ wraps existing jax arrays in place, so the
        # placement is enforced here.  Tracers (graph capture) carry no
        # device and pass through untouched.
        if ctx is not None and not isinstance(out._data, jax.core.Tracer):
            dev = tgt.jax_device
            if dev is not None and dev not in out._data.devices():
                out._data = jax.device_put(out._data, dev)
        return out
    if dtype is None:
        np_in = onp.asarray(source_array)
        # MXNet's default dtype is float32: wide floats narrow, float16 and
        # all integer dtypes pass through.
        if np_in.dtype.kind == "f" and np_in.dtype != onp.float16:
            dtype = "float32"
        else:
            dtype = np_in.dtype
    return NDArray(onp.asarray(source_array), ctx=ctx, dtype=dtype)
