"""Generate python-level operator functions from the registry.

Reference analog: at import time the reference enumerates C-registered ops
and code-generates python wrappers into ``mxnet.ndarray.op``
(``python/mxnet/ndarray/register.py:115-277``).  Here generation is
introspective: the registered pure-JAX fn's signature tells us which leading
parameters are arrays (``num_inputs``) and which are attrs; positional
passing of attrs works the MXNet way (``nd.reshape(x, (2, 3))``).
"""
from __future__ import annotations

import inspect
from typing import Any, Callable

import jax
import numpy as _onp

from ..ops.registry import OpSchema
from .ndarray import NDArray, array, invoke


def _looks_like_key(a) -> bool:
    """Is this positional value a PRNG key (vs an MXNet positional attr)?
    Device arrays always count; host numpy only when it has key shape+kind
    (a 0-d float np scalar is an attr like p=np.array(0.5), never a key)."""
    if isinstance(a, (NDArray, jax.Array)):
        return True
    return (isinstance(a, _onp.ndarray) and a.ndim >= 1
            and a.dtype.kind in "uiV")

__all__ = ["make_op_func"]


def make_op_func(schema: OpSchema) -> Callable:
    sig = inspect.signature(schema.fn)
    params = list(sig.parameters)

    if schema.num_inputs == -1:
        attr_names = params[1:]

        def fn(*args, out=None, **kwargs):
            arrays = []
            rest = []
            for a in args:
                if isinstance(a, NDArray):
                    arrays.append(a)
                elif not arrays and not rest and isinstance(a, (list, tuple)) and a and isinstance(a[0], NDArray):
                    arrays.extend(a)
                else:
                    rest.append(a)
            attrs = dict(zip(attr_names, rest))
            attrs.update({k: v for k, v in kwargs.items() if k not in ("name", "ctx", "dtype_hint")})
            attrs = _unwrap_attr_arrays(attrs)
            return invoke(schema, arrays, attrs, out=out)

    elif schema.num_inputs == 0:
        attr_names = params

        def fn(*args, out=None, ctx=None, **kwargs):
            attrs = dict(zip(attr_names, args))
            attrs.update({k: v for k, v in kwargs.items() if k not in ("name", "ctx")})
            attrs = _unwrap_attr_arrays(attrs)
            from ..context import current_context

            ctx = ctx or current_context()
            dummy = []
            out_arr = invoke(schema, dummy, attrs, out=out)
            if out is None and ctx is not None:
                # re-home onto requested ctx
                import jax

                for o in out_arr if isinstance(out_arr, list) else [out_arr]:
                    o._ctx = ctx
                    o._data = jax.device_put(o._data, ctx.jax_device)
            return out_arr

    else:
        n_in = schema.num_inputs
        attr_names = params[n_in:]

        def fn(*args, out=None, **kwargs):
            n_take = n_in
            # rng-input ops (Dropout): a non-key value in the key slot is
            # an MXNet-style positional attr (nd.Dropout(x, 0.5)), never a
            # key — leave the slot for the auto-drawn key
            if (schema.rng_input and len(args) >= n_in
                    and not _looks_like_key(args[n_in - 1])):
                n_take = n_in - 1
            arrays = list(args[:n_take])
            rest = args[n_take:]
            ctx = None
            for a in arrays:
                if isinstance(a, NDArray):
                    ctx = a._ctx
                    break
            arrays = [
                a if isinstance(a, NDArray) or a is None else array(a, ctx=ctx)
                for a in arrays
            ]
            # drop trailing Nones (optional array slots)
            while arrays and arrays[-1] is None:
                arrays.pop()
            if schema.rng_input and len(arrays) == n_in:
                if "key" in kwargs:
                    raise TypeError(f"{schema.name}: key passed both "
                                    "positionally and by keyword")
            elif schema.rng_input and len(arrays) == n_in - 1:
                from .. import random as _random
                from ..context import current_context
                from .ndarray import _wrap

                k = kwargs.pop("key", None)       # keyword key supported
                if k is None:
                    k = _random.next_key()
                elif isinstance(k, NDArray):
                    k = k._data
                arrays.append(_wrap(k, ctx or current_context()))
            attrs = dict(zip(attr_names, rest))
            attrs.update({k: v for k, v in kwargs.items() if k not in ("name", "ctx")})
            attrs = _unwrap_attr_arrays(attrs)
            return invoke(schema, arrays, attrs, out=out)

    fn.__name__ = schema.name
    fn.__doc__ = schema.doc
    return fn


def _unwrap_attr_arrays(attrs: dict) -> dict:
    # attrs must be static python values / jax arrays, not NDArrays
    return {
        k: (v._data if isinstance(v, NDArray) else v) for k, v in attrs.items()
    }
