"""ONNX -> Symbol importer.

Reference: python/mxnet/contrib/onnx/onnx2mx/import_model.py (+
_import_helper.py op map).  Parses the protobuf wire format directly
(proto.py) and rebuilds a Symbol DAG over this framework's op registry,
so imported models run on TPU through the same whole-graph-jit path as
native ones.  Covers the standard opset emitted by the exporter plus the
common inference ops.
"""
from __future__ import annotations

from typing import Any, Dict, List

import numpy as onp

from . import proto

ONNX2MX = {}


def translator(*names):
    def deco(fn):
        for n in names:
            ONNX2MX[n] = fn
        return fn

    return deco


def _attr_pool_kind(node):
    return node["op_type"].startswith("Global")


def _pads_begin(pads):
    """ONNX pads are [x1_begin, x2_begin, ..., x1_end, x2_end]; this
    framework's spatial ops take symmetric padding."""
    if not pads:
        return (0, 0)
    n = len(pads) // 2
    if list(pads[:n]) != list(pads[n:]):
        raise NotImplementedError(
            f"asymmetric ONNX pads {pads} not supported; pad explicitly "
            "with a Pad node")
    return tuple(int(p) for p in pads[:n])


@translator("Conv")
def _conv(node, ins, consts, sym_ops):
    a = node["attrs"]
    return sym_ops["Convolution"](
        *ins, kernel=tuple(a.get("kernel_shape", ())),
        stride=tuple(a.get("strides", (1, 1))),
        dilate=tuple(a.get("dilations", (1, 1))),
        pad=_pads_begin(a.get("pads")), num_group=a.get("group", 1),
        num_filter=0, no_bias=len(ins) == 2)


@translator("ConvTranspose")
def _deconv(node, ins, consts, sym_ops):
    a = node["attrs"]
    return sym_ops["Deconvolution"](
        *ins, kernel=tuple(a.get("kernel_shape", ())),
        stride=tuple(a.get("strides", (1, 1))),
        pad=_pads_begin(a.get("pads")), num_group=a.get("group", 1),
        num_filter=0, no_bias=len(ins) == 2)


@translator("BatchNormalization")
def _bn(node, ins, consts, sym_ops):
    a = node["attrs"]
    return sym_ops["BatchNorm"](
        *ins, eps=a.get("epsilon", 1e-5), momentum=a.get("momentum", 0.9),
        fix_gamma=False, use_global_stats=True)


@translator("Relu")
def _relu(node, ins, consts, sym_ops):
    return sym_ops["relu"](ins[0])


@translator("Sigmoid")
def _sigmoid(node, ins, consts, sym_ops):
    return sym_ops["sigmoid"](ins[0])


@translator("Tanh")
def _tanh(node, ins, consts, sym_ops):
    return sym_ops["tanh"](ins[0])


@translator("Softplus")
def _softplus(node, ins, consts, sym_ops):
    return sym_ops["Activation"](ins[0], act_type="softrelu")


@translator("LeakyRelu")
def _leaky(node, ins, consts, sym_ops):
    return sym_ops["LeakyReLU"](ins[0],
                                slope=node["attrs"].get("alpha", 0.01))


@translator("Elu")
def _elu(node, ins, consts, sym_ops):
    return sym_ops["LeakyReLU"](ins[0], act_type="elu",
                                slope=node["attrs"].get("alpha", 1.0))


@translator("PRelu")
def _prelu(node, ins, consts, sym_ops):
    return sym_ops["LeakyReLU"](ins[0], ins[1], act_type="prelu")


@translator("Erf")
def _erf(node, ins, consts, sym_ops):
    return sym_ops["erf"](ins[0])


@translator("MaxPool", "AveragePool", "GlobalMaxPool", "GlobalAveragePool")
def _pool(node, ins, consts, sym_ops):
    a = node["attrs"]
    ptype = "max" if "Max" in node["op_type"] else "avg"
    if _attr_pool_kind(node):
        return sym_ops["Pooling"](ins[0], pool_type=ptype, global_pool=True)
    return sym_ops["Pooling"](
        ins[0], kernel=tuple(a.get("kernel_shape", (1, 1))),
        stride=tuple(a.get("strides", (1, 1))),
        pad=_pads_begin(a.get("pads")),
        pool_type=ptype,
        pooling_convention="full" if a.get("ceil_mode") else "valid",
        # ONNX spec default is count_include_pad=0
        count_include_pad=bool(a.get("count_include_pad", 0)))


@translator("Gemm")
def _gemm(node, ins, consts, sym_ops):
    a = node["attrs"]
    assert a.get("transB", 0) == 1 and not a.get("transA", 0), \
        "only transB=1 Gemm supported (the exporter's form)"
    return sym_ops["FullyConnected"](
        *ins, num_hidden=0, no_bias=len(ins) == 2, flatten=False)


@translator("MatMul")
def _matmul(node, ins, consts, sym_ops):
    return sym_ops["matmul"](ins[0], ins[1])


@translator("Add")
def _add(node, ins, consts, sym_ops):
    return sym_ops["broadcast_add"](ins[0], ins[1])


@translator("Sub")
def _sub(node, ins, consts, sym_ops):
    return sym_ops["broadcast_sub"](ins[0], ins[1])


@translator("Mul")
def _mul(node, ins, consts, sym_ops):
    return sym_ops["broadcast_mul"](ins[0], ins[1])


@translator("Div")
def _div(node, ins, consts, sym_ops):
    return sym_ops["broadcast_div"](ins[0], ins[1])


@translator("Sum")
def _sum(node, ins, consts, sym_ops):
    out = ins[0]
    for x in ins[1:]:
        out = sym_ops["broadcast_add"](out, x)
    return out


@translator("Flatten")
def _flatten(node, ins, consts, sym_ops):
    return sym_ops["flatten"](ins[0])


@translator("Softmax")
def _softmax(node, ins, consts, sym_ops):
    return sym_ops["softmax"](ins[0], axis=node["attrs"].get("axis", -1))


@translator("LayerNormalization")
def _ln(node, ins, consts, sym_ops):
    a = node["attrs"]
    return sym_ops["LayerNorm"](*ins, axis=a.get("axis", -1),
                                eps=a.get("epsilon", 1e-5))


@translator("Gather")
def _gather(node, ins, consts, sym_ops):
    assert node["attrs"].get("axis", 0) == 0
    return sym_ops["embedding"](ins[1], ins[0])


@translator("Cast")
def _cast(node, ins, consts, sym_ops):
    np_dt = proto.ONNX_TO_NP[node["attrs"]["to"]]
    return sym_ops["cast"](ins[0], dtype=str(np_dt))


@translator("Transpose")
def _transpose(node, ins, consts, sym_ops):
    perm = node["attrs"].get("perm")
    return sym_ops["transpose"](ins[0],
                                axes=tuple(perm) if perm else None)


@translator("Reshape")
def _reshape(node, ins, consts, sym_ops):
    shape = consts[node["input"][1]]
    return sym_ops["reshape"](ins[0],
                              shape=tuple(int(s) for s in shape))


@translator("Slice")
def _slice(node, ins, consts, sym_ops):
    starts = [int(s) for s in consts[node["input"][1]]]
    ends = [int(s) for s in consts[node["input"][2]]]
    axes = [int(s) for s in consts[node["input"][3]]] \
        if len(node["input"]) > 3 else list(range(len(starts)))
    begin = {}
    for ax, st, en in zip(axes, starts, ends):
        begin[ax] = (st, en)
    max_ax = max(begin) + 1
    b = [begin.get(i, (None, None))[0] for i in range(max_ax)]
    e = [begin.get(i, (None, None))[1] for i in range(max_ax)]
    return sym_ops["slice"](ins[0], begin=tuple(b), end=tuple(e))


@translator("Identity", "Dropout")
def _identity(node, ins, consts, sym_ops):
    return sym_ops["identity"](ins[0])


@translator("Concat")
def _concat(node, ins, consts, sym_ops):
    return sym_ops["concat"](*ins, dim=node["attrs"].get("axis", 1))


@translator("ReduceMean")
def _reduce_mean(node, ins, consts, sym_ops):
    a = node["attrs"]
    return sym_ops["mean"](ins[0], axis=tuple(a.get("axes", ())) or None,
                           keepdims=bool(a.get("keepdims", 1)))


def import_model(model_file: str):
    """Load an .onnx file -> (Symbol, arg_params, aux_params)
    (reference onnx2mx/import_model.py:import_model)."""
    from ... import symbol as _sym_mod
    from ...ndarray import array as _nd_array

    with open(model_file, "rb") as f:
        m = proto.parse_model(f.read())
    g = m["graph"]
    init = g["initializers"]

    sym_ops = {n: getattr(_sym_mod, n) for n in dir(_sym_mod)
               if not n.startswith("_")}

    values: Dict[str, Any] = {}
    consts: Dict[str, onp.ndarray] = dict(init)
    for name, _elem, _shape in g["inputs"]:
        if name not in init:
            values[name] = _sym_mod.var(name)
    for name in init:
        values[name] = _sym_mod.var(name)

    extra_params: Dict[str, onp.ndarray] = {}
    for node in g["nodes"]:
        op = node["op_type"]
        if op == "Constant":
            # constant tensors are both attr-consumable (consts) and
            # value-consumable (a var backed by an imported param)
            out_name = node["output"][0]
            val = node["attrs"].get("value")
            consts[out_name] = val
            values[out_name] = _sym_mod.var(out_name)
            extra_params[out_name] = onp.asarray(val)
            continue
        if op not in ONNX2MX:
            raise NotImplementedError(
                f"no import translator for ONNX op '{op}'")
        ins = [values[i] for i in node["input"] if i in values]
        out = ONNX2MX[op](node, ins, consts, sym_ops)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for nm, o in zip(node["output"], outs):
            values[nm] = o

    out_syms = [values[nm] for nm, _e, _s in g["outputs"]]
    sym = out_syms[0] if len(out_syms) == 1 else _sym_mod.Group(out_syms)
    arg_params = {k: _nd_array(v) for k, v in init.items()}
    arg_params.update({k: _nd_array(v) for k, v in extra_params.items()})
    return sym, arg_params, {}
