"""Symbol-DAG -> ONNX exporter.

Reference: python/mxnet/contrib/onnx/mx2onnx/_export_model.py (exporter
driven by per-op translator functions, _op_translations.py).  Same design
here: ``MX2ONNX`` maps registry op names to translators emitting standard
ONNX nodes (opset 17); fused MXNet ops (interleaved self-attention
matmuls, FullyConnected on >2D) are decomposed into
Reshape/Transpose/Slice/MatMul primitives, and value-independent ops
(arange_like) are folded to constant initializers using the statically
known shapes.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional

import numpy as onp

from . import proto

MX2ONNX: Dict[str, Callable] = {}


def translator(*names):
    def deco(fn):
        for n in names:
            MX2ONNX[n] = fn
        return fn

    return deco


class _Ctx:
    """Per-export state handed to translators."""

    def __init__(self, opset):
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self.init_names: set = set()
        self.shapes: Dict[str, tuple] = {}   # onnx tensor name -> shape
        self.opset = opset
        self._uid = 0

    def uid(self, base):
        self._uid += 1
        return f"{base}_{self._uid}"

    def add_node(self, op_type, inputs, outputs, name=None, **attrs):
        self.nodes.append(proto.node(op_type, list(inputs), list(outputs),
                                     name or outputs[0], attrs))

    def add_init(self, name, array):
        if name not in self.init_names:
            self.init_names.add(name)
            self.initializers.append(proto.tensor(name, onp.asarray(array)))
        return name

    def const(self, base, array):
        return self.add_init(self.uid(base), array)


def _pads2(pad):
    ph, pw = (pad if pad else (0, 0))
    return [int(ph), int(pw), int(ph), int(pw)]


@translator("Convolution")
def _conv(node, ins, outs, ctx):
    a = node.attrs
    attrs = dict(kernel_shape=[int(k) for k in a.get("kernel", ())],
                 strides=[int(s) for s in a.get("stride", (1, 1))],
                 pads=_pads2(a.get("pad")),
                 dilations=[int(d) for d in a.get("dilate", (1, 1))],
                 group=int(a.get("num_group", 1)))
    ctx.add_node("Conv", ins, outs, **attrs)


@translator("Deconvolution")
def _deconv(node, ins, outs, ctx):
    a = node.attrs
    ctx.add_node("ConvTranspose", ins, outs,
                 kernel_shape=[int(k) for k in a.get("kernel", ())],
                 strides=[int(s) for s in a.get("stride", (1, 1))],
                 pads=_pads2(a.get("pad")),
                 group=int(a.get("num_group", 1)))


@translator("BatchNorm")
def _bn(node, ins, outs, ctx):
    a = node.attrs
    ctx.add_node("BatchNormalization", ins[:5], outs[:1],
                 epsilon=float(a.get("eps", 1e-3)),
                 momentum=float(a.get("momentum", 0.9)))


@translator("Activation")
def _act(node, ins, outs, ctx):
    table = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus", "softsign": "Softsign"}
    ctx.add_node(table[node.attrs.get("act_type", "relu")], ins, outs)


@translator("relu")
def _relu(node, ins, outs, ctx):
    ctx.add_node("Relu", ins, outs)


@translator("sigmoid")
def _sigmoid(node, ins, outs, ctx):
    ctx.add_node("Sigmoid", ins, outs)


@translator("tanh")
def _tanh(node, ins, outs, ctx):
    ctx.add_node("Tanh", ins, outs)


@translator("LeakyReLU")
def _leaky(node, ins, outs, ctx):
    a = node.attrs
    act = a.get("act_type", "leaky")
    if act == "leaky":
        ctx.add_node("LeakyRelu", ins[:1], outs,
                     alpha=float(a.get("slope", 0.25)))
    elif act == "elu":
        ctx.add_node("Elu", ins[:1], outs, alpha=float(a.get("slope", 0.25)))
    elif act == "prelu":
        ctx.add_node("PRelu", ins[:2], outs)
    elif act == "gelu":
        # exact gelu: 0.5 * x * (1 + erf(x / sqrt(2)))
        x = ins[0]
        s = ctx.const("gelu_sqrt2", onp.asarray(math.sqrt(2.0), onp.float32))
        half = ctx.const("gelu_half", onp.asarray(0.5, onp.float32))
        one = ctx.const("gelu_one", onp.asarray(1.0, onp.float32))
        d = ctx.uid("gelu_div")
        ctx.add_node("Div", [x, s], [d])
        e = ctx.uid("gelu_erf")
        ctx.add_node("Erf", [d], [e])
        p = ctx.uid("gelu_1p")
        ctx.add_node("Add", [e, one], [p])
        m = ctx.uid("gelu_xm")
        ctx.add_node("Mul", [x, p], [m])
        ctx.add_node("Mul", [m, half], outs)
    else:
        raise ValueError(f"LeakyReLU act_type {act} not exportable")


@translator("Pooling")
def _pool(node, ins, outs, ctx):
    a = node.attrs
    ptype = a.get("pool_type", "max")
    if a.get("global_pool", False):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}[ptype]
        ctx.add_node(op, ins, outs)
        return
    op = {"max": "MaxPool", "avg": "AveragePool"}[ptype]
    attrs = dict(kernel_shape=[int(k) for k in a.get("kernel", (1, 1))],
                 strides=[int(s) for s in a.get("stride") or (1, 1)],
                 pads=_pads2(a.get("pad")))
    if op == "AveragePool":
        attrs["count_include_pad"] = int(a.get("count_include_pad", True))
    if a.get("pooling_convention", "valid") == "full":
        attrs["ceil_mode"] = 1
    ctx.add_node(op, ins, outs, **attrs)


@translator("FullyConnected")
def _fc(node, ins, outs, ctx):
    a = node.attrs
    no_bias = a.get("no_bias", False)
    data, weight = ins[0], ins[1]
    rank = len(ctx.shapes.get(data, (2,)))
    flatten = a.get("flatten", True)
    if flatten and rank != 2:
        f = ctx.uid("flat")
        ctx.add_node("Flatten", [data], [f], axis=1)
        data = f
        rank = 2
    if rank == 2:
        ins2 = [data, weight] + ([] if no_bias else [ins[2]])
        ctx.add_node("Gemm", ins2, outs, alpha=1.0, beta=1.0, transA=0,
                     transB=1)
    else:
        # flatten=False on ND input: MatMul with pre-transposed weight
        wt = ctx.uid(weight + "_T")
        ctx.add_node("Transpose", [weight], [wt], perm=[1, 0])
        mm = ctx.uid("fc_mm") if not no_bias else outs[0]
        ctx.add_node("MatMul", [data, wt], [mm])
        if not no_bias:
            ctx.add_node("Add", [mm, ins[2]], outs)


@translator("broadcast_add", "elemwise_add", "_plus")
def _add(node, ins, outs, ctx):
    ctx.add_node("Add", ins, outs)


@translator("broadcast_sub", "elemwise_sub")
def _sub(node, ins, outs, ctx):
    ctx.add_node("Sub", ins, outs)


@translator("broadcast_mul", "elemwise_mul")
def _mul(node, ins, outs, ctx):
    ctx.add_node("Mul", ins, outs)


@translator("broadcast_div", "elemwise_div")
def _div(node, ins, outs, ctx):
    ctx.add_node("Div", ins, outs)


@translator("add_n")
def _addn(node, ins, outs, ctx):
    ctx.add_node("Sum", ins, outs)


@translator("flatten", "Flatten")
def _flatten(node, ins, outs, ctx):
    ctx.add_node("Flatten", ins, outs, axis=1)


@translator("softmax")
def _softmax(node, ins, outs, ctx):
    ctx.add_node("Softmax", ins[:1], outs,
                 axis=int(node.attrs.get("axis", -1)))


@translator("LayerNorm")
def _ln(node, ins, outs, ctx):
    a = node.attrs
    ctx.add_node("LayerNormalization", ins[:3], outs[:1],
                 axis=int(a.get("axis", -1)),
                 epsilon=float(a.get("eps", 1e-5)))


@translator("embedding", "Embedding")
def _embed(node, ins, outs, ctx):
    # mxnet: (indices, weight); onnx Gather: (data=weight, indices)
    idx64 = ctx.uid("idx64")
    ctx.add_node("Cast", [ins[0]], [idx64], to=proto.INT64)
    ctx.add_node("Gather", [ins[1], idx64], outs, axis=0)


@translator("transpose")
def _transpose(node, ins, outs, ctx):
    axes = node.attrs.get("axes")
    if axes:
        ctx.add_node("Transpose", ins, outs, perm=[int(x) for x in axes])
    else:
        ctx.add_node("Transpose", ins, outs)


@translator("reshape", "Reshape")
def _reshape(node, ins, outs, ctx):
    shape = [int(s) for s in node.attrs.get("shape", ())]
    shp = ctx.const("shape", onp.asarray(shape, onp.int64))
    ctx.add_node("Reshape", [ins[0], shp], outs)


@translator("Dropout")
def _dropout(node, ins, outs, ctx):
    ctx.add_node("Identity", ins[:1], outs[:1])   # inference export


@translator("Concat", "concat")
def _concat(node, ins, outs, ctx):
    ctx.add_node("Concat", ins, outs,
                 axis=int(node.attrs.get("dim", node.attrs.get("axis", 1))))


@translator("arange_like")
def _arange_like(node, ins, outs, ctx):
    """Value-independent: fold to a constant from the static shape."""
    from ...ops.registry import get_op

    shape = ctx.shapes[ins[0]]
    val = get_op("arange_like").fn(onp.zeros(shape, onp.float32),
                                   **node.attrs)
    ctx.add_init(outs[0], onp.asarray(val, onp.float32))


def _slice_qkv(ctx, x5, which, name, S, B, H, hd):
    """Slice [S,B,H,3,hd] at index ``which`` on axis 3 -> [S,B,H,hd]."""
    st = ctx.const("st", onp.asarray([which], onp.int64))
    en = ctx.const("en", onp.asarray([which + 1], onp.int64))
    ax = ctx.const("ax", onp.asarray([3], onp.int64))
    sl = ctx.uid(name + "_sl")
    ctx.add_node("Slice", [x5, st, en, ax], [sl])
    shp = ctx.const("shp", onp.asarray([S, B, H, hd], onp.int64))
    out = ctx.uid(name)
    ctx.add_node("Reshape", [sl, shp], [out])
    return out


def _sbhd_to_bh_s_d(ctx, x, name, S, B, H, hd):
    t = ctx.uid(name + "_t")
    ctx.add_node("Transpose", [x], [t], perm=[1, 2, 0, 3])
    shp = ctx.const("shp", onp.asarray([B * H, S, hd], onp.int64))
    out = ctx.uid(name + "_r")
    ctx.add_node("Reshape", [t, shp], [out])
    return out


@translator("interleaved_matmul_selfatt_qk")
def _att_qk(node, ins, outs, ctx):
    """(S,B,3E) interleaved qkv -> (B*H, S, S) scaled QK^T, decomposed to
    Reshape/Slice/Transpose/MatMul (reference contrib/transformer.cc:650)."""
    S, B, E3 = ctx.shapes[ins[0]]
    H = int(node.attrs.get("heads", 1))
    hd = E3 // 3 // H
    shp5 = ctx.const("shp5", onp.asarray([S, B, H, 3, hd], onp.int64))
    x5 = ctx.uid("qkv5")
    ctx.add_node("Reshape", [ins[0], shp5], [x5])
    q = _slice_qkv(ctx, x5, 0, "q", S, B, H, hd)
    k = _slice_qkv(ctx, x5, 1, "k", S, B, H, hd)
    qb = _sbhd_to_bh_s_d(ctx, q, "qb", S, B, H, hd)
    kb = _sbhd_to_bh_s_d(ctx, k, "kb", S, B, H, hd)
    scale = ctx.const("scale",
                      onp.asarray(1.0 / math.sqrt(hd), onp.float32))
    qs = ctx.uid("q_scaled")
    ctx.add_node("Mul", [qb, scale], [qs])
    kt = ctx.uid("k_T")
    ctx.add_node("Transpose", [kb], [kt], perm=[0, 2, 1])
    ctx.add_node("MatMul", [qs, kt], outs)


@translator("interleaved_matmul_selfatt_valatt")
def _att_valatt(node, ins, outs, ctx):
    """attention (B*H,S,S) x V from interleaved qkv -> (S,B,E)."""
    S, B, E3 = ctx.shapes[ins[0]]
    H = int(node.attrs.get("heads", 1))
    hd = E3 // 3 // H
    shp5 = ctx.const("shp5", onp.asarray([S, B, H, 3, hd], onp.int64))
    x5 = ctx.uid("qkv5")
    ctx.add_node("Reshape", [ins[0], shp5], [x5])
    v = _slice_qkv(ctx, x5, 2, "v", S, B, H, hd)
    vb = _sbhd_to_bh_s_d(ctx, v, "vb", S, B, H, hd)
    mm = ctx.uid("att_v")
    ctx.add_node("MatMul", [ins[1], vb], [mm])
    shp4 = ctx.const("shp4", onp.asarray([B, H, S, hd], onp.int64))
    r4 = ctx.uid("att_r4")
    ctx.add_node("Reshape", [mm, shp4], [r4])
    t = ctx.uid("att_t")
    ctx.add_node("Transpose", [r4], [t], perm=[2, 0, 1, 3])
    shp3 = ctx.const("shp3", onp.asarray([S, B, H * hd], onp.int64))
    ctx.add_node("Reshape", [t, shp3], outs)


@translator("dot", "linalg_gemm2", "batch_dot")
def _matmul(node, ins, outs, ctx):
    ctx.add_node("MatMul", ins, outs)


@translator("mean")
def _mean(node, ins, outs, ctx):
    a = node.attrs
    ax = a.get("axis")
    attrs = {"keepdims": int(a.get("keepdims", False))}
    if ax is not None:
        attrs["axes"] = [int(x) for x in (ax if isinstance(ax, (tuple, list))
                                          else (ax,))]
    ctx.add_node("ReduceMean", ins, outs, **attrs)


# ---------------------------------------------------------------------------


def export_model(sym, params, in_shapes=None, in_types=None,
                 onnx_file_path="model.onnx", opset_version=17,
                 model_name="mxnet_tpu_model"):
    """Export a traced Symbol + params to an ONNX file
    (reference mx2onnx/_export_model.py export_model).

    ``params``: {name: NDArray | jax/numpy array}.  ``in_shapes``: shapes
    for the non-parameter inputs, in ``sym.list_inputs()`` order.  Returns
    the path.
    """
    import jax

    from ...ops.registry import get_op

    param_arrays = {}
    for k, v in (params or {}).items():
        arr = v.asnumpy() if hasattr(v, "asnumpy") else onp.asarray(v)
        param_arrays[k.split(":", 1)[-1]] = arr

    nodes = sym._topo() if hasattr(sym, "_topo") else None
    if nodes is None:
        # topological walk over the DAG
        seen, nodes = set(), []

        def walk(n):
            if id(n) in seen:
                return
            seen.add(id(n))
            for (src, _i) in n.inputs:
                walk(src)
            nodes.append(n)

        for (n, _i) in sym._outputs:
            walk(n)

    data_inputs = [n.name for n in nodes
                   if n.op is None and n.name not in param_arrays]
    in_shapes = list(in_shapes or [])
    in_types = list(in_types or ["float32"] * len(data_inputs))
    if len(in_shapes) != len(data_inputs):
        raise ValueError(
            f"need shapes for inputs {data_inputs}, got {in_shapes}")

    ctx = _Ctx(opset_version)

    # ---- static shape propagation (abstract eval per node) --------------
    import jax.numpy as jnp

    name_of: Dict[Any, List[str]] = {}
    aval: Dict[str, Any] = {}

    def out_names(n):
        if n.num_outputs == 1:
            return [n.name]
        return [f"{n.name}:{i}" for i in range(n.num_outputs)]

    for n in nodes:
        name_of[id(n)] = out_names(n)
    for n in nodes:
        if n.op is None:
            if n.name in param_arrays:
                arr = param_arrays[n.name]
                sds = jax.ShapeDtypeStruct(arr.shape, arr.dtype)
            else:
                i = data_inputs.index(n.name)
                sds = jax.ShapeDtypeStruct(
                    tuple(in_shapes[i]), onp.dtype(in_types[i]))
            aval[n.name] = sds
            ctx.shapes[n.name] = tuple(sds.shape)
            continue
        schema = get_op(n.op)
        ins_av = [aval[name_of[id(src)][i]] for (src, i) in n.inputs]
        if schema.num_inputs == -1:
            out = jax.eval_shape(lambda *a: schema.fn(list(a), **n.attrs),
                                 *ins_av)
        else:
            out = jax.eval_shape(lambda *a: schema.fn(*a, **n.attrs),
                                 *ins_av)
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        for nm, o in zip(name_of[id(n)], outs):
            aval[nm] = o
            ctx.shapes[nm] = tuple(o.shape)

    # ---- translate -------------------------------------------------------
    for n in nodes:
        if n.op is None:
            if n.name in param_arrays:
                ctx.add_init(n.name, param_arrays[n.name])
            continue
        if n.op not in MX2ONNX:
            raise NotImplementedError(
                f"no ONNX translator for op '{n.op}' (node {n.name}); "
                f"supported: {sorted(MX2ONNX)}")
        ins = [name_of[id(src)][i] for (src, i) in n.inputs]
        MX2ONNX[n.op](n, ins, name_of[id(n)], ctx)

    g_inputs = [
        proto.value_info(nm, proto.NP_TO_ONNX[onp.dtype(dt)], tuple(shp))
        for nm, shp, dt in zip(data_inputs, in_shapes, in_types)
    ]
    g_outputs = []
    for (n, i) in sym._outputs:
        nm = name_of[id(n)][i]
        g_outputs.append(proto.value_info(
            nm, proto.NP_TO_ONNX[onp.dtype(str(aval[nm].dtype))],
            tuple(aval[nm].shape)))

    gb = proto.graph(ctx.nodes, model_name, ctx.initializers, g_inputs,
                     g_outputs)
    mb = proto.model(gb, opset=opset_version)
    with open(onnx_file_path, "wb") as f:
        f.write(mb)
    return onnx_file_path
