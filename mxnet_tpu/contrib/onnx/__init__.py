"""``mx.contrib.onnx`` — ONNX export/import without an onnx dependency.

Reference: python/mxnet/contrib/onnx/ (mx2onnx exporter + onnx2mx
importer).  The protobuf wire format is read/written directly
(:mod:`proto`), so exported ``.onnx`` files load in onnxruntime /
netron / any ONNX consumer, and standard ONNX inference graphs import
back as Symbols running on TPU.
"""
from . import proto
from .mx2onnx import MX2ONNX, export_model
from .onnx2mx import ONNX2MX, import_model

__all__ = ["export_model", "import_model", "proto", "MX2ONNX", "ONNX2MX"]


def get_model_metadata(model_file: str):
    """Shapes/names of an ONNX model's inputs and outputs
    (reference onnx2mx/import_model.py:get_model_metadata)."""
    with open(model_file, "rb") as f:
        m = proto.parse_model(f.read())
    g = m["graph"]
    init = set(g["initializers"])
    return {
        "input_tensor_data": [(n, tuple(s)) for n, _e, s in g["inputs"]
                              if n not in init],
        "output_tensor_data": [(n, tuple(s)) for n, _e, s in g["outputs"]],
    }
