"""Minimal ONNX protobuf wire codec — no ``onnx`` package required.

The reference's exporter (python/mxnet/contrib/onnx/mx2onnx) builds
ModelProto through the onnx python bindings; this environment has no onnx
distribution, so we serialize the (stable, versioned) ONNX protobuf wire
format directly: ModelProto / GraphProto / NodeProto / TensorProto /
AttributeProto / ValueInfoProto and the reader for the same subset.
Field numbers follow onnx/onnx.proto (IR version 8, default opset 17).

Protobuf wire format: each field is a varint key ``(field_num << 3) |
wire_type`` followed by a varint (type 0), fixed 32-bit little-endian
(type 5), or length-prefixed bytes (type 2).
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as onp

# TensorProto.DataType
FLOAT, UINT8, INT8, INT32, INT64, BOOL, FLOAT16, DOUBLE, BFLOAT16 = \
    1, 2, 3, 6, 7, 9, 10, 11, 16

NP_TO_ONNX = {
    onp.dtype("float32"): FLOAT,
    onp.dtype("float64"): DOUBLE,
    onp.dtype("int32"): INT32,
    onp.dtype("int64"): INT64,
    onp.dtype("int8"): INT8,
    onp.dtype("uint8"): UINT8,
    onp.dtype("bool"): BOOL,
    onp.dtype("float16"): FLOAT16,
}
try:
    import ml_dtypes as _mld

    NP_TO_ONNX[onp.dtype(_mld.bfloat16)] = BFLOAT16
except ImportError:                                  # pragma: no cover
    pass
ONNX_TO_NP = {v: k for k, v in NP_TO_ONNX.items()}

# AttributeProto.AttributeType
AT_FLOAT, AT_INT, AT_STRING, AT_TENSOR, AT_FLOATS, AT_INTS, AT_STRINGS = \
    1, 2, 3, 4, 6, 7, 8


def _varint(n: int) -> bytes:
    if n < 0:
        n &= (1 << 64) - 1          # two's-complement 64-bit
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _f_varint(field: int, value: int) -> bytes:
    return _key(field, 0) + _varint(int(value))


def _f_bytes(field: int, payload: bytes) -> bytes:
    return _key(field, 2) + _varint(len(payload)) + payload


def _f_string(field: int, s: str) -> bytes:
    return _f_bytes(field, s.encode("utf-8"))


def _f_float(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", v)


# --- writers ---------------------------------------------------------------


def tensor(name: str, array: onp.ndarray) -> bytes:
    """TensorProto: dims=1, data_type=2, name=8, raw_data=9."""
    array = onp.ascontiguousarray(array)
    dt = NP_TO_ONNX[array.dtype]
    out = b"".join(_f_varint(1, d) for d in array.shape)
    out += _f_varint(2, dt)
    out += _f_string(8, name)
    out += _f_bytes(9, array.tobytes())
    return out


def attribute(name: str, value: Any) -> bytes:
    """AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8,
    type=20."""
    out = _f_string(1, name)
    if isinstance(value, bool):
        out += _f_varint(3, int(value)) + _f_varint(20, AT_INT)
    elif isinstance(value, int):
        out += _f_varint(3, value) + _f_varint(20, AT_INT)
    elif isinstance(value, float):
        out += _f_float(2, value) + _f_varint(20, AT_FLOAT)
    elif isinstance(value, str):
        out += _f_string(4, value) + _f_varint(20, AT_STRING)
    elif isinstance(value, onp.ndarray):
        out += _f_bytes(5, tensor("", value)) + _f_varint(20, AT_TENSOR)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            out += b"".join(_f_float(7, v) for v in value)
            out += _f_varint(20, AT_FLOATS)
        else:
            out += b"".join(_f_varint(8, int(v)) for v in value)
            out += _f_varint(20, AT_INTS)
    else:
        raise TypeError(f"unsupported attribute {name}={value!r}")
    return out


def node(op_type: str, inputs: List[str], outputs: List[str],
         name: str = "", attrs: Optional[Dict[str, Any]] = None) -> bytes:
    """NodeProto: input=1, output=2, name=3, op_type=4, attribute=5."""
    out = b"".join(_f_string(1, i) for i in inputs)
    out += b"".join(_f_string(2, o) for o in outputs)
    out += _f_string(3, name or outputs[0])
    out += _f_string(4, op_type)
    for k, v in (attrs or {}).items():
        out += _f_bytes(5, attribute(k, v))
    return out


def value_info(name: str, elem_type: int, shape: Tuple[int, ...]) -> bytes:
    """ValueInfoProto: name=1, type=2 {tensor_type=1 {elem_type=1,
    shape=2 {dim=1 {dim_value=1}}}}."""
    dims = b"".join(_f_bytes(1, _f_varint(1, d)) for d in shape)
    tshape = _f_bytes(2, dims)
    ttype = _f_varint(1, elem_type) + tshape
    return _f_string(1, name) + _f_bytes(2, _f_bytes(1, ttype))


def graph(nodes: List[bytes], name: str, initializers: List[bytes],
          inputs: List[bytes], outputs: List[bytes]) -> bytes:
    """GraphProto: node=1, name=2, initializer=5, input=11, output=12."""
    out = b"".join(_f_bytes(1, n) for n in nodes)
    out += _f_string(2, name)
    out += b"".join(_f_bytes(5, t) for t in initializers)
    out += b"".join(_f_bytes(11, i) for i in inputs)
    out += b"".join(_f_bytes(12, o) for o in outputs)
    return out


def model(graph_bytes: bytes, opset: int = 17,
          producer: str = "mxnet_tpu") -> bytes:
    """ModelProto: ir_version=1, producer_name=2, opset_import=8
    {domain=1, version=2}, graph=7."""
    out = _f_varint(1, 8)                     # IR version 8
    out += _f_string(2, producer)
    out += _f_bytes(7, graph_bytes)
    out += _f_bytes(8, _f_string(1, "") + _f_varint(2, opset))
    return out


# --- reader ----------------------------------------------------------------


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = result = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf: bytes):
    """Yield (field_num, wire_type, value) over a message payload."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            v = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            v = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, v


def _signed(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _packed_varints(v) -> List[int]:
    """Accept either a single varint value or a packed (wire-type-2)
    payload of varints — proto3 packs repeated scalars by default, which
    is how the official onnx/PyTorch exporters write dims/ints."""
    if isinstance(v, int):
        return [_signed(v)]
    out, pos = [], 0
    while pos < len(v):
        x, pos = _read_varint(v, pos)
        out.append(_signed(x))
    return out


def _packed_floats(v) -> List[float]:
    if not isinstance(v, (bytes, bytearray)):
        return [v]
    if len(v) == 4:
        return [struct.unpack("<f", v)[0]]
    return list(struct.unpack(f"<{len(v) // 4}f", v))


def parse_tensor(buf: bytes) -> Tuple[str, onp.ndarray]:
    dims, dt, name, raw = [], FLOAT, "", b""
    floats, int64s, int32s = [], [], []
    for f, w, v in _fields(buf):
        if f == 1:
            dims.extend(_packed_varints(v))
        elif f == 2:
            dt = v
        elif f == 8:
            name = v.decode()
        elif f == 9:
            raw = v
        elif f == 4:
            floats.extend(_packed_floats(v) if w != 0 else [v])
        elif f == 7:
            int64s.extend(_packed_varints(v))
        elif f == 5:
            int32s.extend(_packed_varints(v))
    np_dt = ONNX_TO_NP[dt]
    if raw:
        arr = onp.frombuffer(raw, np_dt).reshape(dims)
    elif floats:
        arr = onp.asarray(floats, np_dt).reshape(dims)
    elif int64s:
        arr = onp.asarray(int64s, np_dt).reshape(dims)
    elif int32s:
        arr = onp.asarray(int32s, np_dt).reshape(dims)
    else:
        arr = onp.zeros(dims, np_dt)
    return name, arr


def parse_attribute(buf: bytes) -> Tuple[str, Any]:
    name, atype = "", None
    fval = ival = sval = tval = None
    floats, ints = [], []
    for f, w, v in _fields(buf):
        if f == 1:
            name = v.decode()
        elif f == 2:
            fval = struct.unpack("<f", v)[0]
        elif f == 3:
            ival = _signed(v)
        elif f == 4:
            sval = v.decode()
        elif f == 5:
            tval = parse_tensor(v)[1]
        elif f == 7:
            floats.extend(_packed_floats(v))
        elif f == 8:
            ints.extend(_packed_varints(v))
        elif f == 20:
            atype = v
    if atype == AT_FLOAT:
        return name, fval
    if atype == AT_INT:
        return name, ival
    if atype == AT_STRING:
        return name, sval
    if atype == AT_TENSOR:
        return name, tval
    if atype == AT_FLOATS:
        return name, floats
    if atype == AT_INTS:
        return name, ints
    # untyped: best-effort
    for v in (ival, fval, sval, tval):
        if v is not None:
            return name, v
    return name, ints or floats


def parse_node(buf: bytes) -> Dict[str, Any]:
    out = {"input": [], "output": [], "name": "", "op_type": "",
           "attrs": {}}
    for f, w, v in _fields(buf):
        if f == 1:
            out["input"].append(v.decode())
        elif f == 2:
            out["output"].append(v.decode())
        elif f == 3:
            out["name"] = v.decode()
        elif f == 4:
            out["op_type"] = v.decode()
        elif f == 5:
            k, val = parse_attribute(v)
            out["attrs"][k] = val
    return out


def parse_value_info(buf: bytes) -> Tuple[str, int, List[int]]:
    name, elem, shape = "", FLOAT, []
    for f, w, v in _fields(buf):
        if f == 1:
            name = v.decode()
        elif f == 2:
            for f2, _w2, v2 in _fields(v):          # TypeProto
                if f2 == 1:                          # tensor_type
                    for f3, _w3, v3 in _fields(v2):
                        if f3 == 1:
                            elem = v3
                        elif f3 == 2:                # shape
                            for f4, _w4, v4 in _fields(v3):
                                if f4 == 1:          # dim
                                    dv = 0
                                    for f5, _w5, v5 in _fields(v4):
                                        if f5 == 1:
                                            dv = _signed(v5)
                                    shape.append(dv)
    return name, elem, shape


def parse_graph(buf: bytes) -> Dict[str, Any]:
    g = {"nodes": [], "name": "", "initializers": {}, "inputs": [],
         "outputs": []}
    for f, w, v in _fields(buf):
        if f == 1:
            g["nodes"].append(parse_node(v))
        elif f == 2:
            g["name"] = v.decode()
        elif f == 5:
            n, arr = parse_tensor(v)
            g["initializers"][n] = arr
        elif f == 11:
            g["inputs"].append(parse_value_info(v))
        elif f == 12:
            g["outputs"].append(parse_value_info(v))
    return g


def parse_model(buf: bytes) -> Dict[str, Any]:
    m = {"ir_version": 0, "producer": "", "graph": None, "opset": 0}
    for f, w, v in _fields(buf):
        if f == 1:
            m["ir_version"] = v
        elif f == 2:
            m["producer"] = v.decode()
        elif f == 7:
            m["graph"] = parse_graph(v)
        elif f == 8:
            for f2, _w2, v2 in _fields(v):
                if f2 == 2:
                    m["opset"] = max(m["opset"], _signed(v2))
    return m
