"""Bridges between Gluon data loading and the DataIter world (reference
``python/mxnet/contrib/io.py``)."""
from __future__ import annotations

from .. import ndarray as nd
from ..io import DataDesc, DataIter

__all__ = ["DataLoaderIter"]


class DataLoaderIter(DataIter):
    """Wrap a ``gluon.data.DataLoader`` as a ``DataIter`` so loader-based
    pipelines feed symbolic/Module-style code (reference contrib/io.py:25).

    The last ragged batch is zero-padded up to ``batch_size`` with
    ``getpad()`` reporting the pad count, matching the reference.
    """

    def __init__(self, loader, data_name="data", label_name="softmax_label",
                 dtype="float32"):
        super().__init__()
        self._loader = loader
        self._iter = iter(loader)
        data, label = next(self._iter)
        self.batch_size = data.shape[0]
        self.dtype = dtype
        self.provide_data = [DataDesc(data_name, data.shape, dtype)]
        self.provide_label = [DataDesc(label_name, label.shape, dtype)]
        self._current_batch = None
        self.reset()

    def reset(self):
        self._iter = iter(self._loader)

    def iter_next(self):
        try:
            self._current_batch = next(self._iter)
        except StopIteration:
            self._current_batch = None
        return self._current_batch is not None

    def _padded(self, arr):
        shape = arr.shape
        out = nd.zeros((self.batch_size,) + tuple(shape[1:]),
                       dtype=self.dtype)
        out[: shape[0]] = arr.astype(self.dtype)
        return out

    def getdata(self):
        data = self._current_batch[0]
        if self.getpad():
            return [self._padded(data)]
        return [data.astype(self.dtype)]

    def getlabel(self):
        label = self._current_batch[1]
        if self.getpad():
            return [self._padded(label)]
        return [label.astype(self.dtype)]

    def getpad(self):
        return self.batch_size - self._current_batch[0].shape[0]

    def getindex(self):
        return None
