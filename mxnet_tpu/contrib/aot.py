"""Ahead-of-time compiled model artifacts (StableHLO export).

Reference analog: the TensorRT subgraph backend
(``python/mxnet/contrib/tensorrt.py``,
``src/operator/subgraph/tensorrt/nnvm_to_onnx.cc``) — hand the inference
graph to an engine-specific compiler and ship the compiled artifact.  On
TPU the engine compiler is XLA itself, so the TPU-native answer is:
serialize the hybridized forward as portable **StableHLO** plus the
parameters, and reload/run it anywhere a JAX runtime exists — no model
code, no framework Python classes, versioned IR stability guaranteed by
StableHLO.

    from mxnet_tpu.contrib import aot
    aot.export_block(net, example, "model.mxa")     # after net(example)
    run = aot.load("model.mxa")
    y = run(x)                                      # numpy/jax array in/out

The artifact also serves the reference's `HybridBlock.export` role for
deployment, with a stronger contract: `SymbolBlock.imports` needs this
framework to rebuild the graph; an `.mxa` needs only jax.

Format: a zip archive (``header.json`` + ``model.stablehlo`` +
``params.npz``) — a pure data container, deliberately NOT pickle, so
loading an untrusted artifact cannot execute code.  The batch (leading)
dimension is exported symbolically by default, so one artifact serves any
batch size; the remaining dimensions are static (XLA's compilation
model).
"""
from __future__ import annotations

import io
import json
import zipfile
from typing import Any, Dict

import numpy as onp

__all__ = ["export_block", "load", "AOT_FORMAT_VERSION"]

AOT_FORMAT_VERSION = 2


def export_block(block, example_input, path: str, *, platforms=None,
                 polymorphic_batch: bool = True) -> str:
    """Serialize ``block``'s inference forward to StableHLO + params.

    ``block`` must have run at least one forward (all parameter shapes
    known — uninitialized deferred-shape parameters raise).  With
    ``polymorphic_batch`` (default) the example's leading dimension is
    exported as a symbolic size so the artifact serves any batch; other
    dimensions are compiled statically.  ``platforms``: optional list like
    ["tpu", "cpu"] to pin lowering targets.
    """
    import jax
    from jax import export as jexport

    from ..ndarray import NDArray
    from ..parallel.train import functional_call

    # p.data() raises a clear "not initialized" error for deferred-shape
    # params; silently skipping them would bake trace-time random inits
    # into the StableHLO as constants (a silently-wrong artifact)
    params = {n: p.data()._data for n, p in block.collect_params().items()}
    x = example_input._data if isinstance(example_input, NDArray) \
        else onp.asarray(example_input)

    def fwd(param_arrays: Dict[str, Any], data):
        out, _mut = functional_call(block, param_arrays, (data,),
                                    training=False)
        if isinstance(out, (list, tuple)):
            return tuple(o._data if isinstance(o, NDArray) else o
                         for o in out)
        return out._data if isinstance(out, NDArray) else out

    if polymorphic_batch and getattr(x, "ndim", 0) >= 1:
        (b,) = jexport.symbolic_shape("b")
        in_shape = (b,) + tuple(x.shape[1:])
    else:
        in_shape = tuple(x.shape)

    kwargs = {}
    if platforms is not None:
        kwargs["platforms"] = tuple(platforms)
    exported = jexport.export(jax.jit(fwd), **kwargs)(
        {n: jax.ShapeDtypeStruct(a.shape, a.dtype)
         for n, a in params.items()},
        jax.ShapeDtypeStruct(in_shape, x.dtype))

    header = {
        "format_version": AOT_FORMAT_VERSION,
        "input_shape": ["b" if polymorphic_batch else int(x.shape[0])]
        + [int(d) for d in x.shape[1:]],
        "input_dtype": str(x.dtype),
        "param_names": sorted(params),
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("header.json", json.dumps(header))
        zf.writestr("model.stablehlo", exported.serialize())
        buf = io.BytesIO()
        onp.savez(buf, **{n: onp.asarray(a) for n, a in params.items()})
        zf.writestr("params.npz", buf.getvalue())
    return path


class _AOTModel:
    """Loaded artifact: a callable closed over the deserialized StableHLO
    computation and the parameter arrays."""

    def __init__(self, header, stablehlo: bytes, params):
        from jax import export as jexport

        self.format_version = header["format_version"]
        self.input_shape = header["input_shape"]
        self.input_dtype = header["input_dtype"]
        self._params = params
        self._exported = jexport.deserialize(stablehlo)

    def __call__(self, data):
        from ..ndarray import NDArray

        if isinstance(data, NDArray):
            data = data._data
        return self._exported.call(self._params, data)


def load(path: str) -> _AOTModel:
    """Load an .mxa artifact.  The container is plain data (zip of JSON +
    StableHLO bytes + npz) — no code execution on load, safe for
    untrusted files."""
    with zipfile.ZipFile(path, "r") as zf:
        header = json.loads(zf.read("header.json"))
        ver = header.get("format_version")
        if ver != AOT_FORMAT_VERSION:
            raise ValueError(
                f"unsupported .mxa format version {ver} "
                f"(this build reads {AOT_FORMAT_VERSION})")
        stablehlo = zf.read("model.stablehlo")
        npz = onp.load(io.BytesIO(zf.read("params.npz")),
                       allow_pickle=False)
        params = {n: npz[n] for n in npz.files}
    return _AOTModel(header, stablehlo, params)
