"""``mx.contrib.symbol`` — contrib operators as Symbol builders (reference
``python/mxnet/contrib/symbol.py``; resolution is dynamic through
``mxnet_tpu.symbol.contrib``)."""
from ..symbol import contrib as _c


def __getattr__(name):
    return getattr(_c, name)
