"""``mx.contrib`` (reference ``python/mxnet/contrib/``)."""
from . import onnx
from . import quantization
from . import text
