"""``mx.contrib`` (reference ``python/mxnet/contrib/``)."""
from . import aot
from . import io
from . import ndarray
from . import ndarray as nd
from . import onnx
from . import quantization
from . import symbol
from . import symbol as sym
from . import tensorboard
from . import text
