"""``mx.contrib`` (reference ``python/mxnet/contrib/``)."""
from . import aot
from . import onnx
from . import quantization
from . import text
