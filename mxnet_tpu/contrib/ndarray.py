"""``mx.contrib.ndarray`` — contrib operators under the ndarray API
(reference ``python/mxnet/contrib/ndarray.py``, where generated contrib op
wrappers are attached; here every registry op resolves dynamically through
``mxnet_tpu.ndarray.contrib``)."""
from ..ndarray.contrib import *  # noqa: F401,F403
from ..ndarray import contrib as _c


def __getattr__(name):
    return getattr(_c, name)
