"""INT8 quantized inference: calibrate -> convert -> run.

Reference: ``src/operator/quantization/`` (quantize/dequantize/requantize
ops, quantized conv/fc kernels, calibrate.cc's naive/entropy threshold
selection, and quantize_graph_pass.cc's graph rewrite that wraps
quantizable nodes in quantize/dequantize pairs; python driver
python/mxnet/contrib/quantization.py quantize_model).

TPU-native design: the graph rewrite happens on the Symbol DAG (the same
artifact hybridize traces), and the quantized kernels are XLA lowerings
that keep the s8 x s8 -> s32 matmul/conv on the MXU with per-tensor
scales applied as cheap epilogues — XLA fuses the dequantize into the
surrounding elementwise work.  Activation ranges come from running the
fp32 graph on calibration batches and recording per-node output ranges
(naive min/max or percentile clipping, the entropy-lite analog).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as onp

from ..ops.registry import register

__all__ = ["quantize", "dequantize", "requantize", "collect_calib_ranges",
           "quantize_symbol", "quantize_net", "QuantizedNet",
           "pallas_skipped_count"]

INT8_MIN, INT8_MAX = -127.0, 127.0       # symmetric, matches reference

# The final half of ROADMAP item 2's "fix or delete loudly" verdict on
# the Pallas int8 conv path: chip bench (BENCH_builder_r05) measured it
# at 0.345x of plain lax — and int8 itself LOSING to bf16 at matched
# batch — so round 9 DELETED the conv kernels (int8_conv1x1/int8_conv3x3
# are gone from ops/pallas_kernels.py; the rebuilt int8_matmul stays as
# the microbench A/B vehicle).  Every conv a Pallas route would have
# claimed is still counted here and logged once per process, and setting
# MXNET_INT8_PALLAS nonzero now REFUSES loudly instead of routing.
from .. import telemetry as _telemetry

_PALLAS_SKIPPED = _telemetry.counter(
    "quantization.pallas_skipped",
    "quantized convs a Pallas int8 route would have claimed (the "
    "kernel was retired on the 0.345x measurement)")
_PALLAS_SKIP_LOGGED = False

_INT8_PALLAS_VERDICT = (
    "the Pallas int8 conv route was retired in round 9: it measured "
    "0.345x of plain lax.conv s8 on chip and int8 lost to bf16 at "
    "matched batch (BENCH_builder_r05.json lanes[].pallas_vs_lax; "
    "docs/PERF.md 'MFU campaign round 2').  Quantized convs always use "
    "lax.conv s8->s32 on the MXU.  The rebuilt fused int8 matmul "
    "(ops/pallas_kernels.py int8_matmul: (m,n,k) grid, s32 VMEM "
    "accumulator, in-register requantize) is re-measured by 'python "
    "benchmark/microbench_tpu.py --which int8' (section_int8_pallas); "
    "production re-entry requires that bench to beat lax on chip.")


def pallas_skipped_count() -> int:
    """Quantized convs that a Pallas int8 route would have claimed
    (the kernel was retired on the 0.345x measurement; see
    ``_INT8_PALLAS_VERDICT``).  View over the
    ``quantization.pallas_skipped`` telemetry counter."""
    return int(_PALLAS_SKIPPED.value)


def _count_pallas_skip() -> None:
    global _PALLAS_SKIP_LOGGED
    _PALLAS_SKIPPED.inc()
    if not _PALLAS_SKIP_LOGGED:
        _PALLAS_SKIP_LOGGED = True
        from .. import log as _log

        _log.get_logger("mxnet_tpu.quantization").warning(
            "quantized convs use plain lax.conv s8 — "
            + _INT8_PALLAS_VERDICT
            + "  [logged once; convs counted in "
            "quantization.pallas_skipped_count()]")


# ---------------------------------------------------------------------------
# ops (reference quantize.cc / dequantize.cc / requantize.cc)
# ---------------------------------------------------------------------------

@register("quantize", num_inputs=1, num_outputs=-1, differentiable=False)
def quantize(data, min_range=-1.0, max_range=1.0, out_type="int8"):
    """fp32 -> int8 with symmetric scale from the calibrated range
    (reference quantize_v2 with min/max_calib_range)."""
    scale = INT8_MAX / jnp.maximum(jnp.maximum(abs(float(min_range)),
                                               abs(float(max_range))),
                                   1e-12)
    q = jnp.clip(jnp.round(data * scale), INT8_MIN, INT8_MAX).astype(
        jnp.int8)
    return (q, jnp.float32(min_range), jnp.float32(max_range))


@register("dequantize", num_inputs=3, differentiable=False)
def dequantize(qdata, min_range, max_range, out_type="float32"):
    scale = jnp.maximum(jnp.maximum(jnp.abs(min_range),
                                    jnp.abs(max_range)), 1e-12) / INT8_MAX
    return qdata.astype(jnp.float32) * scale


@register("requantize", num_inputs=3, num_outputs=-1, differentiable=False)
def requantize(qdata32, min_range, max_range, min_calib_range=None,
               max_calib_range=None):
    """int32 accumulator -> int8 with a new scale (reference
    requantize.cc)."""
    in_scale = jnp.maximum(jnp.maximum(jnp.abs(min_range),
                                       jnp.abs(max_range)), 1e-12) / (
        INT8_MAX * INT8_MAX)
    f = qdata32.astype(jnp.float32) * in_scale
    lo = float(min_calib_range if min_calib_range is not None else -1.0)
    hi = float(max_calib_range if max_calib_range is not None else 1.0)
    out_scale = INT8_MAX / max(abs(lo), abs(hi), 1e-12)
    q = jnp.clip(jnp.round(f * out_scale), INT8_MIN, INT8_MAX).astype(
        jnp.int8)
    return (q, jnp.float32(lo), jnp.float32(hi))


def _sym_scale(lo: float, hi: float) -> float:
    return max(abs(lo), abs(hi), 1e-12) / INT8_MAX


def _quantized_epilogue(out, fused_relu, out_min, out_max):
    """Shared epilogue: optional fused relu, then optional fused
    REQUANTIZE (the reference's quantize_graph_pass.cc requantize-fusion):
    when the consumer is another quantized kernel, emit int8 directly at
    the consumer's calibrated scale instead of fp32 -> separate quantize
    node.  Halves the node count of deep int8 graphs — the round-2 ~8-min
    tunnel compile came from those chains."""
    if fused_relu:
        out = jnp.maximum(out, 0)
    if out_min is not None and out_max is not None:
        scale = INT8_MAX / max(abs(float(out_min)), abs(float(out_max)),
                               1e-12)
        out = jnp.clip(jnp.round(out * scale), INT8_MIN, INT8_MAX).astype(
            jnp.int8)
    return out


@register("quantized_fully_connected", num_inputs=-1, differentiable=False)
def quantized_fully_connected(arrays, num_hidden=0, no_bias=False,
                              flatten=True, data_scale=1.0, w_scale=1.0,
                              fused_relu=False, out_min=None, out_max=None):
    """s8 data x s8 weight -> s32 on the MXU, fp32 epilogue (reference
    quantized_fully_connected.cc).  arrays = [qdata, qweight, (bias fp32)]."""
    qd, qw = arrays[0], arrays[1]
    if flatten and qd.ndim > 2:
        qd = qd.reshape(qd.shape[0], -1)
    acc = jax.lax.dot_general(
        qd, qw, (((qd.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (data_scale * w_scale)
    if not no_bias and len(arrays) > 2:
        out = out + arrays[2]
    return _quantized_epilogue(out, fused_relu, out_min, out_max)


def _refuse_pallas_int8(kernel, stride, dilate, pad, num_group, layout):
    """The retired-route gate: geometries a Pallas int8 conv would have
    claimed (NHWC 1x1 any-stride / 3x3 stride-1/pad-1) count a skip and
    log once; a nonzero MXNET_INT8_PALLAS refuses LOUDLY with the
    measurement instead of silently routing nowhere."""
    from .. import config as _config
    from ..base import MXNetError

    mode = _config.get("MXNET_INT8_PALLAS")
    if mode:
        raise MXNetError(
            f"MXNET_INT8_PALLAS={mode} refused: " + _INT8_PALLAS_VERDICT)
    if (tuple(dilate) == (1, 1) and num_group == 1 and layout == "NHWC"
            and (tuple(kernel) == (1, 1) and tuple(pad) == (0, 0)
                 or tuple(kernel) == (3, 3) and tuple(stride) == (1, 1)
                 and tuple(pad) == (1, 1))):
        _count_pallas_skip()


@register("quantized_conv", num_inputs=-1, differentiable=False)
def quantized_conv(arrays, kernel=(1, 1), stride=(1, 1), dilate=(1, 1),
                   pad=(0, 0), num_filter=1, num_group=1, no_bias=False,
                   layout=None, data_scale=1.0, w_scale=1.0,
                   fused_relu=False, out_min=None, out_max=None):
    """s8 conv with s32 accumulation (reference quantized_conv.cc).

    Layout-general like the fp32 Convolution op: the NHWC fast path the
    bench uses quantizes without relayouts (weights stay in the layout the
    fp32 model trained in — O is axis 0 for both OIHW and OHWI, so the
    offline weight quantization is layout-independent)."""
    from ..ops.nn import (_conv_dimension_numbers, _tup,
                          maybe_pad_conv_channels)

    qd, qw = arrays[0], arrays[1]
    nsp = len(kernel)
    if layout is None:
        layout = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[nsp]
    stride = _tup(stride, nsp) if stride else (1,) * nsp
    dilate = _tup(dilate, nsp) if dilate else (1,) * nsp
    pad = _tup(pad, nsp) if pad else (0,) * nsp

    _refuse_pallas_int8(kernel, stride, dilate, pad, num_group, layout)
    qd = qd.astype(jnp.int8)
    qw = qw.astype(jnp.int8)
    # MXU-alignment padding pass (ops/nn.py): int8 sublane quantum is 32,
    # so misaligned channel axes pad with zero taps (exact in integer
    # math) and Cout slices back below
    c_axis = layout.index("C")
    true_cout = None
    padded = maybe_pad_conv_channels(qd, qw, layout, num_group)
    if padded is not None:
        qd, qw, true_cout = padded
    dn = jax.lax.conv_dimension_numbers(
        qd.shape, qw.shape, _conv_dimension_numbers(layout))
    out = jax.lax.conv_general_dilated(
        qd, qw,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate, feature_group_count=num_group,
        dimension_numbers=dn,
        preferred_element_type=jnp.int32)
    if true_cout is not None and out.shape[c_axis] != true_cout:
        out = jax.lax.slice_in_dim(out, 0, true_cout, axis=c_axis)
    out = out.astype(jnp.float32) * (data_scale * w_scale)
    if not no_bias and len(arrays) > 2:
        shape = [1] * out.ndim
        shape[c_axis] = arrays[2].shape[0]
        out = out + arrays[2].reshape(shape)
    return _quantized_epilogue(out, fused_relu, out_min, out_max)


# ---------------------------------------------------------------------------
# calibration (reference calibrate.cc + quantize_model driver)
# ---------------------------------------------------------------------------

def collect_calib_ranges(sym, feeds: List[Dict[str, Any]],
                         mode: str = "naive",
                         percentile: float = 99.99) -> Dict[str, Tuple[float,
                                                                       float]]:
    """Run the fp32 graph on calibration batches and record per-node output
    ranges.  ``mode='naive'`` = min/max (reference CalibrationNaive);
    ``'percentile'`` clips outliers (the entropy-lite analog of
    CalibrationEntropy)."""
    from ..symbol.symbol import execute_graph

    nodes = sym._topo()
    entries = [(n, i) for n in nodes if n.op is not None
               for i in range(n.num_outputs)]
    names = [_out_name(n, i) for (n, i) in entries]
    ranges: Dict[str, Tuple[float, float]] = {}
    for feed in feeds:
        feed = {k: (v._data if hasattr(v, "_data") else jnp.asarray(v))
                for k, v in feed.items()}
        outs = execute_graph(entries, feed)
        for name, o in zip(names, outs):
            if not jnp.issubdtype(o.dtype, jnp.floating):
                continue
            v = onp.asarray(o, onp.float32).reshape(-1)
            if mode == "percentile":
                lo = float(onp.percentile(v, 100.0 - percentile))
                hi = float(onp.percentile(v, percentile))
            else:
                lo, hi = float(v.min()), float(v.max())
            if name in ranges:
                plo, phi = ranges[name]
                ranges[name] = (min(lo, plo), max(hi, phi))
            else:
                ranges[name] = (lo, hi)
    return ranges


def _out_name(n, i):
    return n.name if n.num_outputs == 1 else f"{n.name}:{i}"


# ---------------------------------------------------------------------------
# graph rewrite (reference quantize_graph_pass.cc)
# ---------------------------------------------------------------------------

QUANTIZABLE = {"Convolution", "FullyConnected"}


def _consumer_map(sym):
    """id(node) -> [(consumer_node, input_pos)] plus head multiplicity."""
    cons: Dict[int, list] = {}
    heads: Dict[int, int] = {}
    for n in sym._topo():
        for pos, (src, _i) in enumerate(n.inputs):
            cons.setdefault(id(src), []).append((n, pos))
    for (h, _i) in sym._outputs:
        heads[id(h)] = heads.get(id(h), 0) + 1
    return cons, heads


def _constant_fold(sym, param_arrays: Dict[str, onp.ndarray]):
    """Evaluate param-only subtrees offline and replace them with new
    params (reference analog: the MKLDNN subgraph fuser sees weights as
    constants; here e.g. the space-to-depth stem re-expresses conv0's
    weight as reshape/transpose ops over the stored param, which must
    collapse back to a plain variable for the BN fold and offline weight
    quantization to see a Convolution fed by a param).  Returns
    (new_sym, new_params)."""
    from ..symbol.symbol import SymNode, Symbol, execute_graph

    nodes = sym._topo()
    const: Dict[int, bool] = {}
    for n in nodes:
        if n.op is None:
            const[id(n)] = n.name in param_arrays
        else:
            det = not any(k in n.op.lower()
                          for k in ("rand", "dropout", "sample"))
            const[id(n)] = (det and bool(n.inputs)
                            and all(const[id(s)] for (s, _i) in n.inputs))
    cons, heads = _consumer_map(sym)
    frontier = [n for n in nodes if n.op is not None and const[id(n)]
                and (id(n) in heads
                     or any(not const[id(u)]
                            for (u, _p) in cons.get(id(n), [])))]
    if not frontier:
        return sym, param_arrays
    entries = [(n, i) for n in frontier for i in range(n.num_outputs)]
    outs = execute_graph(entries, {k: jnp.asarray(v)
                                   for k, v in param_arrays.items()})
    new_params = dict(param_arrays)
    repl: Dict[Tuple[int, int], SymNode] = {}
    for (n, i), o in zip(entries, outs):
        name = f"{n.name}_const" + (str(i) if n.num_outputs > 1 else "")
        while name in new_params:
            name += "_"
        new_params[name] = onp.asarray(o)
        repl[(id(n), i)] = SymNode(None, name, {}, [])
    cache: Dict[int, SymNode] = {}

    def rebuild(n) -> SymNode:
        got = cache.get(id(n))
        if got is not None:
            return got
        ins = []
        for (src, i) in n.inputs:
            r = repl.get((id(src), i))
            ins.append((r, 0) if r is not None else (rebuild(src), i))
        out = SymNode(n.op, n.name, dict(n.attrs), ins, n.num_outputs)
        out.attr_dict = dict(n.attr_dict)     # keep AttrScope/__shape__
        cache[id(n)] = out
        return out

    new_outputs = [((repl[(id(n), i)], 0) if (id(n), i) in repl
                    else (rebuild(n), i)) for (n, i) in sym._outputs]
    return Symbol(new_outputs), new_params


def _fold_bn_relu(sym, param_arrays: Dict[str, onp.ndarray]):
    """Inference-graph fusion BEFORE quantization (the reference reaches
    the same shape through the MKLDNN subgraph fuser + quantize pass:
    conv+bn+relu collapses to one conv with folded weights and a relu
    epilogue).  BatchNorm running stats fold into the conv's weight/bias:

        w'[c] = w[c] * gamma_c / sqrt(var_c + eps)
        b'[c] = (b[c] - mean_c) * gamma_c / sqrt(var_c + eps) + beta_c

    The folded node takes the name of the LAST fused op so downstream
    calibrated-range lookups keyed by original output names still hit.
    Only single-consumer chains fold (a second consumer still needs the
    unfused intermediate).  Returns (new_sym, new_params).
    """
    from ..symbol.symbol import SymNode, Symbol

    cons, heads = _consumer_map(sym)
    new_params = dict(param_arrays)

    def _single_consumer(n):
        return len(cons.get(id(n), [])) == 1 and id(n) not in heads

    cache: Dict[int, SymNode] = {}

    def fold(n) -> SymNode:
        got = cache.get(id(n))
        if got is not None:
            return got
        new_inputs = [(fold(src), i) for (src, i) in n.inputs]
        out = None
        if (n.op == "BatchNorm" and len(n.inputs) == 5
                and not n.attrs.get("training")
                and not n.attrs.get("output_mean_var")):
            conv_orig, _ci = n.inputs[0]
            conv_new = new_inputs[0][0]
            # the BN must normalize the conv's output-channel axis (axis 1
            # for NCHW, 3 for NHWC); the per-channel fold math itself is
            # layout-independent because O is axis 0 of the weight either way
            conv_layout = (conv_new.attrs.get("layout") or "NCHW"
                           if conv_new.op == "Convolution" else "NCHW")
            axis_ok = int(n.attrs.get("axis", 1)) == conv_layout.index("C")
            stat_names = [s.name for (s, _j) in n.inputs[1:]]
            w_ok = (axis_ok
                    and conv_new.op == "Convolution"
                    and len(conv_new.inputs) >= 2
                    and conv_new.inputs[1][0].op is None
                    and conv_new.inputs[1][0].name in new_params
                    and (conv_new.attrs.get("no_bias", False)
                         or len(conv_new.inputs) < 3
                         or (conv_new.inputs[2][0].op is None
                             and conv_new.inputs[2][0].name in new_params)))
            if (w_ok and _single_consumer(conv_orig)
                    and all(s in new_params for s in stat_names)):
                g, beta, mean, var = (new_params[s] for s in stat_names)
                if n.attrs.get("fix_gamma", True):
                    g = onp.ones_like(g)
                eps = float(n.attrs.get("eps", 1e-3))
                scale = g / onp.sqrt(var + eps)
                w_name = conv_new.inputs[1][0].name
                w = new_params[w_name]
                if conv_new.attrs.get("no_bias", False) \
                        or len(conv_new.inputs) < 3:
                    b = onp.zeros(w.shape[0], w.dtype)
                else:
                    b = new_params[conv_new.inputs[2][0].name]
                wf = (w * scale.reshape((-1,) + (1,) * (w.ndim - 1))) \
                    .astype(w.dtype)
                bf = ((b - mean) * scale + beta).astype(w.dtype)
                wf_name, bf_name = n.name + "_wfold", n.name + "_bfold"
                new_params[wf_name] = wf
                new_params[bf_name] = bf
                attrs = dict(conv_new.attrs)
                attrs["no_bias"] = False
                out = SymNode("Convolution", n.name, attrs,
                              [conv_new.inputs[0],
                               (SymNode(None, wf_name, {}, []), 0),
                               (SymNode(None, bf_name, {}, []), 0)],
                              num_outputs=1)
                out.attrs["_bn_folded"] = True
        elif ((n.op == "Activation"
               and n.attrs.get("act_type", "relu") == "relu")
              or n.op == "relu"):
            src_orig, _si = n.inputs[0]
            src_new = new_inputs[0][0]
            if (src_new.op in QUANTIZABLE
                    and src_new.attrs.get("_bn_folded")
                    and _single_consumer(src_orig)):
                attrs = dict(src_new.attrs)
                attrs["fused_relu"] = True
                out = SymNode(src_new.op, n.name, attrs,
                              list(src_new.inputs), num_outputs=1)
        if out is None:
            out = SymNode(n.op, n.name, dict(n.attrs), new_inputs,
                          n.num_outputs)
            out.attr_dict = dict(n.attr_dict)
        cache[id(n)] = out
        return out

    new_sym = Symbol([(fold(n), i) for (n, i) in sym._outputs])
    # the internal marker must not leak into serialized graphs
    for n in new_sym._topo():
        n.attrs.pop("_bn_folded", None)
    return new_sym, new_params


def _fuse_requantize(sym) -> int:
    """Reference quantize_graph_pass.cc requantize-fusion, TPU shape:
    when EVERY consumer of a quantized kernel is a `quantize` node with
    one identical calibrated range, emit int8 from the kernel's epilogue
    (out_min/out_max attrs) and delete the quantize nodes.  Mutates the
    graph in place; returns the number of kernels fused."""
    cons, heads = _consumer_map(sym)
    fused = 0
    for n in sym._topo():
        if n.op not in ("quantized_conv", "quantized_fully_connected"):
            continue
        if id(n) in heads:
            continue
        users = cons.get(id(n), [])
        if not users or not all(u.op == "quantize" for (u, _p) in users):
            continue
        if any(id(u) in heads for (u, _p) in users):
            continue          # a head quantize node must keep quantizing
        ranges = {(float(u.attrs.get("min_range", -1.0)),
                   float(u.attrs.get("max_range", 1.0)))
                  for (u, _p) in users}
        if len(ranges) != 1:
            continue
        (lo, hi), = ranges
        n.attrs["out_min"], n.attrs["out_max"] = lo, hi
        for (q, _p) in users:
            for (c2, p2) in cons.get(id(q), []):
                c2.inputs[p2] = (n, 0)
        fused += 1
    return fused


def quantize_symbol(sym, params: Dict[str, Any],
                    calib_ranges: Dict[str, Tuple[float, float]],
                    quantized_dtype: str = "int8",
                    excluded_names: Tuple[str, ...] = ()):
    """Rewrite a Symbol: every quantizable node whose input range was
    calibrated becomes a quantized kernel fed by int8 weights (offline
    quantized here) and int8 activations (quantized at run time with the
    calibrated scale).  Returns (new_sym, new_params).

    Mirrors quantize_graph_pass.cc: nodes not in QUANTIZABLE (or
    explicitly excluded) stay fp32; dequantize happens in the kernel
    epilogue so adjacent fp32 ops see ordinary floats.
    """
    from ..symbol.symbol import SymNode, Symbol

    param_arrays = {k: (v.asnumpy() if hasattr(v, "asnumpy")
                        else onp.asarray(v)) for k, v in params.items()}
    # param-only subtrees (e.g. the s2d stem's weight re-expression)
    # collapse to plain params first so the folds below see conv-fed-by-
    # variable shapes; then conv+bn(+relu) -> one conv with folded weights
    # and a relu epilogue (reference: MKLDNN subgraph fuse + quantize pass)
    sym, param_arrays = _constant_fold(sym, param_arrays)
    sym, param_arrays = _fold_bn_relu(sym, param_arrays)
    new_params: Dict[str, onp.ndarray] = dict(param_arrays)
    cache: Dict[int, SymNode] = {}

    def rewrite(n) -> SymNode:
        got = cache.get(id(n))
        if got is not None:
            return got
        new_inputs = [(rewrite(src), i) for (src, i) in n.inputs]
        out = None
        # quantized_conv implements the 2D NCHW/NHWC paths (the bench's
        # channel-minor fast path quantizes natively); other ranks /
        # layouts stay fp32 rather than silently mis-lowering
        conv_ok = (n.op != "Convolution"
                   or (len(n.attrs.get("kernel", ())) == 2
                       and n.attrs.get("layout") in (None, "NCHW", "NHWC")))
        if (n.op in QUANTIZABLE and conv_ok
                and n.name not in excluded_names
                and len(n.inputs) >= 2):
            data_src, data_idx = n.inputs[0]
            w_src, _wi = n.inputs[1]
            in_name = _out_name(data_src, data_idx)
            w_is_param = w_src.op is None and w_src.name in param_arrays
            rng = calib_ranges.get(in_name)
            if data_src.op is None:          # graph input: calibrated too?
                rng = rng or calib_ranges.get(data_src.name)
            if w_is_param and rng is not None:
                lo, hi = rng
                d_scale = _sym_scale(lo, hi)
                w = param_arrays[w_src.name]
                w_absmax = float(onp.abs(w).max()) or 1e-12
                w_scale = w_absmax / INT8_MAX
                qw = onp.clip(onp.round(w / w_scale), INT8_MIN,
                              INT8_MAX).astype(onp.int8)
                qw_name = w_src.name + "_quantized"
                new_params[qw_name] = qw
                qw_node = SymNode(None, qw_name, {}, [])
                # runtime activation quantize with the calibrated range
                qa = SymNode("quantize", n.name + "_qdata",
                             {"min_range": lo, "max_range": hi},
                             [new_inputs[0]])
                qop = ("quantized_conv" if n.op == "Convolution"
                       else "quantized_fully_connected")
                attrs = dict(n.attrs)
                attrs["data_scale"] = d_scale
                attrs["w_scale"] = w_scale
                q_inputs = [(qa, 0), (qw_node, 0)] + new_inputs[2:]
                out = SymNode(qop, n.name + "_quantized", attrs, q_inputs,
                              num_outputs=1)
        if out is None:
            out = SymNode(n.op, n.name, dict(n.attrs), new_inputs,
                          n.num_outputs)
        cache[id(n)] = out
        return out

    new_outputs = [(rewrite(n), i) for (n, i) in sym._outputs]
    new_sym = Symbol(new_outputs)
    _fuse_requantize(new_sym)
    # prune params the rewritten graph no longer references (a shared /
    # excluded consumer may still need the fp32 copy, so pruning is by
    # actual reference, not by what was quantized)
    referenced = {n.name for n in new_sym._topo() if n.op is None}
    new_params = {k: v for k, v in new_params.items() if k in referenced}
    return new_sym, new_params


class QuantizedNet:
    """Callable wrapper: jitted execution of a quantized symbol."""

    def __init__(self, sym, params: Dict[str, onp.ndarray]):
        from ..symbol.symbol import _jit_graph

        self.sym = sym
        self.params = {k: jnp.asarray(v) for k, v in params.items()}
        data_names = [a for a in sym.list_arguments() if a not in params]
        assert len(data_names) == 1, data_names
        self._data_name = data_names[0]
        self._fn = _jit_graph(sym)          # shared jit cache (symbol.py)

    def stage(self, device=None):
        """Commit the quantized params to ``device`` (default backend's
        device 0 when None).  Conversion/calibration usually runs under a
        host-CPU default device; without re-staging, every call would
        re-transfer the weights to the accelerator."""
        device = device or jax.devices()[0]
        self.params = {k: jax.device_put(v, device)
                       for k, v in self.params.items()}
        jax.block_until_ready(list(self.params.values()))
        return self

    def __call__(self, x):
        x = x._data if hasattr(x, "_data") else jnp.asarray(x)
        outs = self._fn({**self.params, self._data_name: x})
        return outs[0] if len(outs) == 1 else outs


def quantize_net(net, calib_data: List[Any], calib_mode: str = "naive",
                 quantized_dtype: str = "int8",
                 excluded_names: Tuple[str, ...] = ()) -> QuantizedNet:
    """End-to-end driver (reference contrib/quantization.py
    quantize_model): trace the hybridizable ``net``, calibrate on the
    given batches, rewrite the graph, return a jitted int8 predictor."""
    from ..ndarray import NDArray
    from ..ndarray.ndarray import _wrap
    from ..context import current_context

    first = calib_data[0]
    if not isinstance(first, NDArray):
        first = _wrap(jnp.asarray(first), current_context())
    net(first)                                  # ensure traced shapes
    sym = net._trace_symbol()
    params = {k: v.data() for k, v in net.collect_params().items()}
    data_names = [a for a in sym.list_arguments() if a not in params]
    assert len(data_names) == 1, f"single-input nets only: {data_names}"
    feeds = [{data_names[0]: (b._data if hasattr(b, "_data")
                              else jnp.asarray(b))} for b in calib_data]
    for f in feeds:
        for k, v in params.items():
            f[k] = v._data if hasattr(v, "_data") else jnp.asarray(v)
    ranges = collect_calib_ranges(sym, feeds, mode=calib_mode)
    # graph inputs get their own observed range
    for f in feeds:
        v = onp.asarray(f[data_names[0]], onp.float32)
        lo, hi = float(v.min()), float(v.max())
        if data_names[0] in ranges:
            plo, phi = ranges[data_names[0]]
            lo, hi = min(lo, plo), max(hi, phi)
        ranges[data_names[0]] = (lo, hi)
    qsym, qparams = quantize_symbol(sym, params, ranges,
                                    quantized_dtype=quantized_dtype,
                                    excluded_names=excluded_names)
    return QuantizedNet(qsym, qparams)


# ---------------------------------------------------------------------------
# quantized operator breadth (reference src/operator/quantization/*.cc):
# int8 flows through pooling/activation/shape ops unchanged (same scale),
# elementwise arithmetic accumulates in int32, batch_norm folds into the
# scale, embedding gathers int8 rows.  All registered under both the bare
# and the reference's _contrib_* names.
# ---------------------------------------------------------------------------

@register("quantize_v2", num_inputs=1, num_outputs=-1, differentiable=False,
          aliases=("_contrib_quantize_v2",))
def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8"):
    """Calibrated-range quantize (reference quantize_v2.cc); without a
    calibrated range, the data min/max is used (the reference's runtime
    min/max path)."""
    if min_calib_range is None or max_calib_range is None:
        amax = jnp.maximum(jnp.max(jnp.abs(data)), 1e-12)
        scale = INT8_MAX / amax
        q = jnp.clip(jnp.round(data * scale), INT8_MIN, INT8_MAX).astype(
            jnp.int8)
        return (q, -amax, amax)
    lo, hi = float(min_calib_range), float(max_calib_range)
    scale = INT8_MAX / max(abs(lo), abs(hi), 1e-12)
    q = jnp.clip(jnp.round(data * scale), INT8_MIN, INT8_MAX).astype(
        jnp.int8)
    return (q, jnp.float32(lo), jnp.float32(hi))


@register("quantized_act", num_inputs=3, num_outputs=-1,
          differentiable=False, aliases=("_contrib_quantized_act",))
def quantized_act(qdata, min_range, max_range, act_type="relu"):
    """int8 activation (reference quantized_activation.cc): relu keeps the
    scale (max(0,x) in int domain)."""
    if act_type != "relu":
        raise NotImplementedError(
            f"quantized_act supports relu (got {act_type}); dequantize for "
            "other activations")
    return (jnp.maximum(qdata, 0), min_range, max_range)


@register("quantized_pooling", num_inputs=3, num_outputs=-1,
          differentiable=False, aliases=("_contrib_quantized_pooling",))
def quantized_pooling(qdata, min_range, max_range, kernel=(2, 2),
                      stride=None, pad=(0, 0), pool_type="max",
                      global_pool=False):
    """int8 pooling (reference quantized_pooling.cc): max-pool stays in
    int8; avg-pool accumulates in int32 then renormalizes."""
    n, c, h, w = qdata.shape
    if global_pool:
        kernel, stride, pad = (h, w), (1, 1), (0, 0)
    stride = stride or kernel
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    pads = ((0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1]))
    if pool_type == "max":
        out = jax.lax.reduce_window(qdata, jnp.int8(-128), jax.lax.max,
                                    window, strides, pads)
    else:
        acc = jax.lax.reduce_window(
            qdata.astype(jnp.int32), jnp.int32(0), jax.lax.add, window,
            strides, pads)
        out = jnp.clip(jnp.round(acc / (kernel[0] * kernel[1])),
                       INT8_MIN, INT8_MAX).astype(jnp.int8)
    return (out, min_range, max_range)


@register("quantized_flatten", num_inputs=3, num_outputs=-1,
          differentiable=False, aliases=("_contrib_quantized_flatten",))
def quantized_flatten(qdata, min_range, max_range):
    return (qdata.reshape(qdata.shape[0], -1), min_range, max_range)


@register("quantized_concat", num_inputs=-1, num_outputs=-1,
          differentiable=False, aliases=("_contrib_quantized_concat",))
def quantized_concat(arrays, num_args=0, dim=1):
    """Concat int8 inputs (reference quantized_concat.cc): inputs are
    rescaled to the widest input range so one output scale is exact.
    arrays = [q0..qn-1, min0, max0, min1, max1, ...]."""
    n = num_args or len(arrays) // 3
    qs = arrays[:n]
    mins = arrays[n::2][:n]
    maxs = arrays[n + 1::2][:n]
    amaxs = [jnp.maximum(jnp.abs(lo), jnp.abs(hi))
             for lo, hi in zip(mins, maxs)]
    out_amax = amaxs[0]
    for a in amaxs[1:]:
        out_amax = jnp.maximum(out_amax, a)
    scaled = [
        jnp.clip(jnp.round(q.astype(jnp.float32) * (a / out_amax)),
                 INT8_MIN, INT8_MAX).astype(jnp.int8)
        for q, a in zip(qs, amaxs)]
    return (jnp.concatenate(scaled, axis=dim), -out_amax, out_amax)


@register("quantized_elemwise_add", num_inputs=6, num_outputs=-1,
          differentiable=False, aliases=("_contrib_quantized_elemwise_add",))
def quantized_elemwise_add(qa, qb, a_min, a_max, b_min, b_max):
    """int8 + int8 -> int32 accumulator with fp32 scales folded (reference
    quantized_elemwise_add.cc); output re-quantized to the sum range."""
    sa = jnp.maximum(jnp.maximum(jnp.abs(a_min), jnp.abs(a_max)),
                     1e-12) / INT8_MAX
    sb = jnp.maximum(jnp.maximum(jnp.abs(b_min), jnp.abs(b_max)),
                     1e-12) / INT8_MAX
    f = qa.astype(jnp.float32) * sa + qb.astype(jnp.float32) * sb
    out_amax = jnp.maximum(jnp.abs(a_min) + jnp.abs(b_min),
                           jnp.abs(a_max) + jnp.abs(b_max))
    q = jnp.clip(jnp.round(f * (INT8_MAX / jnp.maximum(out_amax, 1e-12))),
                 INT8_MIN, INT8_MAX).astype(jnp.int8)
    return (q, -out_amax, out_amax)


@register("quantized_elemwise_mul", num_inputs=6, num_outputs=-1,
          differentiable=False, aliases=("_contrib_quantized_elemwise_mul",))
def quantized_elemwise_mul(qa, qb, a_min, a_max, b_min, b_max):
    """int8 * int8 -> int32 (exact); scales multiply (reference
    quantized_elemwise_mul.cc)."""
    acc = qa.astype(jnp.int32) * qb.astype(jnp.int32)
    sa = jnp.maximum(jnp.maximum(jnp.abs(a_min), jnp.abs(a_max)),
                     1e-12)
    sb = jnp.maximum(jnp.maximum(jnp.abs(b_min), jnp.abs(b_max)),
                     1e-12)
    out_amax = sa * sb
    return (acc, -out_amax, out_amax)


@register("quantized_batch_norm", num_inputs=7, num_outputs=-1,
          differentiable=False, aliases=("_contrib_quantized_batch_norm",))
def quantized_batch_norm(qdata, gamma, beta, moving_mean, moving_var,
                         min_range, max_range, eps=1e-3,
                         min_calib_range=None, max_calib_range=None):
    """Inference BN over int8 (reference quantized_batch_norm.cc): folds
    (gamma, beta, mean, var) into a per-channel affine applied in fp32,
    then re-quantizes to the calibrated output range."""
    in_scale = jnp.maximum(jnp.maximum(jnp.abs(min_range),
                                       jnp.abs(max_range)), 1e-12) / INT8_MAX
    inv = gamma / jnp.sqrt(moving_var + eps)
    shape = (1, -1) + (1,) * (qdata.ndim - 2)
    f = (qdata.astype(jnp.float32) * in_scale - moving_mean.reshape(shape)) \
        * inv.reshape(shape) + beta.reshape(shape)
    lo = float(min_calib_range if min_calib_range is not None else -1.0)
    hi = float(max_calib_range if max_calib_range is not None else 1.0)
    out_scale = INT8_MAX / max(abs(lo), abs(hi), 1e-12)
    q = jnp.clip(jnp.round(f * out_scale), INT8_MIN, INT8_MAX).astype(
        jnp.int8)
    return (q, jnp.float32(lo), jnp.float32(hi))


@register("quantized_embedding", num_inputs=4, num_outputs=-1,
          differentiable=False, aliases=("_contrib_quantized_embedding",))
def quantized_embedding(indices, qweight, min_range, max_range,
                        input_dim=0, output_dim=0):
    """Gather int8 rows (reference quantized_indexing_op.cc); the scale is
    unchanged by a gather."""
    out = jnp.take(qweight, indices.astype(jnp.int32), axis=0)
    return (out, min_range, max_range)


@register("calibrate_entropy", num_inputs=1, num_outputs=-1,
          differentiable=False, aliases=("_contrib_calibrate_entropy",))
def calibrate_entropy(hist_and_edges, num_quantized_bins=255):
    """KL-divergence threshold selection over a histogram (reference
    calibrate.cc): picks the clip threshold whose quantized distribution
    minimizes KL against the clipped reference distribution.  Host-side
    (calibration is offline); input = histogram counts, attr-free edges
    assumed symmetric uniform."""
    import numpy as _onp

    hist = _onp.asarray(hist_and_edges, dtype=_onp.float64)
    nbins = hist.size
    best_kl, best_t = _onp.inf, nbins
    for t in range(num_quantized_bins, nbins + 1, 2):
        p = hist[:t].copy()
        p[t - 1] += hist[t:].sum()          # clip mass into the last bin
        p_sum = p.sum()
        if p_sum == 0:
            continue
        # quantize t bins down to num_quantized_bins, then expand back
        factor = t / num_quantized_bins
        q = _onp.zeros(t)
        for j in range(num_quantized_bins):
            lo = int(_onp.floor(j * factor))
            hi = int(_onp.ceil((j + 1) * factor))
            mass = hist[lo:hi].sum()
            nz = (hist[lo:hi] > 0).sum()
            if nz:
                q[lo:hi] = _onp.where(hist[lo:hi] > 0, mass / nz, 0)
        q_sum = q.sum()
        if q_sum == 0:
            continue
        pn, qn = p / p_sum, q / q_sum
        mask = (pn > 0) & (qn > 0)
        kl = float(_onp.sum(pn[mask] * _onp.log(pn[mask] / qn[mask])))
        if kl < best_kl:
            best_kl, best_t = kl, t
    return (jnp.asarray(best_t, jnp.int32), jnp.asarray(best_kl))


# ---------------------------------------------------------------------------
# intgemm family (reference src/operator/contrib/intgemm/*.cc): CPU int8
# GEMM pre/post-processing ops.  On TPU the MXU consumes plain int8 tiles,
# so prepare_* are layout no-ops with the same contracts.
# ---------------------------------------------------------------------------

@register("intgemm_maxabsolute", num_inputs=1, differentiable=False,
          aliases=("_contrib_intgemm_maxabsolute",))
def intgemm_maxabsolute(data):
    return jnp.max(jnp.abs(data))


@register("intgemm_prepare_data", num_inputs=2, differentiable=False,
          aliases=("_contrib_intgemm_prepare_data",))
def intgemm_prepare_data(data, maxabs):
    """fp32 -> int8 with scale 127/maxabs (reference
    intgemm/prepare_data_op.cc)."""
    scale = INT8_MAX / jnp.maximum(maxabs, 1e-12)
    return jnp.clip(jnp.round(data * scale), INT8_MIN, INT8_MAX).astype(
        jnp.int8)


@register("intgemm_prepare_weight", num_inputs=-1, differentiable=False,
          aliases=("_contrib_intgemm_prepare_weight",))
def intgemm_prepare_weight(arrays, already_quantized=False):
    """Weight pre-pass (reference intgemm/prepare_weight_op.cc).  The
    reference permutes into a CPU-register tiled layout; the MXU needs no
    relayout, so this quantizes (if needed) and keeps row-major."""
    if already_quantized or len(arrays) == 1:
        return arrays[0].astype(jnp.int8)
    data, maxabs = arrays
    scale = INT8_MAX / jnp.maximum(maxabs, 1e-12)
    return jnp.clip(jnp.round(data * scale), INT8_MIN, INT8_MAX).astype(
        jnp.int8)


@register("intgemm_take_weight", num_inputs=2, differentiable=False,
          aliases=("_contrib_intgemm_take_weight",))
def intgemm_take_weight(qweight, indices):
    """Gather rows of a prepared weight (reference
    intgemm/take_weight_op.cc — vocabulary shortlisting)."""
    return jnp.take(qweight, indices.astype(jnp.int32), axis=0)


@register("intgemm_fully_connected", num_inputs=-1, differentiable=False,
          aliases=("_contrib_intgemm_fully_connected",))
def intgemm_fully_connected(arrays, num_hidden=0, no_bias=True, flatten=True,
                            out_type="float32"):
    """int8 x int8 -> int32/fp32 GEMM (reference
    intgemm/intgemm_fully_connected_op.cc).  arrays = [data_s8, weight_s8,
    scale (fp32 scalar = product of the two quantization scales), (bias)]."""
    qd, qw = arrays[0], arrays[1]
    if flatten and qd.ndim > 2:
        qd = qd.reshape(qd.shape[0], -1)
    acc = jax.lax.dot_general(
        qd.astype(jnp.int8), qw.astype(jnp.int8),
        (((qd.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    if out_type == "int32":
        return acc
    scale = arrays[2] if len(arrays) > 2 else jnp.float32(1)
    out = acc.astype(jnp.float32) * scale
    if not no_bias and len(arrays) > 3:
        out = out + arrays[3]
    return out
