"""``mx.contrib.text`` (reference ``python/mxnet/contrib/text/``)."""
from . import embedding, vocab
from .vocab import Vocabulary, count_tokens_from_str
