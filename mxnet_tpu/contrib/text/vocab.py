"""Text vocabulary (reference ``python/mxnet/contrib/text/vocab.py``)."""
from __future__ import annotations

import collections
from typing import Counter, Dict, List, Optional

__all__ = ["Vocabulary", "count_tokens_from_str"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Token counter from raw text (reference utils.count_tokens_from_str)."""
    source_str = source_str.lower() if to_lower else source_str
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    for seq in source_str.split(seq_delim):
        counter.update(t for t in seq.split(token_delim) if t)
    return counter


class Vocabulary:
    """Indexed vocabulary with reserved + unknown tokens (reference
    vocab.py Vocabulary)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        assert min_freq > 0
        self._unknown_token = unknown_token
        reserved_tokens = list(reserved_tokens or [])
        assert len(set(reserved_tokens)) == len(reserved_tokens), \
            "reserved tokens must not repeat"
        assert unknown_token not in reserved_tokens
        self._idx_to_token: List[str] = [unknown_token] + reserved_tokens
        self._reserved_tokens = reserved_tokens
        self._token_to_idx: Dict[str, int] = {
            t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            self._index_counter_keys(counter, most_freq_count, min_freq)

    def _index_counter_keys(self, counter, most_freq_count, min_freq):
        pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
        kept = 0
        for token, freq in pairs:
            if freq < min_freq:
                break
            if most_freq_count is not None and kept >= most_freq_count:
                break
            if token not in self._token_to_idx:
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                kept += 1

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """tokens -> indices, unknown maps to index 0 (reference
        to_indices)."""
        single = isinstance(tokens, str)
        if single:
            tokens = [tokens]
        out = [self._token_to_idx.get(t, 0) for t in tokens]
        return out[0] if single else out

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        if single:
            indices = [indices]
        for i in indices:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError(f"token index {i} out of range")
        out = [self._idx_to_token[i] for i in indices]
        return out[0] if single else out

    __getitem__ = to_indices

    def __contains__(self, token):
        return token in self._token_to_idx
