"""Pretrained token embeddings (reference
``python/mxnet/contrib/text/embedding.py``).

File-based only (no network egress): ``CustomEmbedding`` loads any
``token<elem_delim>v1 ... vN`` text file; the GloVe/FastText classes accept
a ``pretrained_file_path`` pointing at an already-downloaded archive
member."""
from __future__ import annotations

import io
import logging
import os
from typing import Callable, Dict, List, Optional

import numpy as onp

from ...ndarray import NDArray, array

__all__ = ["register", "create", "get_pretrained_file_names",
           "TokenEmbedding", "CustomEmbedding", "GloVe", "FastText"]

_REGISTRY: Dict[str, type] = {}


def register(cls):
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(embedding_name, **kwargs):
    cls = _REGISTRY.get(embedding_name.lower())
    if cls is None:
        raise KeyError(f"unknown embedding {embedding_name}; "
                       f"have {sorted(_REGISTRY)}")
    return cls(**kwargs)


def get_pretrained_file_names(embedding_name=None):
    if embedding_name is not None:
        cls = _REGISTRY.get(embedding_name.lower())
        return list(getattr(cls, "pretrained_file_names", []))
    return {n: list(getattr(c, "pretrained_file_names", []))
            for n, c in _REGISTRY.items()}


class TokenEmbedding:
    """Base: token -> vector with unknown fallback (reference
    embedding.py _TokenEmbedding)."""

    def __init__(self, unknown_token="<unk>",
                 init_unknown_vec=onp.zeros):
        self._unknown_token = unknown_token
        self._init_unknown_vec = init_unknown_vec
        self._idx_to_token: List[str] = [unknown_token]
        self._token_to_idx: Dict[str, int] = {unknown_token: 0}
        self._idx_to_vec: Optional[onp.ndarray] = None

    def _load_embedding_txt(self, path, elem_delim=" ", encoding="utf8"):
        vecs = []
        vec_len = None
        with io.open(path, "r", encoding=encoding) as f:
            for line_num, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if len(parts) <= 2:
                    continue  # header line of fasttext-format files
                token, elems = parts[0], parts[1:]
                if vec_len is None:
                    vec_len = len(elems)
                elif len(elems) != vec_len:
                    logging.warning("line %d: bad vector length, skipped",
                                    line_num)
                    continue
                if token in self._token_to_idx:
                    continue
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                vecs.append(onp.asarray(elems, onp.float32))
        assert vec_len is not None, f"no vectors found in {path}"
        unk = self._init_unknown_vec(vec_len).astype(onp.float32)
        self._idx_to_vec = onp.vstack([unk] + vecs)

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def vec_len(self):
        return 0 if self._idx_to_vec is None else self._idx_to_vec.shape[1]

    @property
    def idx_to_vec(self) -> NDArray:
        return array(self._idx_to_vec)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False) -> NDArray:
        single = isinstance(tokens, str)
        if single:
            tokens = [tokens]
        idxs = []
        for t in tokens:
            if t in self._token_to_idx:
                idxs.append(self._token_to_idx[t])
            elif lower_case_backup and t.lower() in self._token_to_idx:
                idxs.append(self._token_to_idx[t.lower()])
            else:
                idxs.append(0)
        vecs = self._idx_to_vec[idxs]
        return array(vecs[0] if single else vecs)

    def update_token_vectors(self, tokens, new_vectors):
        if isinstance(tokens, str):
            tokens = [tokens]
        nv = new_vectors.asnumpy() if isinstance(new_vectors, NDArray) \
            else onp.asarray(new_vectors)
        if nv.ndim == 1:
            nv = nv[None, :]
        for t, v in zip(tokens, nv):
            if t not in self._token_to_idx:
                raise ValueError(f"token {t!r} is unknown")
            self._idx_to_vec[self._token_to_idx[t]] = v


@register
class CustomEmbedding(TokenEmbedding):
    """Load a user text file of embeddings (reference CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ", encoding="utf8",
                 **kwargs):
        super().__init__(**kwargs)
        self._load_embedding_txt(pretrained_file_path, elem_delim, encoding)


@register
class GloVe(TokenEmbedding):
    """GloVe vectors from a local file (reference GloVe; downloads disabled
    in this environment)."""

    pretrained_file_names = [
        "glove.42B.300d.txt", "glove.6B.50d.txt", "glove.6B.100d.txt",
        "glove.6B.200d.txt", "glove.6B.300d.txt", "glove.840B.300d.txt",
        "glove.twitter.27B.25d.txt", "glove.twitter.27B.50d.txt",
        "glove.twitter.27B.100d.txt", "glove.twitter.27B.200d.txt",
    ]

    def __init__(self, pretrained_file_name="glove.6B.50d.txt",
                 embedding_root=os.path.join("~", ".mxnet", "embedding"),
                 pretrained_file_path=None, **kwargs):
        super().__init__(**kwargs)
        path = pretrained_file_path or os.path.join(
            os.path.expanduser(embedding_root), "glove",
            pretrained_file_name)
        if not os.path.exists(path):
            raise IOError(
                f"{path} not found; downloads are disabled — place the "
                "file there or pass pretrained_file_path")
        self._load_embedding_txt(path)


@register
class FastText(TokenEmbedding):
    pretrained_file_names = [
        "wiki.en.vec", "wiki.simple.vec", "crawl-300d-2M.vec",
    ]

    def __init__(self, pretrained_file_name="wiki.simple.vec",
                 embedding_root=os.path.join("~", ".mxnet", "embedding"),
                 pretrained_file_path=None, **kwargs):
        super().__init__(**kwargs)
        path = pretrained_file_path or os.path.join(
            os.path.expanduser(embedding_root), "fasttext",
            pretrained_file_name)
        if not os.path.exists(path):
            raise IOError(
                f"{path} not found; downloads are disabled — place the "
                "file there or pass pretrained_file_path")
        self._load_embedding_txt(path)


class CompositeEmbedding(TokenEmbedding):
    """Concatenate several embeddings over one vocabulary (reference
    CompositeEmbedding)."""

    def __init__(self, vocabulary, token_embeddings):
        super().__init__(unknown_token=vocabulary.unknown_token)
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        parts = [emb.get_vecs_by_tokens(self._idx_to_token).asnumpy()
                 for emb in token_embeddings]  # one vectorized lookup each
        self._idx_to_vec = onp.concatenate(parts, axis=1)


__all__.append("CompositeEmbedding")
