"""TensorBoard logging callback (reference
``python/mxnet/contrib/tensorboard.py``).

The reference logs metrics through the external ``mxboard`` package; this
backend uses it when installed and otherwise degrades to standard logging
(the environment bakes no TensorBoard writer, and inventing an event-file
format here would drift from what ``tensorboard --logdir`` expects).
"""
from __future__ import annotations

import logging

__all__ = ["LogMetricsCallback"]


class LogMetricsCallback:
    """Batch/eval-end callback writing ``eval_metric`` values to
    TensorBoard (reference tensorboard.py:56 LogMetricsCallback).

    Parameters
    ----------
    logging_dir : str
        Event-file directory for ``tensorboard --logdir``.
    prefix : str, optional
        Prepended to every metric name (e.g. ``train``/``eval`` so both
        curves share a plot).
    """

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.summary_writer = None
        try:
            from mxboard import SummaryWriter

            self.summary_writer = SummaryWriter(logging_dir)
        except ImportError:
            logging.error(
                "mxboard is not installed (`pip install mxboard`); "
                "LogMetricsCallback will log metrics via logging.info "
                "instead of TensorBoard events")

    def __call__(self, param):
        """``param`` is a BatchEndParam-style object with ``eval_metric``
        and ``epoch`` attributes (see mxnet_tpu.callback)."""
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = f"{self.prefix}-{name}"
            if self.summary_writer is not None:
                self.summary_writer.add_scalar(name, value,
                                               global_step=param.epoch)
            else:
                logging.info("tensorboard[%s] epoch=%s %s=%s",
                             self.prefix or "", param.epoch, name, value)
