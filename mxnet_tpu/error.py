"""Typed error classes (reference ``python/mxnet/error.py``): a name ->
exception-class registry used to rehydrate errors crossing the
C/serialization boundary, plus :class:`InternalError`.
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["MXNetError", "InternalError", "register_error", "register",
           "ERROR_TYPE"]

ERROR_TYPE = {}


def register_error(name_or_cls=None, cls=None):
    """Register an error class under its name (reference error.py
    register_error) — decorator and call forms both work."""
    if isinstance(name_or_cls, str):
        if cls is not None:
            ERROR_TYPE[name_or_cls] = cls
            return cls

        def deco(c):
            ERROR_TYPE[name_or_cls] = c
            return c

        return deco
    c = name_or_cls
    ERROR_TYPE[c.__name__] = c
    return c


register = register_error


@register_error
class InternalError(MXNetError):
    """Framework-internal invariant violation (reference error.py:31)."""


for _name, _cls in [("ValueError", ValueError), ("TypeError", TypeError),
                    ("AttributeError", AttributeError),
                    ("IndexError", IndexError),
                    ("NotImplementedError", NotImplementedError),
                    ("IOError", IOError),
                    ("FloatingPointError", FloatingPointError),
                    ("RuntimeError", RuntimeError),
                    ("KeyError", KeyError),
                    ("MXNetError", MXNetError)]:
    register_error(_name, _cls)
