"""Unified telemetry: one counter registry, one event bus, one span layer.

The reference frame ships observability as a first-class subsystem
(``src/profiler/`` lock-free stat queues, engine exec stats, KVStore
server counters).  Our reproduction instead accreted ~57 ad-hoc counter
references across 10+ modules — ``cached_step.trace_count``,
``spmd.reshard_count``, ``metric.host_sync_count``,
``flash_fallback_count``, ``quantization.pallas_skipped_count()`` — plus
three disjoint stats surfaces (``program_store.stats()``,
``GenerativeEngine.stats()``, ``faults.events()``) and a chrome-trace
profiler the production paths never fed.  Every measured win so far
started from a counter somebody remembered to check; this module makes
those measurements ONE queryable, exportable system:

- **Counter registry** — every counter is *declared*
  (:func:`counter` with namespace-dotted name, docstring, and kind
  ``cumulative`` / ``gauge`` / ``time``) and every legacy accessor
  (``cached_step.deferred_read_count()``, ``spmd.reshard_count()``, …)
  is now a view over it.  :func:`snapshot` / :func:`delta` are cheap,
  thread-safe, and deterministically ordered (sorted by name), so two
  identical steady-state runs produce byte-identical deltas —
  ``tools/check_telemetry.py`` enforces exactly that, plus "no counter
  ships unregistered or untested".

- **Event bus** — a bounded structured log (:func:`event` /
  :func:`events`) of runtime *happenings*: retrace, fallback, shed,
  preempt, cache evict, AMP overflow, and every fault-site action
  (``faults.record_event`` mirrors here), each stamped with the current
  train-step index and a monotonic timestamp.  Capacity:
  ``MXNET_TELEMETRY_EVENTS``.

- **Spans** — duration records (:func:`span` context manager /
  :func:`record_span` post-hoc) unifying ``profiler.StepTimeline``
  phases, the compiled train step, serving request admit→dispatch→retire
  lifecycles, and decode iterations into one chrome-trace timeline:
  completed spans land in the profiler's trace buffer (the existing
  ``profiler.dump`` pipe) and, under ``MXNET_TELEMETRY_XLA=1``, inside
  ``jax.profiler`` device traces via trace annotations.

- **Exporters** — :func:`flush` appends events + a counter snapshot as
  JSON-lines to ``MXNET_TELEMETRY_DIR`` (the flight recorder;
  ``engine.waitall()`` flushes), :func:`report` renders the one-call
  counter table, and bench.py stamps :func:`delta` per lane.

See docs/OBSERVABILITY.md for the namespace map, event taxonomy, span
hierarchy, and how to add a counter.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from collections.abc import Mapping
from typing import Any, Callable, Dict, Iterator, List, Optional

from . import config as _config

__all__ = [
    "Counter", "CounterGroup", "counter", "gauge", "gauge_fn", "get",
    "registered", "snapshot", "delta", "reset", "instance_name",
    "event", "events", "set_step", "current_step", "next_step",
    "span", "record_span", "spans", "report", "flush",
    "flight_recorder_path", "KINDS",
]

# one lock guards registry structure AND every counter value: increments
# are atomic, and a snapshot taken under it can never observe a torn
# multi-counter update in progress (tools/check_telemetry.py's
# thread-safety contract)
_LOCK = threading.RLock()

KINDS = ("cumulative", "gauge", "time")


class Counter:
    """One declared counter.  ``cumulative`` counters move by
    :meth:`inc` and are monotonic between resets; ``gauge`` / ``time``
    counters take :meth:`set` (and are excluded from the deterministic
    steady-state comparison the CI gate runs)."""

    __slots__ = ("name", "doc", "kind", "family", "_value")

    def __init__(self, name: str, doc: str = "", kind: str = "cumulative",
                 family: Optional[str] = None):
        if kind not in KINDS:
            raise ValueError(f"counter kind {kind!r} not in {KINDS}")
        self.name = name
        self.doc = doc
        self.kind = kind
        self.family = family
        self._value = 0.0 if kind == "time" else 0

    def inc(self, n: int = 1) -> None:
        with _LOCK:
            self._value += n

    add = inc

    def set(self, v) -> None:
        with _LOCK:
            self._value = v

    @property
    def value(self):
        with _LOCK:
            return self._value

    def reset(self) -> None:
        self.set(0.0 if self.kind == "time" else 0)

    def __int__(self) -> int:
        return int(self.value)

    def __repr__(self) -> str:
        return (f"Counter({self.name!r}, kind={self.kind!r}, "
                f"value={self.value!r})")


_COUNTERS: Dict[str, Counter] = {}
_GAUGE_FNS: Dict[str, Callable[[], Any]] = {}
_GAUGE_DOCS: Dict[str, str] = {}
_SEQ: Dict[str, int] = {}


def counter(name: str, doc: str = "", kind: str = "cumulative",
            family: Optional[str] = None) -> Counter:
    """Declare (idempotently) and return the registry counter ``name``.

    Names are namespace-dotted (``cached_step.deferred_read``,
    ``program_store.train_step.traces``); dynamic per-instance counters
    (fault sites, serving engines) pass ``family`` — the stable name the
    CI gate's test-coverage check keys on."""
    with _LOCK:
        c = _COUNTERS.get(name)
        if c is None:
            c = _COUNTERS[name] = Counter(name, doc, kind, family)
        return c


def gauge(name: str, doc: str = "",
          family: Optional[str] = None) -> Counter:
    """Declare a ``gauge``-kind counter (absolute value, :meth:`set`)."""
    return counter(name, doc, kind="gauge", family=family)


def gauge_fn(name: str, fn: Callable[[], Any], doc: str = "") -> None:
    """Register a *computed* gauge: ``snapshot()`` calls ``fn()`` for its
    value (e.g. ``engine.drainables`` = live drainable registrations)."""
    with _LOCK:
        _GAUGE_FNS[name] = fn
        _GAUGE_DOCS[name] = doc


def get(name: str) -> Counter:
    with _LOCK:
        try:
            return _COUNTERS[name]
        except KeyError:
            raise KeyError(
                f"undeclared telemetry counter {name!r}; declare it with "
                "telemetry.counter(name, doc, kind)") from None


def registered() -> Dict[str, Dict[str, Any]]:
    """Metadata of every declared counter (incl. computed gauges)."""
    with _LOCK:
        out = {n: {"kind": c.kind, "doc": c.doc, "family": c.family}
               for n, c in _COUNTERS.items()}
        for n in _GAUGE_FNS:
            out.setdefault(n, {"kind": "gauge", "doc": _GAUGE_DOCS[n],
                               "family": None})
    return out


def instance_name(prefix: str) -> str:
    """Deterministic per-process instance prefix (``serving.engine0``,
    ``serving.engine1``, …) for counter groups owned by object
    instances."""
    with _LOCK:
        n = _SEQ.get(prefix, 0)
        _SEQ[prefix] = n + 1
    return f"{prefix}{n}"


def snapshot() -> Dict[str, Any]:
    """All counter values, deterministically ordered (sorted by name).
    Cheap: one lock hold + one dict copy; computed gauges evaluate
    outside the lock (they must not re-enter the registry)."""
    with _LOCK:
        vals = {n: c._value for n, c in _COUNTERS.items()}
        fns = list(_GAUGE_FNS.items())
    for n, fn in fns:
        if n not in vals:
            try:
                vals[n] = fn()
            except Exception:
                vals[n] = None
    return dict(sorted(vals.items()))


def delta(base: Mapping, current: Optional[Mapping] = None
          ) -> Dict[str, Any]:
    """Counter movement since ``base`` (a prior :func:`snapshot`):
    cumulative/time counters subtract, gauges report their current
    value.  Counters born after ``base`` delta from 0.  Ordering is
    deterministic (sorted)."""
    cur = snapshot() if current is None else current
    kinds = registered()
    out: Dict[str, Any] = {}
    for name in sorted(cur):
        kind = kinds.get(name, {}).get("kind", "cumulative")
        v = cur[name]
        if kind == "gauge" or v is None:
            out[name] = v
            continue
        b = base.get(name, 0) or 0
        out[name] = v - b
    return out


def reset(prefix: Optional[str] = None) -> None:
    """Zero declared counters (tests/benchmarks) — all of them, or only
    those whose name starts with ``prefix``.  Events and spans are
    untouched (clear those via their own buffers)."""
    with _LOCK:
        for n, c in _COUNTERS.items():
            if prefix is None or n.startswith(prefix):
                c._value = 0.0 if c.kind == "time" else 0


class CounterGroup(Mapping):
    """A fixed-key set of registry counters under one dotted prefix —
    the per-instance ``_stats`` dicts of ``ServingEngine`` /
    ``GenerativeEngine`` / ``PagePool`` and the per-site fault counters,
    kept dict-compatible (``dict(group)`` / ``group["k"]`` / iteration)
    so every existing ``stats()`` caller and test sees plain ints, while
    the values live in the registry and ride :func:`snapshot`.

    ``group.inc(k)`` is the atomic increment; ``group[k] = v`` sets
    (``+=`` works but is get-then-set — use :meth:`inc` on paths that
    race)."""

    __slots__ = ("prefix", "_counters")

    def __init__(self, prefix: str, keys, doc: str = "",
                 kind: str = "cumulative", family: Optional[str] = None):
        self.prefix = prefix
        self._counters = {k: counter(f"{prefix}.{k}", doc, kind, family)
                          for k in keys}

    def __getitem__(self, k):
        return self._counters[k].value

    def __setitem__(self, k, v) -> None:
        self._counters[k].set(v)

    def inc(self, k, n: int = 1) -> None:
        self._counters[k].inc(n)

    def __iter__(self) -> Iterator:
        return iter(self._counters)

    def __len__(self) -> int:
        return len(self._counters)

    def reset(self) -> None:
        for c in self._counters.values():
            c.reset()


# ---------------------------------------------------------------------------
# step index (stamped onto events; advanced by cached_step.TrainStep)
# ---------------------------------------------------------------------------
_STEP: List[Optional[int]] = [None]


def set_step(i: Optional[int]) -> None:
    """Pin the current train-step index (events stamp it)."""
    _STEP[0] = i


def next_step() -> int:
    """Advance and return the process-wide step index (TrainStep calls
    this once per step; serving/decode events inherit whatever step the
    co-resident trainer is on, or None when nothing trains)."""
    with _LOCK:
        _STEP[0] = 0 if _STEP[0] is None else _STEP[0] + 1
        return _STEP[0]


def current_step() -> Optional[int]:
    return _STEP[0]


# ---------------------------------------------------------------------------
# event bus
# ---------------------------------------------------------------------------
# taxonomy (docs/OBSERVABILITY.md): retrace | fallback | shed | preempt |
# cache_evict | amp_overflow | fault | <caller-defined>
_EVENTS: "deque" = deque(
    maxlen=max(1, int(_config.get("MXNET_TELEMETRY_EVENTS"))))
_EVENTS_EMITTED = counter(
    "telemetry.events", "structured events emitted through the bus "
    "(the bounded buffer keeps the newest MXNET_TELEMETRY_EVENTS)")
_EVT_LOCK = threading.Lock()
_FLUSH_SEQ = [0]          # bus sequence already flushed to disk


_RESERVED_EVENT_KEYS = ("kind", "name", "step", "t_us", "seq")


def event(kind: str, name: str, /, step: Any = "auto", **fields) -> None:
    """Append one structured event: ``kind`` from the taxonomy, ``name``
    the subsystem/site, ``step`` the train-step index (default: the
    current one), plus a monotonic microsecond timestamp.  Extra fields
    whose names collide with the bus keys are prefixed ``x_``."""
    ev: Dict[str, Any] = {
        "kind": kind, "name": name,
        "step": current_step() if step == "auto" else step,
        "t_us": time.monotonic_ns() // 1000,
    }
    for k, v in fields.items():
        if v is not None:
            ev["x_" + k if k in _RESERVED_EVENT_KEYS else k] = v
    with _EVT_LOCK:
        _EVENTS_EMITTED.inc()
        ev["seq"] = int(_EVENTS_EMITTED.value)
        _EVENTS.append(ev)


def events(kind: Optional[str] = None,
           name: Optional[str] = None) -> List[Dict[str, Any]]:
    with _EVT_LOCK:
        evs = list(_EVENTS)
    if kind is not None:
        evs = [e for e in evs if e["kind"] == kind]
    if name is not None:
        evs = [e for e in evs if e["name"] == name]
    return evs


def clear_events() -> None:
    """Drop buffered events (tests); the emitted counter is untouched."""
    with _EVT_LOCK:
        _EVENTS.clear()


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------
_SPANS: "deque" = deque(maxlen=2048)
_SPANS_RECORDED = counter(
    "telemetry.spans", "completed spans recorded (train_step / "
    "step_phase / serving / decode / user categories)")


def record_span(name: str, cat: str, t0_ns: int, t1_ns: int,
                step: Any = "auto",
                args: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Record one completed span post-hoc (the lifecycle spans whose
    endpoints were timed elsewhere — serving admit→retire).  Also emits
    into the profiler's chrome-trace buffer when collection is running,
    so every span category lands in the one ``profiler.dump``
    timeline."""
    rec = {
        "name": name, "cat": cat,
        "step": current_step() if step == "auto" else step,
        "t0_us": t0_ns // 1000,
        "dur_us": max((t1_ns - t0_ns) // 1000, 1),
        "thread": threading.get_ident(),
    }
    if args:
        rec["args"] = dict(args)
    _SPANS_RECORDED.inc()
    _SPANS.append(rec)
    from . import profiler as _profiler

    _profiler._emit(name, cat, "X", ts=rec["t0_us"], dur=rec["dur_us"],
                    args=rec.get("args"))
    return rec


def _xla_annotations_on() -> bool:
    return bool(_config.get("MXNET_TELEMETRY_XLA"))


class span:
    """Context-manager span: times the enclosed work, records it (see
    :func:`record_span`), and — with ``MXNET_TELEMETRY_XLA=1`` — wraps
    it in a ``jax.profiler`` trace annotation so the host-side bracket
    shows up inside XLA device profiles."""

    __slots__ = ("name", "cat", "args", "_t0", "_ann")

    def __init__(self, name: str, cat: str = "user",
                 args: Optional[Dict[str, Any]] = None):
        self.name = name
        self.cat = cat
        self.args = dict(args) if args else None
        self._t0 = None
        self._ann = None

    def annotate(self, **kw) -> "span":
        """Attach/extend span args mid-flight (recorded at exit)."""
        if self.args is None:
            self.args = {}
        self.args.update(kw)
        return self

    def __enter__(self) -> "span":
        self._t0 = time.perf_counter_ns()
        if _xla_annotations_on():
            try:
                import jax

                self._ann = jax.profiler.TraceAnnotation(
                    f"{self.cat}:{self.name}")
                self._ann.__enter__()
            except Exception:
                self._ann = None
        return self

    def __exit__(self, *exc) -> None:
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            finally:
                self._ann = None
        if self._t0 is not None:
            record_span(self.name, self.cat, self._t0,
                        time.perf_counter_ns(), args=self.args)
            self._t0 = None


def spans(cat: Optional[str] = None,
          limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """Recent completed span records, oldest first (bounded buffer)."""
    out = list(_SPANS)
    if cat is not None:
        out = [s for s in out if s["cat"] == cat]
    if limit is not None:
        out = out[-int(limit):]
    return out


def clear_spans() -> None:
    _SPANS.clear()


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def flight_recorder_path() -> Optional[str]:
    """Where :func:`flush` writes (``MXNET_TELEMETRY_DIR`` set), else
    None (recorder off)."""
    d = _config.get("MXNET_TELEMETRY_DIR")
    if not d:
        return None
    return os.path.join(os.path.expanduser(d),
                        f"telemetry-{os.getpid()}.jsonl")


_FLUSH_LOCK = threading.Lock()


def flush(snapshot_too: bool = True) -> Optional[str]:
    """Flight recorder: append every event not yet flushed (and,
    default, one ``{"kind": "snapshot"}`` record of all counters) as
    JSON-lines under ``MXNET_TELEMETRY_DIR``.  No-op returning None when
    the knob is unset.  ``engine.waitall()`` calls this, so a drained
    process always has its telemetry on disk."""
    path = flight_recorder_path()
    if path is None:
        return None
    with _FLUSH_LOCK:
        with _EVT_LOCK:
            pending = [e for e in _EVENTS if e["seq"] > _FLUSH_SEQ[0]]
            if pending:
                _FLUSH_SEQ[0] = pending[-1]["seq"]
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a") as f:
            for e in pending:
                f.write(json.dumps(e) + "\n")
            if snapshot_too:
                f.write(json.dumps({
                    "kind": "snapshot", "step": current_step(),
                    "t_us": time.monotonic_ns() // 1000,
                    "counters": snapshot()}) + "\n")
    return path


def report(prefix: Optional[str] = None, nonzero_only: bool = True) -> str:
    """One-call counter table (name, kind, value), grouped by top-level
    namespace — the human end of the registry."""
    snap = snapshot()
    kinds = registered()
    lines = [f"{'Counter':<52}{'Kind':>12}{'Value':>16}", "=" * 80]
    last_ns = None
    for name, val in snap.items():
        if prefix is not None and not name.startswith(prefix):
            continue
        if nonzero_only and not val:
            continue
        ns = name.split(".", 1)[0]
        if ns != last_ns:
            if last_ns is not None:
                lines.append("-" * 80)
            last_ns = ns
        kind = kinds.get(name, {}).get("kind", "?")
        if isinstance(val, float):
            lines.append(f"{name:<52}{kind:>12}{val:>16.3f}")
        else:
            lines.append(f"{name:<52}{kind:>12}{val!s:>16}")
    lines.append("=" * 80)
    lines.append(f"{len(snap)} declared counters; "
                 f"{len(events())} buffered events; "
                 f"{len(spans())} buffered spans")
    return "\n".join(lines)
