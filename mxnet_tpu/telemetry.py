"""Unified telemetry: one counter registry, one event bus, one span layer.

The reference frame ships observability as a first-class subsystem
(``src/profiler/`` lock-free stat queues, engine exec stats, KVStore
server counters).  Our reproduction instead accreted ~57 ad-hoc counter
references across 10+ modules — ``cached_step.trace_count``,
``spmd.reshard_count``, ``metric.host_sync_count``,
``flash_fallback_count``, ``quantization.pallas_skipped_count()`` — plus
three disjoint stats surfaces (``program_store.stats()``,
``GenerativeEngine.stats()``, ``faults.events()``) and a chrome-trace
profiler the production paths never fed.  Every measured win so far
started from a counter somebody remembered to check; this module makes
those measurements ONE queryable, exportable system:

- **Counter registry** — every counter is *declared*
  (:func:`counter` with namespace-dotted name, docstring, and kind
  ``cumulative`` / ``gauge`` / ``time``) and every legacy accessor
  (``cached_step.deferred_read_count()``, ``spmd.reshard_count()``, …)
  is now a view over it.  :func:`snapshot` / :func:`delta` are cheap,
  thread-safe, and deterministically ordered (sorted by name), so two
  identical steady-state runs produce byte-identical deltas —
  ``tools/check_telemetry.py`` enforces exactly that, plus "no counter
  ships unregistered or untested".

- **Event bus** — a bounded structured log (:func:`event` /
  :func:`events`) of runtime *happenings*: retrace, fallback, shed,
  preempt, cache evict, AMP overflow, and every fault-site action
  (``faults.record_event`` mirrors here), each stamped with the current
  train-step index and a monotonic timestamp.  Capacity:
  ``MXNET_TELEMETRY_EVENTS``.

- **Spans** — duration records (:func:`span` context manager /
  :func:`record_span` post-hoc) unifying ``profiler.StepTimeline``
  phases, the compiled train step, serving request admit→dispatch→retire
  lifecycles, and decode iterations into one chrome-trace timeline:
  completed spans land in the profiler's trace buffer (the existing
  ``profiler.dump`` pipe) and, under ``MXNET_TELEMETRY_XLA=1``, inside
  ``jax.profiler`` device traces via trace annotations.

- **Trace context** (ISSUE 15) — every serving request mints a
  ``trace_id`` at its admission edge (:class:`trace_scope`;
  ``MXNET_TELEMETRY_TRACE``, default on) carried in a thread-local
  stack that the replica router's dispatch/hedge threads and the decode
  scheduler re-enter, so the ``shed`` / ``failover`` / ``hedge`` /
  ``breaker`` / ``fault`` events and the ``serving`` / ``decode`` spans
  of ONE request all stamp the same id (+ parent span id).
  :func:`trace` returns the stitched lifecycle (admission → each
  dispatch attempt → prefill/decode iterations → retire/shed), and the
  chrome-trace export links the spans of one request into one flow.
  Disabled (``MXNET_TELEMETRY_TRACE=0``): no ids are minted, no trace
  fields appear anywhere, and the hot paths pay one thread-local read.

- **Exporters** — :func:`flush` writes this process's events, spans,
  and a counter snapshot as ONE atomic JSON-lines shard
  (``telemetry-r<rank>-p<pid>.jsonl``, write-then-rename so a SIGKILL
  never leaves a torn shard) under ``MXNET_TELEMETRY_DIR`` (the flight
  recorder; ``engine.waitall()`` and the preemption drain flush; the
  directory is bounded by ``MXNET_TELEMETRY_MAX_MB`` with oldest-shard
  rotation).  :func:`merge` folds a directory of per-process shards
  into one fleet snapshot (cumulative counters summed, gauges kept
  per-process) and :func:`merge_chrome_trace` into one chrome trace
  with per-process lanes.  :func:`report` renders the one-call counter
  table, bench.py stamps :func:`delta` per lane, and
  ``python -m mxnet_tpu.telemetry`` is the on-box CLI
  (``report`` / ``trace <id>`` / ``merge <dir>``).

See docs/OBSERVABILITY.md for the namespace map, event taxonomy, span
hierarchy, trace-field schema, and how to add a counter.
"""
from __future__ import annotations

import json
import os
import threading
import time
import zlib
from collections import deque
from collections.abc import Mapping
from typing import Any, Callable, Dict, Iterator, List, Optional

from . import config as _config

__all__ = [
    "Counter", "CounterGroup", "counter", "gauge", "gauge_fn", "get",
    "registered", "snapshot", "delta", "reset", "instance_name",
    "event", "events", "set_step", "current_step", "next_step",
    "span", "record_span", "spans", "report", "flush",
    "flight_recorder_path", "KINDS",
    "tracing_enabled", "new_trace_id", "trace_scope", "current_trace",
    "current_span_id", "trace", "merge", "merge_chrome_trace", "main",
]

# one lock guards registry structure AND every counter value: increments
# are atomic, and a snapshot taken under it can never observe a torn
# multi-counter update in progress (tools/check_telemetry.py's
# thread-safety contract)
_LOCK = threading.RLock()

KINDS = ("cumulative", "gauge", "time")


class Counter:
    """One declared counter.  ``cumulative`` counters move by
    :meth:`inc` and are monotonic between resets; ``gauge`` / ``time``
    counters take :meth:`set` (and are excluded from the deterministic
    steady-state comparison the CI gate runs)."""

    __slots__ = ("name", "doc", "kind", "family", "_value")

    def __init__(self, name: str, doc: str = "", kind: str = "cumulative",
                 family: Optional[str] = None):
        if kind not in KINDS:
            raise ValueError(f"counter kind {kind!r} not in {KINDS}")
        self.name = name
        self.doc = doc
        self.kind = kind
        self.family = family
        self._value = 0.0 if kind == "time" else 0

    def inc(self, n: int = 1) -> None:
        with _LOCK:
            self._value += n

    add = inc

    def set(self, v) -> None:
        with _LOCK:
            self._value = v

    @property
    def value(self):
        with _LOCK:
            return self._value

    def reset(self) -> None:
        self.set(0.0 if self.kind == "time" else 0)

    def __int__(self) -> int:
        return int(self.value)

    def __repr__(self) -> str:
        return (f"Counter({self.name!r}, kind={self.kind!r}, "
                f"value={self.value!r})")


_COUNTERS: Dict[str, Counter] = {}
_GAUGE_FNS: Dict[str, Callable[[], Any]] = {}
_GAUGE_DOCS: Dict[str, str] = {}
_GAUGE_FAMILIES: Dict[str, Optional[str]] = {}
_SEQ: Dict[str, int] = {}


def counter(name: str, doc: str = "", kind: str = "cumulative",
            family: Optional[str] = None) -> Counter:
    """Declare (idempotently) and return the registry counter ``name``.

    Names are namespace-dotted (``cached_step.deferred_read``,
    ``program_store.train_step.traces``); dynamic per-instance counters
    (fault sites, serving engines) pass ``family`` — the stable name the
    CI gate's test-coverage check keys on."""
    with _LOCK:
        c = _COUNTERS.get(name)
        if c is None:
            c = _COUNTERS[name] = Counter(name, doc, kind, family)
        return c


def gauge(name: str, doc: str = "",
          family: Optional[str] = None) -> Counter:
    """Declare a ``gauge``-kind counter (absolute value, :meth:`set`)."""
    return counter(name, doc, kind="gauge", family=family)


def gauge_fn(name: str, fn: Callable[[], Any], doc: str = "",
             family: Optional[str] = None) -> None:
    """Register a *computed* gauge: ``snapshot()`` calls ``fn()`` for its
    value (e.g. ``engine.drainables`` = live drainable registrations).
    Per-instance gauges pass ``family`` — the stable name the CI gate's
    test-coverage check keys on, same as :func:`counter`."""
    with _LOCK:
        _GAUGE_FNS[name] = fn
        _GAUGE_DOCS[name] = doc
        _GAUGE_FAMILIES[name] = family


def register_load_gauges(engine, prefix: str) -> None:
    """Expose an engine's live ``load()`` fields — queue depth,
    in-flight occupancy, KV page-pool pressure — as computed gauges
    under its counter-group prefix (``decode.engine0.queue_depth``
    …), so the replica router's balancer, the fleet autoscaler,
    dashboards, and ``check_perf_delta`` all read the SAME numbers
    (ISSUE 17).  Weakly bound: a closed or collected engine reads 0.0
    at snapshot time instead of pinning the instance alive."""
    import weakref

    ref = weakref.ref(engine)
    # the family is the instance-stripped prefix ('decode.engine0' ->
    # 'decode.engine'), matching the engines' CounterGroup family
    fam = prefix.rstrip("0123456789")

    def _field(key: str):
        def read() -> float:
            eng = ref()
            if eng is None or getattr(eng, "_closed", False):
                return 0.0
            try:
                return float(eng.load().get(key, 0.0))
            except Exception:
                return 0.0
        return read

    for key, doc in (
            ("queue_depth", "Admitted-but-unscheduled requests on this "
             "engine (live load() view; the balancer/autoscaler "
             "input)"),
            ("in_flight", "In-flight occupancy of this engine "
             "(live rows / max rows, or staged batches; load() view)"),
            ("pool_pressure", "KV page-pool pressure of this engine "
             "(1 - free/total pages; 0 for engines without a pool)")):
        gauge_fn(f"{prefix}.{key}", _field(key), doc=doc, family=fam)


def get(name: str) -> Counter:
    with _LOCK:
        try:
            return _COUNTERS[name]
        except KeyError:
            raise KeyError(
                f"undeclared telemetry counter {name!r}; declare it with "
                "telemetry.counter(name, doc, kind)") from None


def registered() -> Dict[str, Dict[str, Any]]:
    """Metadata of every declared counter (incl. computed gauges)."""
    with _LOCK:
        out = {n: {"kind": c.kind, "doc": c.doc, "family": c.family}
               for n, c in _COUNTERS.items()}
        for n in _GAUGE_FNS:
            out.setdefault(n, {"kind": "gauge", "doc": _GAUGE_DOCS[n],
                               "family": _GAUGE_FAMILIES.get(n)})
    return out


def instance_name(prefix: str) -> str:
    """Deterministic per-process instance prefix (``serving.engine0``,
    ``serving.engine1``, …) for counter groups owned by object
    instances."""
    with _LOCK:
        n = _SEQ.get(prefix, 0)
        _SEQ[prefix] = n + 1
    return f"{prefix}{n}"


def snapshot() -> Dict[str, Any]:
    """All counter values, deterministically ordered (sorted by name).
    Cheap: one lock hold + one dict copy; computed gauges evaluate
    outside the lock (they must not re-enter the registry)."""
    with _LOCK:
        vals = {n: c._value for n, c in _COUNTERS.items()}
        fns = list(_GAUGE_FNS.items())
    for n, fn in fns:
        if n not in vals:
            try:
                vals[n] = fn()
            except Exception:
                vals[n] = None
    return dict(sorted(vals.items()))


def delta(base: Mapping, current: Optional[Mapping] = None
          ) -> Dict[str, Any]:
    """Counter movement since ``base`` (a prior :func:`snapshot`):
    cumulative/time counters subtract, gauges report their current
    value.  Counters born after ``base`` delta from 0.  Ordering is
    deterministic (sorted)."""
    cur = snapshot() if current is None else current
    kinds = registered()
    out: Dict[str, Any] = {}
    for name in sorted(cur):
        kind = kinds.get(name, {}).get("kind", "cumulative")
        v = cur[name]
        if kind == "gauge" or v is None:
            out[name] = v
            continue
        b = base.get(name, 0) or 0
        out[name] = v - b
    return out


def reset(prefix: Optional[str] = None) -> None:
    """Zero declared counters (tests/benchmarks) — all of them, or only
    those whose name starts with ``prefix``.  Events and spans are
    untouched (clear those via their own buffers)."""
    with _LOCK:
        for n, c in _COUNTERS.items():
            if prefix is None or n.startswith(prefix):
                c._value = 0.0 if c.kind == "time" else 0


class CounterGroup(Mapping):
    """A fixed-key set of registry counters under one dotted prefix —
    the per-instance ``_stats`` dicts of ``ServingEngine`` /
    ``GenerativeEngine`` / ``PagePool`` and the per-site fault counters,
    kept dict-compatible (``dict(group)`` / ``group["k"]`` / iteration)
    so every existing ``stats()`` caller and test sees plain ints, while
    the values live in the registry and ride :func:`snapshot`.

    ``group.inc(k)`` is the atomic increment; ``group[k] = v`` sets
    (``+=`` works but is get-then-set — use :meth:`inc` on paths that
    race)."""

    __slots__ = ("prefix", "_counters")

    def __init__(self, prefix: str, keys, doc: str = "",
                 kind: str = "cumulative", family: Optional[str] = None):
        self.prefix = prefix
        self._counters = {k: counter(f"{prefix}.{k}", doc, kind, family)
                          for k in keys}

    def __getitem__(self, k):
        return self._counters[k].value

    def __setitem__(self, k, v) -> None:
        self._counters[k].set(v)

    def inc(self, k, n: int = 1) -> None:
        self._counters[k].inc(n)

    def __iter__(self) -> Iterator:
        return iter(self._counters)

    def __len__(self) -> int:
        return len(self._counters)

    def reset(self) -> None:
        for c in self._counters.values():
            c.reset()


# ---------------------------------------------------------------------------
# step index (stamped onto events; advanced by cached_step.TrainStep)
# ---------------------------------------------------------------------------
_STEP: List[Optional[int]] = [None]


def set_step(i: Optional[int]) -> None:
    """Pin the current train-step index (events stamp it)."""
    _STEP[0] = i


def next_step() -> int:
    """Advance and return the process-wide step index (TrainStep calls
    this once per step; serving/decode events inherit whatever step the
    co-resident trainer is on, or None when nothing trains)."""
    with _LOCK:
        _STEP[0] = 0 if _STEP[0] is None else _STEP[0] + 1
        return _STEP[0]


def current_step() -> Optional[int]:
    return _STEP[0]


# ---------------------------------------------------------------------------
# trace context (ISSUE 15: end-to-end request identity)
# ---------------------------------------------------------------------------
# One thread-local stack of (trace_id, span_id) frames.  The OUTERMOST
# frame is minted at a request's admission edge (router.infer/generate,
# bare ServingEngine.infer / GenerativeEngine.generate); worker threads
# re-enter with the explicit id stamped on the request object, so every
# event and span a request touches — on any thread — carries one id.
_TRACE = threading.local()

_TRACES_MINTED = counter(
    "telemetry.traces_minted",
    "request trace ids minted at serving admission edges "
    "(MXNET_TELEMETRY_TRACE; one id = one end-to-end request lifecycle)")


def tracing_enabled() -> bool:
    """Is request-trace minting on?  (``MXNET_TELEMETRY_TRACE``,
    default 1.)  Only admission edges consult this; everything inside a
    request reads the thread-local frame instead — with tracing off no
    frame ever exists, so no trace fields are stamped anywhere."""
    return bool(_config.get("MXNET_TELEMETRY_TRACE"))


def new_trace_id() -> str:
    """Mint a process-unique trace id (``<pid hex>-<seq hex>``)."""
    _TRACES_MINTED.inc()
    return f"{os.getpid():x}-{int(_TRACES_MINTED.value):x}"


def _trace_stack() -> List:
    st = getattr(_TRACE, "stack", None)
    if st is None:
        st = _TRACE.stack = []
    return st


def current_trace() -> Optional[str]:
    """The ambient trace id on this thread, or None (one thread-local
    read — hot-path safe)."""
    st = getattr(_TRACE, "stack", None)
    return st[-1][0] if st else None


def current_span_id() -> Optional[str]:
    """The ambient parent-span id on this thread, or None."""
    st = getattr(_TRACE, "stack", None)
    return st[-1][1] if st else None


def _next_span_id() -> str:
    with _LOCK:
        _SPANS_SEQ[0] += 1
        return f"s{_SPANS_SEQ[0]:x}"


class trace_scope:
    """Establish (or re-enter) the thread's request-trace context.

    - ``trace_scope()`` at an admission edge: inherit the ambient trace
      when one exists (a routed request re-entering an engine), else
      mint a fresh id when :func:`tracing_enabled` — else a no-op.
    - ``trace_scope(trace_id=req.trace_id, parent=req.span_id)`` on a
      worker thread: carry the request's ONE identity across the thread
      hop (the deadline-budget ``until=`` idiom, applied to identity).
      A ``None`` id is a no-op passthrough, so disabled-mode requests
      stay zero-overhead on every thread they touch.

    ``scope.trace_id`` is the active id (None when the scope is a
    passthrough)."""

    __slots__ = ("trace_id", "_parent", "_pushed", "_explicit")

    _UNSET = object()

    def __init__(self, trace_id: Any = _UNSET,
                 parent: Optional[str] = None):
        self._explicit = trace_id is not trace_scope._UNSET
        self.trace_id = (None if trace_id is trace_scope._UNSET
                         else trace_id)
        self._parent = parent
        self._pushed = False

    def __enter__(self) -> "trace_scope":
        tid = self.trace_id
        if tid is None and not self._explicit:
            tid = current_trace()
            if tid is None and tracing_enabled():
                tid = new_trace_id()
        if tid is None:
            return self
        st = _trace_stack()
        parent = self._parent
        if parent is None and st:
            parent = st[-1][1]
        st.append((tid, parent))
        self.trace_id = tid
        self._pushed = True
        return self

    def __exit__(self, *exc) -> None:
        if self._pushed:
            _trace_stack().pop()
            self._pushed = False


def trace(trace_id: str) -> Dict[str, Any]:
    """The stitched lifecycle of one request: every buffered event
    stamped with ``trace_id`` plus every span that carries it (directly,
    or in its ``args.trace_ids`` list — decode iterations batch many
    requests into one dispatch), merged into one time-ordered
    ``records`` list.  Events and spans share the monotonic clock, so
    admission → dispatch attempts → prefill/decode iterations →
    retire/shed come back in lifecycle order."""
    evs = [e for e in events() if e.get("trace_id") == trace_id]
    sps = []
    for s in spans():
        if s.get("trace_id") == trace_id or \
                trace_id in ((s.get("args") or {}).get("trace_ids") or ()):
            sps.append(s)
    records: List[Dict[str, Any]] = []
    for e in evs:
        records.append(dict(e, type="event"))
    for s in sps:
        records.append(dict(s, type="span", t_us=s["t0_us"]))
    records.sort(key=lambda r: (r["t_us"], r.get("seq", 0)))
    return {"trace_id": trace_id, "events": evs, "spans": sps,
            "records": records}


# ---------------------------------------------------------------------------
# event bus
# ---------------------------------------------------------------------------
# taxonomy (docs/OBSERVABILITY.md): retrace | fallback | shed | preempt |
# cache_evict | amp_overflow | fault | <caller-defined>
_EVENTS: "deque" = deque(
    maxlen=max(1, int(_config.get("MXNET_TELEMETRY_EVENTS"))))
_EVENTS_EMITTED = counter(
    "telemetry.events", "structured events emitted through the bus "
    "(the bounded buffer keeps the newest MXNET_TELEMETRY_EVENTS)")
_EVT_LOCK = threading.Lock()
_FLUSH_SEQ = [0]          # bus sequence already flushed to disk


_RESERVED_EVENT_KEYS = ("kind", "name", "step", "t_us", "seq",
                        "trace_id", "parent")


def event(kind: str, name: str, /, step: Any = "auto", **fields) -> None:
    """Append one structured event: ``kind`` from the taxonomy, ``name``
    the subsystem/site, ``step`` the train-step index (default: the
    current one), plus a monotonic microsecond timestamp.  Inside a
    request's :class:`trace_scope` the event additionally stamps
    ``trace_id`` (+ ``parent`` span id) — nothing otherwise.  Extra
    fields whose names collide with the bus keys are prefixed ``x_``."""
    ev: Dict[str, Any] = {
        "kind": kind, "name": name,
        "step": current_step() if step == "auto" else step,
        "t_us": time.monotonic_ns() // 1000,
    }
    tid = current_trace()
    if tid is not None:
        ev["trace_id"] = tid
        sid = current_span_id()
        if sid is not None:
            ev["parent"] = sid
    for k, v in fields.items():
        if v is not None:
            ev["x_" + k if k in _RESERVED_EVENT_KEYS else k] = v
    with _EVT_LOCK:
        _EVENTS_EMITTED.inc()
        ev["seq"] = int(_EVENTS_EMITTED.value)
        _EVENTS.append(ev)


def events(kind: Optional[str] = None,
           name: Optional[str] = None) -> List[Dict[str, Any]]:
    with _EVT_LOCK:
        evs = list(_EVENTS)
    if kind is not None:
        evs = [e for e in evs if e["kind"] == kind]
    if name is not None:
        evs = [e for e in evs if e["name"] == name]
    return evs


def clear_events() -> None:
    """Drop buffered events (tests); the emitted counter is untouched."""
    with _EVT_LOCK:
        _EVENTS.clear()


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------
_SPANS: "deque" = deque(maxlen=2048)
_SPANS_SEQ = [0]          # span ids + flight-recorder flush cursor
_SPANS_RECORDED = counter(
    "telemetry.spans", "completed spans recorded (train_step / "
    "step_phase / serving / decode / user categories)")
# trace ids whose chrome flow already emitted its "s" (start) arrow —
# later spans of the same trace emit "t" (step) so the whole request
# renders as ONE connected flow in chrome://tracing / Perfetto
_FLOW_STARTED: set = set()


def _flow_id(trace_id: str) -> int:
    return zlib.crc32(trace_id.encode()) & 0x7FFFFFFF


def record_span(name: str, cat: str, t0_ns: int, t1_ns: int,
                step: Any = "auto",
                args: Optional[Dict[str, Any]] = None,
                span_id: Optional[str] = None) -> Dict[str, Any]:
    """Record one completed span post-hoc (the lifecycle spans whose
    endpoints were timed elsewhere — serving admit→retire).  Also emits
    into the profiler's chrome-trace buffer when collection is running,
    so every span category lands in the one ``profiler.dump``
    timeline.  Inside a request's :class:`trace_scope` the record
    stamps ``trace_id`` / ``parent`` / its own ``id``, and the chrome
    export additionally links it into the request's flow."""
    rec = {
        "name": name, "cat": cat,
        "step": current_step() if step == "auto" else step,
        "t0_us": t0_ns // 1000,
        "dur_us": max((t1_ns - t0_ns) // 1000, 1),
        "thread": threading.get_ident(),
    }
    tid = current_trace()
    if tid is not None:
        rec["trace_id"] = tid
        rec["id"] = span_id if span_id is not None else _next_span_id()
        parent = current_span_id()
        if parent is not None and parent != rec["id"]:
            rec["parent"] = parent
    with _LOCK:
        _SPANS_SEQ[0] += 1
        rec["seq"] = _SPANS_SEQ[0]
    if args:
        rec["args"] = dict(args)
    _SPANS_RECORDED.inc()
    _SPANS.append(rec)
    from . import profiler as _profiler

    _profiler._emit(name, cat, "X", ts=rec["t0_us"], dur=rec["dur_us"],
                    args=rec.get("args"))
    if tid is not None:
        # one request = one chrome flow: an "s" arrow from the trace's
        # first span, "t" steps through every later one
        first = tid not in _FLOW_STARTED
        if first:
            _FLOW_STARTED.add(tid)
            if len(_FLOW_STARTED) > 8192:
                _FLOW_STARTED.clear()
                _FLOW_STARTED.add(tid)
        _profiler._emit(f"trace:{tid}", "flow", "s" if first else "t",
                        ts=rec["t0_us"], flow_id=_flow_id(tid))
    return rec


def _xla_annotations_on() -> bool:
    return bool(_config.get("MXNET_TELEMETRY_XLA"))


class span:
    """Context-manager span: times the enclosed work, records it (see
    :func:`record_span`), and — with ``MXNET_TELEMETRY_XLA=1`` — wraps
    it in a ``jax.profiler`` trace annotation so the host-side bracket
    shows up inside XLA device profiles.  Inside a request's
    :class:`trace_scope` the span takes an id at entry and becomes the
    ambient PARENT for everything recorded underneath it."""

    __slots__ = ("name", "cat", "args", "_t0", "_ann", "_sid", "_pushed")

    def __init__(self, name: str, cat: str = "user",
                 args: Optional[Dict[str, Any]] = None):
        self.name = name
        self.cat = cat
        self.args = dict(args) if args else None
        self._t0 = None
        self._ann = None
        self._sid = None
        self._pushed = False

    def annotate(self, **kw) -> "span":
        """Attach/extend span args mid-flight (recorded at exit)."""
        if self.args is None:
            self.args = {}
        self.args.update(kw)
        return self

    def __enter__(self) -> "span":
        self._t0 = time.perf_counter_ns()
        st = getattr(_TRACE, "stack", None)
        if st:
            self._sid = _next_span_id()
            st.append((st[-1][0], self._sid))
            self._pushed = True
        if _xla_annotations_on():
            try:
                import jax

                self._ann = jax.profiler.TraceAnnotation(
                    f"{self.cat}:{self.name}")
                self._ann.__enter__()
            except Exception:
                self._ann = None
        return self

    def __exit__(self, *exc) -> None:
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            finally:
                self._ann = None
        if self._pushed:
            _trace_stack().pop()
            self._pushed = False
        if self._t0 is not None:
            record_span(self.name, self.cat, self._t0,
                        time.perf_counter_ns(), args=self.args,
                        span_id=self._sid)
            self._t0 = None


def spans(cat: Optional[str] = None,
          limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """Recent completed span records, oldest first (bounded buffer)."""
    out = list(_SPANS)
    if cat is not None:
        out = [s for s in out if s["cat"] == cat]
    if limit is not None:
        out = out[-int(limit):]
    return out


def clear_spans() -> None:
    _SPANS.clear()
    _FLOW_STARTED.clear()


# ---------------------------------------------------------------------------
# exporters: the flight recorder (per-process shards) + fleet merge
# ---------------------------------------------------------------------------
_SHARDS_ROTATED = counter(
    "telemetry.shards_rotated",
    "flight-recorder shards deleted by the MXNET_TELEMETRY_MAX_MB "
    "oldest-first size-cap rotation (a week-long drill cannot fill "
    "the disk)")


def _flight_dir() -> Optional[str]:
    d = _config.get("MXNET_TELEMETRY_DIR")
    if not d:
        return None
    return os.path.expanduser(d)


def _process_rank() -> int:
    r = _config.get("MXNET_TPU_PROC_ID")
    return int(r) if r is not None else 0


def flight_recorder_path() -> Optional[str]:
    """This process's shard file (``MXNET_TELEMETRY_DIR`` set), else
    None (recorder off).  Shards are pid/rank-stamped —
    ``telemetry-r<rank>-p<pid>.jsonl`` — so every process of a drill or
    a multi-controller job writes its own file and :func:`merge` folds
    them back together."""
    d = _flight_dir()
    if d is None:
        return None
    return os.path.join(
        d, f"telemetry-r{_process_rank()}-p{os.getpid()}.jsonl")


_FLUSH_LOCK = threading.Lock()
_SPAN_FLUSH_SEQ = [0]     # span sequence already flushed to disk


def _shard_line_cap() -> int:
    # bound the per-shard record history like the in-memory bus: the
    # newest 4x the bus capacity of event+span lines survive a rewrite
    return 4 * max(1, int(_config.get("MXNET_TELEMETRY_EVENTS")))


def _rotate_shards(directory: str, keep: str) -> int:
    """Enforce ``MXNET_TELEMETRY_MAX_MB`` over the shard directory:
    delete oldest-mtime shards (never this process's own) until the
    total fits.  Returns shards removed."""
    cap_mb = float(_config.get("MXNET_TELEMETRY_MAX_MB"))
    if cap_mb <= 0:
        return 0
    cap = cap_mb * 1024 * 1024
    shards = []
    try:
        for fn in os.listdir(directory):
            if fn.startswith("telemetry-") and fn.endswith(".jsonl"):
                p = os.path.join(directory, fn)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                shards.append((st.st_mtime, st.st_size, p))
    except OSError:
        return 0
    total = sum(s for _m, s, _p in shards)
    removed = 0
    for _mtime, size, p in sorted(shards):
        if total <= cap:
            break
        if os.path.abspath(p) == os.path.abspath(keep):
            continue
        try:
            os.unlink(p)
        except OSError:
            continue
        total -= size
        removed += 1
    if removed:
        _SHARDS_ROTATED.inc(removed)
    return removed


def flush(snapshot_too: bool = True,
          path: Optional[str] = None) -> Optional[str]:
    """Flight recorder: fold every event and span not yet flushed plus
    (default) one fresh ``{"kind": "snapshot"}`` record of all counters
    into this process's shard under ``MXNET_TELEMETRY_DIR``.  The shard
    is rewritten whole via write-then-rename, so a SIGKILL mid-flush
    can never leave a torn JSON-lines file for :func:`merge` to choke
    on — the previous complete shard survives.  No-op returning None
    when the knob is unset.  ``engine.waitall()`` and the preemption
    drain call this, so a drained process always has its telemetry on
    disk.  ``path`` overrides the shard file (tests)."""
    path = flight_recorder_path() if path is None else path
    if path is None:
        return None
    with _FLUSH_LOCK:
        with _EVT_LOCK:
            pending = [e for e in _EVENTS if e["seq"] > _FLUSH_SEQ[0]]
            if pending:
                _FLUSH_SEQ[0] = pending[-1]["seq"]
        pend_spans = [s for s in list(_SPANS)
                      if s.get("seq", 0) > _SPAN_FLUSH_SEQ[0]]
        if pend_spans:
            _SPAN_FLUSH_SEQ[0] = pend_spans[-1]["seq"]
        # prior data lines survive the rewrite (meta + snapshot are
        # regenerated fresh each flush — only the newest matters)
        old: List[str] = []
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        kind = json.loads(line).get("kind")
                    except ValueError:
                        continue          # torn line from a legacy shard
                    if kind not in ("meta", "snapshot"):
                        old.append(line)
        except OSError:
            pass
        lines = old
        lines.extend(json.dumps(e) for e in pending)
        lines.extend(json.dumps({"kind": "span", **s})
                     for s in pend_spans)
        cap = _shard_line_cap()
        if len(lines) > cap:
            lines = lines[-cap:]
        meta = {"kind": "meta", "pid": os.getpid(),
                "rank": _process_rank(),
                "t_us": time.monotonic_ns() // 1000,
                "counter_kinds": {n: m["kind"]
                                  for n, m in registered().items()}}
        directory = os.path.dirname(path)
        os.makedirs(directory, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(meta) + "\n")
            for line in lines:
                f.write(line + "\n")
            if snapshot_too:
                f.write(json.dumps({
                    "kind": "snapshot", "step": current_step(),
                    "t_us": time.monotonic_ns() // 1000,
                    "counters": snapshot()}) + "\n")
        os.replace(tmp, path)
        _rotate_shards(directory, keep=path)
    return path


# -- fleet merge ------------------------------------------------------------

def _read_shard(path: str) -> Dict[str, Any]:
    sh: Dict[str, Any] = {"path": path, "meta": {}, "snapshot": None,
                          "events": [], "spans": [], "skipped_lines": 0}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                sh["skipped_lines"] += 1      # torn tail — legacy shard
                continue
            kind = rec.get("kind")
            if kind == "meta":
                sh["meta"] = rec
            elif kind == "snapshot":
                sh["snapshot"] = rec          # last one wins
            elif kind == "span":
                sh["spans"].append(rec)
            else:
                sh["events"].append(rec)
    return sh


def merge(directory: str) -> Dict[str, Any]:
    """Fold a directory of per-process flight-recorder shards into ONE
    fleet snapshot: cumulative/time counters SUM across processes,
    gauges stay per-process (summing a queue-depth gauge across ranks
    is a lie), and every event/span comes back stamped with its
    ``pid``/``rank``/``shard``.  Torn or mid-write files (``*.tmp``,
    invalid trailing lines) are skipped, never fatal — a SIGKILLed
    child costs its unflushed tail, not the merge."""
    directory = os.path.expanduser(directory)
    shards: List[Dict[str, Any]] = []
    for fn in sorted(os.listdir(directory)):
        if not (fn.startswith("telemetry-") and fn.endswith(".jsonl")):
            continue
        try:
            shards.append(_read_shard(os.path.join(directory, fn)))
        except OSError:
            continue
    counters: Dict[str, Any] = {}
    gauges: Dict[str, Dict[str, Any]] = {}
    events_all: List[Dict[str, Any]] = []
    spans_all: List[Dict[str, Any]] = []
    processes: List[Dict[str, Any]] = []
    skipped = 0
    for sh in shards:
        name = os.path.basename(sh["path"])
        meta = sh["meta"]
        pid, rank = meta.get("pid"), meta.get("rank", 0)
        kinds = meta.get("counter_kinds", {})
        processes.append({"shard": name, "pid": pid, "rank": rank,
                          "events": len(sh["events"]),
                          "spans": len(sh["spans"]),
                          "skipped_lines": sh["skipped_lines"]})
        skipped += sh["skipped_lines"]
        snap = (sh["snapshot"] or {}).get("counters", {})
        for cname, val in snap.items():
            kind = kinds.get(cname, "cumulative")
            if kind == "gauge" or val is None:
                gauges.setdefault(cname, {})[name] = val
            else:
                counters[cname] = counters.get(cname, 0) + val
        for ev in sh["events"]:
            events_all.append(dict(ev, pid=pid, rank=rank, shard=name))
        for sp in sh["spans"]:
            spans_all.append(dict(sp, pid=pid, rank=rank, shard=name))
    events_all.sort(key=lambda e: e.get("t_us", 0))
    spans_all.sort(key=lambda s: s.get("t0_us", 0))
    return {
        "dir": directory,
        "shards": [os.path.basename(s["path"]) for s in shards],
        "processes": processes,
        "counters": dict(sorted(counters.items())),
        "gauges": dict(sorted(gauges.items())),
        "events": events_all,
        "spans": spans_all,
        "skipped_lines": skipped,
    }


def merge_chrome_trace(directory: str,
                       merged: Optional[Dict[str, Any]] = None
                       ) -> Dict[str, Any]:
    """One chrome trace over every process's shard: each process gets
    its own lane (``pid`` + a ``process_name`` metadata row naming the
    rank), spans land as duration events, and spans sharing a
    ``trace_id`` link into one flow ACROSS processes — a routed request
    that crossed a drill child renders as one connected arrow chain."""
    m = merged if merged is not None else merge(directory)
    events: List[Dict[str, Any]] = []
    for proc in m["processes"]:
        pid = proc["pid"] if proc["pid"] is not None else proc["shard"]
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": f"rank {proc['rank']} "
                                        f"({proc['shard']})"}})
    flow_started: set = set()
    for sp in m["spans"]:
        pid = sp.get("pid") if sp.get("pid") is not None \
            else sp.get("shard")
        ev = {"name": sp["name"], "cat": sp["cat"], "ph": "X",
              "pid": pid, "tid": sp.get("thread", 0),
              "ts": sp["t0_us"], "dur": sp["dur_us"]}
        args = dict(sp.get("args") or {})
        if sp.get("trace_id"):
            args["trace_id"] = sp["trace_id"]
        if args:
            ev["args"] = args
        events.append(ev)
        tid = sp.get("trace_id")
        if tid:
            first = tid not in flow_started
            flow_started.add(tid)
            events.append({"name": f"trace:{tid}", "cat": "flow",
                           "ph": "s" if first else "t", "pid": pid,
                           "tid": sp.get("thread", 0), "ts": sp["t0_us"],
                           "id": _flow_id(tid)})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def report(prefix: Optional[str] = None, nonzero_only: bool = True) -> str:
    """One-call counter table (name, kind, value), grouped by top-level
    namespace — the human end of the registry."""
    snap = snapshot()
    kinds = registered()
    lines = [f"{'Counter':<52}{'Kind':>12}{'Value':>16}", "=" * 80]
    last_ns = None
    for name, val in snap.items():
        if prefix is not None and not name.startswith(prefix):
            continue
        if nonzero_only and not val:
            continue
        ns = name.split(".", 1)[0]
        if ns != last_ns:
            if last_ns is not None:
                lines.append("-" * 80)
            last_ns = ns
        kind = kinds.get(name, {}).get("kind", "?")
        if isinstance(val, float):
            lines.append(f"{name:<52}{kind:>12}{val:>16.3f}")
        else:
            lines.append(f"{name:<52}{kind:>12}{val!s:>16}")
    lines.append("=" * 80)
    lines.append(f"{len(snap)} declared counters; "
                 f"{len(events())} buffered events; "
                 f"{len(spans())} buffered spans")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# CLI: python -m mxnet_tpu.telemetry {report | trace <id> | merge <dir>}
# ---------------------------------------------------------------------------

def _merged_report(merged: Dict[str, Any],
                   prefix: Optional[str] = None) -> str:
    """The :func:`report` table rendered over a fleet merge."""
    lines = [f"{'Counter (fleet sum)':<52}{'Value':>16}", "=" * 68]
    for name, val in merged["counters"].items():
        if prefix is not None and not name.startswith(prefix):
            continue
        if not val:
            continue
        if isinstance(val, float):
            lines.append(f"{name:<52}{val:>16.3f}")
        else:
            lines.append(f"{name:<52}{val!s:>16}")
    lines.append("=" * 68)
    lines.append(f"{len(merged['shards'])} shard(s): "
                 f"{', '.join(merged['shards']) or '-'}; "
                 f"{len(merged['events'])} events, "
                 f"{len(merged['spans'])} spans"
                 + (f"; {merged['skipped_lines']} torn line(s) skipped"
                    if merged["skipped_lines"] else ""))
    return "\n".join(lines)


def _trace_from_merge(merged: Dict[str, Any],
                      trace_id: str) -> Dict[str, Any]:
    """:func:`trace`, but stitched from a shard merge instead of the
    in-process buffers (the on-box inspection path)."""
    evs = [e for e in merged["events"] if e.get("trace_id") == trace_id]
    sps = [s for s in merged["spans"]
           if s.get("trace_id") == trace_id or
           trace_id in ((s.get("args") or {}).get("trace_ids") or ())]
    records = [dict(e, type="event") for e in evs]
    records += [dict(s, type="span", t_us=s["t0_us"]) for s in sps]
    records.sort(key=lambda r: (r["t_us"], r.get("seq", 0)))
    return {"trace_id": trace_id, "events": evs, "spans": sps,
            "records": records}


def main(argv: Optional[List[str]] = None) -> int:
    """On-box inspection without writing a script (OBSERVABILITY.md):

    - ``report [--dir D] [--prefix P]`` — the counter table; with
      ``--dir`` the FLEET sum over that shard directory.
    - ``trace <id> [--dir D]`` — one request's stitched lifecycle
      (events + spans in order), from the in-process buffers or a
      shard directory.
    - ``merge <dir> [--json] [--chrome OUT]`` — fold shards into one
      snapshot; ``--json`` dumps the full merge, ``--chrome`` writes
      the per-process-lane chrome trace.
    """
    import argparse

    p = argparse.ArgumentParser(prog="python -m mxnet_tpu.telemetry",
                                description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser("report", help="counter table")
    rp.add_argument("--dir", default=None,
                    help="shard directory (default: this process)")
    rp.add_argument("--prefix", default=None)
    tp = sub.add_parser("trace", help="one request's stitched lifecycle")
    tp.add_argument("trace_id")
    tp.add_argument("--dir", default=None,
                    help="shard directory (default: in-process buffers)")
    mp = sub.add_parser("merge", help="fold shards into one snapshot")
    mp.add_argument("dir")
    mp.add_argument("--json", action="store_true",
                    help="dump the full merge as JSON")
    mp.add_argument("--chrome", default=None, metavar="OUT",
                    help="also write the merged chrome trace here")
    a = p.parse_args(argv)
    if a.cmd == "report":
        if a.dir:
            print(_merged_report(merge(a.dir), prefix=a.prefix))
        else:
            print(report(prefix=a.prefix))
        return 0
    if a.cmd == "trace":
        tr = (_trace_from_merge(merge(a.dir), a.trace_id) if a.dir
              else trace(a.trace_id))
        print(json.dumps(tr, indent=2, default=str))
        return 0 if tr["records"] else 1
    merged = merge(a.dir)
    if a.chrome:
        with open(a.chrome, "w") as f:
            json.dump(merge_chrome_trace(a.dir, merged), f)
    if a.json:
        print(json.dumps(merged, default=str))
    else:
        print(_merged_report(merged))
    return 0


if __name__ == "__main__":          # pragma: no cover - CLI entry
    import sys as _sys

    _sys.exit(main())
