"""Async pipeline engine.

The reference's scheduling heart is the threaded dependency engine
(``src/engine/threaded_engine.cc``): every op is pushed with read/write
var lists and IO prefetch, host<->device copies, compute, and checkpoint
writes all overlap.  On TPU, XLA's async dispatch stream already orders
*device* work — but PRs 1-4 shrank the device side to one donated
program per step, so the step gap is now pure HOST time: the blocking
``device_put`` per batch, the AMP all-finite host read, per-batch metric
scalar reads, and stop-the-world checkpoint snapshots.  This module owns
the host side of the pipeline:

- :class:`DevicePrefetcher` / :func:`prefetch` — a depth-k transfer
  stage: a thread stages batch N+1 (device_put, optional bucket padding)
  while step N runs, preserving order, retrying transient transfer
  faults under the ``engine.prefetch`` site.
- a **drainable registry** — deferred AMP flag reads
  (``cached_step.TrainStep``), device metric accumulators (``metric``),
  async checkpoint writers (``parallel.elastic.CheckpointManager``) and
  serving queues register themselves; :func:`waitall` drains them all
  before the XLA effects barrier, giving waitall the reference semantics
  ("block until every pushed async op completed") instead of being a
  device-only fence.
- :func:`bulk` — real bulking semantics under ``NaiveEngine``: inside a
  ``bulk(n)`` scope the per-op synchronous barrier fires every n ops
  instead of every op (the reference's op-bulking knob).

``MXNET_ENGINE_TYPE=NaiveEngine`` is the debug/parity escape hatch: it
forces prefetch depth 0, a synchronous AMP gate, host-side metric
accumulation, and synchronous checkpoint snapshots — fully synchronous
execution, mirroring the reference's NaiveEngine role.
"""
from __future__ import annotations

import contextlib
import queue as _queue
import threading
import weakref
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional

__all__ = ["bulk", "set_bulk_size", "waitall", "engine_type", "is_naive",
           "prefetch", "DevicePrefetcher", "prefetch_depth",
           "register_drainable", "drainable_count", "naive_sync"]

_bulk_size = 15  # reference default MXNET_ENGINE_BULK_SIZE-ish
_TL = threading.local()

# Everything with outstanding async host-side state registers here; an
# object only needs a .drain() method.  WeakSet: a dropped prefetcher /
# metric / checkpoint manager unregisters itself by dying.
_DRAINABLES: "weakref.WeakSet" = weakref.WeakSet()


def engine_type() -> str:
    """Engine selection (reference CreateEngine, src/engine/engine.cc:32,
    driven by MXNET_ENGINE_TYPE).  ThreadedEnginePerDevice = the async
    pipeline over XLA dispatch (default); NaiveEngine = synchronous eager
    dispatch for deterministic debugging, same role as the reference's
    NaiveEngine.  The knob is declared uncached so flipping it
    mid-process (its whole point when debugging) takes effect on the
    next op."""
    from . import config

    return config.get("MXNET_ENGINE_TYPE")


def is_naive() -> bool:
    """Hot-path check (called per eager op by ndarray.invoke).  Goes
    through the config registry like every other env read (graftlint
    env-discipline): the knob is declared uncached, so this is one
    registry hit + one environment read — flipping it mid-process (its
    debugging role) still takes effect on the next op."""
    from . import config

    return config.get("MXNET_ENGINE_TYPE") == "NaiveEngine"


def prefetch_depth() -> int:
    """Effective device-prefetch depth (MXNET_ENGINE_PREFETCH);
    NaiveEngine forces 0 — the fully synchronous escape hatch."""
    if is_naive():
        return 0
    from . import config

    return max(0, config.get("MXNET_ENGINE_PREFETCH"))


def amp_lag() -> int:
    """Effective deferred-AMP-gate lag window (MXNET_AMP_LAG, clamped to
    one unread flag); NaiveEngine forces 0 (synchronous gate)."""
    if is_naive():
        return 0
    from . import config

    return min(1, max(0, config.get("MXNET_AMP_LAG")))


# ---------------------------------------------------------------------------
# drainable registry + waitall
# ---------------------------------------------------------------------------

def register_drainable(obj):
    """Register an object carrying outstanding async host-side state
    (must expose ``.drain()``); :func:`waitall` drains every registered
    live object.  Weakly referenced — no unregister needed."""
    _DRAINABLES.add(obj)
    return obj


def drainable_count() -> int:
    """Live drainable registrations (exported as the computed telemetry
    gauge ``engine.drainables``)."""
    return len(_DRAINABLES)


def _register_drainables_gauge():
    from . import telemetry

    telemetry.gauge_fn(
        "engine.drainables", lambda: len(_DRAINABLES),
        "live drainable registrations (prefetchers, metric "
        "accumulators, checkpoint writers, serving queues)")


_register_drainables_gauge()


def waitall():
    """Block until ALL outstanding async work completes (reference
    MXEngineWaitAll): deferred AMP flag reads, device metric
    accumulators, prefetch transfers, queued checkpoint snapshots/writes,
    serving queues — then the XLA effects barrier.  Errors a drainable
    absorbed asynchronously (e.g. a failed background checkpoint)
    surface here, exactly like the reference engine re-raising a
    captured op exception at the wait point."""
    for obj in list(_DRAINABLES):
        drain = getattr(obj, "drain", None)
        if drain is not None:
            drain()
    from .ndarray import waitall as _w

    _w()
    # a drained process has no telemetry left in flight either: flush
    # the flight recorder (no-op unless MXNET_TELEMETRY_DIR is set)
    from . import telemetry

    try:
        telemetry.flush()
    except OSError:           # unwritable dir must not fail waitall
        pass


# ---------------------------------------------------------------------------
# bulk scope (real semantics under NaiveEngine)
# ---------------------------------------------------------------------------

def set_bulk_size(size: int) -> int:
    """Reference MXEngineSetBulkSize.  The async engine fuses via XLA
    anyway; under NaiveEngine the value is the per-op sync stride inside
    a bulk scope."""
    global _bulk_size
    prev = _bulk_size
    _bulk_size = int(size)
    return prev


@contextlib.contextmanager
def bulk(size: int):
    """Reference engine bulk scope.  Under the async engine this is
    advisory (XLA already bulks); under NaiveEngine ops inside the scope
    synchronize every ``size`` ops instead of every op, and the scope
    exit is a barrier."""
    prev = set_bulk_size(size)
    _TL.bulk_depth = getattr(_TL, "bulk_depth", 0) + 1
    try:
        yield
    finally:
        _TL.bulk_depth -= 1
        tail = getattr(_TL, "bulk_tail", None)
        _TL.bulk_tail = None
        _TL.bulk_pending = 0
        if tail is not None and is_naive():
            import jax

            # graftlint: disable=host-sync -- bulk-scope exit barrier under
            # NaiveEngine: synchronous execution is the escape hatch's job
            jax.block_until_ready(tail)
        set_bulk_size(prev)


def naive_sync(arrays) -> None:
    """NaiveEngine per-op barrier (called by ndarray.invoke after each
    eager dispatch): block so errors surface at the faulting op — except
    inside a :func:`bulk` scope, where the barrier fires every
    ``bulk_size`` ops (the scope exit still syncs the tail)."""
    import jax

    if getattr(_TL, "bulk_depth", 0) <= 0 or _bulk_size <= 1:
        # graftlint: disable=host-sync -- the NaiveEngine per-op barrier
        # IS the documented synchronous mode
        jax.block_until_ready(arrays)
        return
    _TL.bulk_pending = getattr(_TL, "bulk_pending", 0) + 1
    _TL.bulk_tail = arrays
    if _TL.bulk_pending >= _bulk_size:
        _TL.bulk_pending = 0
        _TL.bulk_tail = None
        # graftlint: disable=host-sync -- same barrier, bulk stride hit
        jax.block_until_ready(arrays)


# ---------------------------------------------------------------------------
# device prefetch stage
# ---------------------------------------------------------------------------

def _default_transfer(item):
    """Host batch -> device NDArrays (the DataLoader._wrap staging
    contract: one device_put per array leaf)."""
    from .ndarray import NDArray, array

    if isinstance(item, (tuple, list)):
        return type(item)(_default_transfer(x) for x in item)
    if isinstance(item, NDArray):
        return item
    return array(item)


def _bucket_pad(policy):
    """Bucket padding (PR 4's BucketPolicy grid) for host batches: the
    batch axis of every host leaf pads up to its bucket BEFORE the
    device_put, so a variable-length stream stages a bounded shape set
    (no retrace churn downstream)."""
    import numpy as onp

    def pad(x):
        if isinstance(x, (tuple, list)):
            return type(x)(pad(v) for v in x)
        # graftlint: disable=host-sync -- pads HOST batches before the
        # device_put; device arrays never reach this transfer stage
        arr = onp.asarray(x)
        if arr.ndim < 1:
            return arr
        b = policy.bucket(int(arr.shape[0]))
        if b is None or b == arr.shape[0]:
            return arr
        fill = onp.zeros((b - arr.shape[0],) + arr.shape[1:], arr.dtype)
        return onp.concatenate([arr, fill], axis=0)

    return pad


def _bucket_transfer(policy):
    pad = _bucket_pad(policy)

    def transfer(item):
        return _default_transfer(pad(item))

    return transfer


def _sharded_transfer(sharding, policy=None):
    """Device transfer that stages every batch leaf WITH the given batch
    ``NamedSharding`` (``cached_step.TrainStep.batch_sharding``): the
    prefetch thread's device_put already lands per-device shards on the
    SPMD mesh, so the compiled step pays no re-placement — and under
    multi-controller the host leaf is this process's shard of the global
    batch (``parallel.spmd.put_batch`` assembles the global array).
    Optional ``policy`` composes PR-4 bucket padding BEFORE the put."""
    from .context import current_context
    from .ndarray import NDArray
    from .ndarray.ndarray import _wrap
    from .parallel import spmd as _spmd

    mesh = sharding.mesh
    pad = _bucket_pad(policy) if policy is not None else (lambda x: x)

    def put(x):
        if isinstance(x, (tuple, list)):
            return type(x)(put(v) for v in x)
        if isinstance(x, NDArray):
            data = _spmd.put_batch(x._data, mesh)
            return x if data is x._data else _wrap(data, x.ctx, type(x))
        import numpy as onp

        # graftlint: disable=host-sync -- HOST batch leaf being staged
        return _wrap(_spmd.put_batch(onp.asarray(x), mesh),
                     current_context())

    def transfer(item):
        return put(pad(item))

    return transfer


class DevicePrefetcher:
    """Depth-k device prefetch: a transfer thread pulls items from
    ``source`` and stages them onto the device (``transfer``, default:
    the DataLoader ``_wrap`` device_put contract) into a bounded FIFO,
    so batch N+1's host->device copy overlaps step N's execution — the
    ThreadedEngine IO-prefetch stage.

    Ordering contract: one producer, one FIFO — items are delivered in
    source order, never reordered, dropped, or duplicated; a source
    exception is delivered in order, after every batch the source
    produced before it.  Transient transfer failures retry under the
    shared policy (site ``engine.prefetch``).

    ``stats()`` reports the staged count and the dispatch-ahead depth
    gauge (how many batches were already staged each time the consumer
    took one) — ``steady_ahead`` is the benchmark's headline pipeline
    metric.
    """

    def __init__(self, source: Iterable, depth: Optional[int] = None,
                 transfer: Optional[Callable] = None,
                 name: str = "prefetch"):
        self._source = iter(source)
        self._transfer = transfer or _default_transfer
        self._depth = prefetch_depth() if depth is None \
            else max(1, int(depth))
        if self._depth < 1:
            self._depth = 1
        self._q: "_queue.Queue" = _queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._idle = threading.Event()  # no transfer in flight
        self._idle.set()
        self._staged = 0
        self._ahead_samples: List[int] = []
        self._done = False
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"mxnet-{name}")
        self._thread.start()
        register_drainable(self)

    # -- producer --------------------------------------------------------
    def _put(self, item) -> None:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return
            except _queue.Full:
                continue

    def _run(self):
        from . import faults as _faults
        from . import preemption as _preemption

        try:
            while not self._stop.is_set():
                if _preemption.draining():
                    # preemption drain: stop pulling/staging NEW batches
                    # (already-staged ones stay deliverable); the
                    # consumer sees a normal end-of-stream at the next
                    # take, so the train loop winds down cleanly
                    self._put(("end", None))
                    return
                try:
                    item = next(self._source)
                except StopIteration:
                    self._put(("end", None))
                    return
                self._idle.clear()
                try:
                    # transfer is pure (same host batch -> same device
                    # payload), so a transient device_put hiccup retries
                    out = _faults.retry_call(self._transfer, item,
                                             site="engine.prefetch")
                finally:
                    self._idle.set()
                self._staged += 1
                self._put(("ok", out))
        except BaseException as e:   # delivered in order, then stop
            self._put(("error", e))
        finally:
            self._idle.set()

    # -- consumer --------------------------------------------------------
    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        ahead = self._q.qsize()
        kind, val = self._q.get()
        if kind == "end":
            self._done = True
            raise StopIteration
        if kind == "error":
            self._done = True
            raise val
        # only takes that yielded a batch count toward the gauge (the
        # terminal end/error take is not a consume)
        self._ahead_samples.append(ahead)
        return val

    # -- lifecycle / introspection --------------------------------------
    def drain(self, timeout: float = 60.0) -> None:
        """Block until the in-flight transfer (if any) has been staged —
        after drain() the device holds every batch the transfer thread
        pulled from the source."""
        self._idle.wait(timeout)

    def close(self) -> None:
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except _queue.Empty:
            pass
        self._thread.join(timeout=5.0)
        self._done = True

    def stats(self) -> Dict[str, Any]:
        samples = self._ahead_samples
        # the first take races thread start-up; steady state is the rest
        steady = sorted(samples[1:]) if len(samples) > 1 else sorted(samples)
        return {
            "depth": self._depth,
            "staged": self._staged,
            "consumed": len(samples),
            "max_ahead": max(samples, default=0),
            "steady_ahead": steady[len(steady) // 2] if steady else 0,
        }


def prefetch(source: Iterable, depth: Optional[int] = None,
             transfer: Optional[Callable] = None, bucket: bool = False,
             sharding=None):
    """Wrap an iterable of host batches in a :class:`DevicePrefetcher`.

    ``depth`` defaults to ``MXNET_ENGINE_PREFETCH``; depth 0 (or
    ``MXNET_ENGINE_TYPE=NaiveEngine``) returns a synchronous generator
    applying the same transfer inline — the escape hatch keeps the
    call-site code identical.  ``bucket=True`` pads each batch's leading
    axis up to the ``MXNET_SHAPE_BUCKETS`` grid before the device_put
    (reusing PR 4's BucketPolicy) so variable-length streams stage a
    bounded shape set.  ``sharding`` (a batch ``NamedSharding``, e.g.
    ``TrainStep.batch_sharding``) stages every leaf onto the SPMD mesh
    — batch axis sharded over ``'dp'``, per-process shard of the global
    batch under multi-controller — so sharded steps consume prefetched
    batches without a re-placement copy."""
    policy = None
    if bucket:
        from . import serving as _serving

        p = _serving.BucketPolicy()
        if p.enabled:
            policy = p
    if transfer is None:
        if sharding is not None:
            transfer = _sharded_transfer(sharding, policy)
        elif policy is not None:
            transfer = _bucket_transfer(policy)
    eff_depth = prefetch_depth() if depth is None else max(0, int(depth))
    if is_naive():
        eff_depth = 0
    fn = transfer or _default_transfer
    if eff_depth < 1:
        return (fn(item) for item in source)
    return DevicePrefetcher(source, depth=eff_depth, transfer=fn)
