"""Engine facade.

The reference's threaded dependency engine (``src/engine/threaded_engine.cc``)
scheduled every op asynchronously with read/write var tracking.  On TPU,
XLA's async dispatch stream *is* the engine: ops return before execution and
data dependencies order work on-device.  This module keeps the user-facing
engine API (bulk scope, waitall) as thin shims.
"""
from __future__ import annotations

import contextlib

__all__ = ["bulk", "set_bulk_size", "waitall", "engine_type", "is_naive"]

_bulk_size = 15  # reference default MXNET_ENGINE_BULK_SIZE-ish; advisory only


def engine_type() -> str:
    """Engine selection (reference CreateEngine, src/engine/engine.cc:32,
    driven by MXNET_ENGINE_TYPE).  ThreadedEnginePerDevice = XLA async
    dispatch (default); NaiveEngine = synchronous eager dispatch for
    deterministic debugging, same role as the reference's NaiveEngine.
    The knob is declared uncached so flipping it mid-process (its whole
    point when debugging) takes effect on the next op."""
    from . import config

    return config.get("MXNET_ENGINE_TYPE")


def is_naive() -> bool:
    """Hot-path check (called per eager op by ndarray.invoke): one dict
    lookup against the raw environment, skipping the registry layers.
    engine_type() remains the validated/documented read."""
    import os

    return os.environ.get("MXNET_ENGINE_TYPE") == "NaiveEngine"


def set_bulk_size(size: int) -> int:
    """Reference MXEngineSetBulkSize.  XLA fuses automatically; the value is
    stored only for API parity."""
    global _bulk_size
    prev = _bulk_size
    _bulk_size = size
    return prev


@contextlib.contextmanager
def bulk(size: int):
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)


def waitall():
    from .ndarray import waitall as _w

    _w()
